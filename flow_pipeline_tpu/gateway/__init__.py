"""flowgate: a replicated, delta-fed serve gateway.

The reference pipeline's read surface is Grafana hitting ClickHouse — a
dedicated read tier decoupled from ingest. flowserve (r14) still serves
every snapshot from the dataplane's own cores: on the 2-core bench box
readers and the worker time-slice the same CPUs
(reader_contention_pct 56, p99 70ms vs p50 3.3ms). flowgate moves the
read tier OFF the dataplane by construction:

- the publisher side (worker or mesh coordinator) grows a
  **subscription feed** (:mod:`.feed`): between versions it ships
  **deltas** — only changed top-K rows, dirty CMS plane tiles and new
  range slots travel (:mod:`.delta`); a version gap or CRC mismatch
  falls back to a full-snapshot resync;
- each **gateway replica** (:mod:`.subscriber`) mirrors the upstream's
  versioned snapshot stream into its OWN :class:`~..serve.SnapshotStore`
  and serves it through the unchanged ``ServeServer`` — so every
  ``/query/*`` answer is bit-exact against the direct snapshot path at
  the same version *by construction* (same immutable arrays, same
  handler code);
- **K stateless replicas** sit behind client-side consistent hashing
  over the query key (:mod:`.ring`): reads scale horizontally, and a
  replica kill is invisible — the client re-rings onto the survivors;
- **tail latency**: the hot query set (top-K at default k per family)
  is pre-rendered into the response cache the moment a snapshot lands,
  so the p99 path is one dict lookup + one ``sendall``.

The mergeability that makes the tier cheap is the same linearity story
as the mesh (PAPERS.md 1910.10441 / 1902.06993): every family's
snapshot is a monoid fold, so the coordinator's published snapshot IS
the network-wide merged view, and a gateway holding that immutable
object can answer for the whole mesh.
"""

from .delta import (DeltaError, DeltaGapError, apply_delta, decode_frames,
                    diff_states, encode_delta, encode_full, snapshot_state,
                    state_to_snapshot)
from .feed import SnapshotFeed
from .ring import GatewayClient, HashRing
from .subscriber import GATEWAY_METRICS, SnapshotGateway

__all__ = [
    "DeltaError",
    "DeltaGapError",
    "GATEWAY_METRICS",
    "GatewayClient",
    "HashRing",
    "SnapshotFeed",
    "SnapshotGateway",
    "apply_delta",
    "decode_frames",
    "diff_states",
    "encode_delta",
    "encode_full",
    "snapshot_state",
    "state_to_snapshot",
]
