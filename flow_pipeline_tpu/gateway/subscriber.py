"""flowgate subscriber: a gateway replica mirroring upstream snapshots.

A :class:`SnapshotGateway` subscribes to one or more upstream snapshot
streams (a worker's or the mesh coordinator's flowserve surface — over
HTTP via ``/sub/snapshot``, or in-process via a store/feed object for
tests and embedded wiring) and reconstructs each stream into its OWN
:class:`~..serve.SnapshotStore`. The serving story is deliberately
boring: the gateway's ``ServeServer`` runs the UNCHANGED handler code
over the reconstructed immutable snapshot, and the reconstruction
carries the upstream's arrays bit-identically (gateway/delta.py), so
every ``/query/*`` answer equals the direct snapshot path's at the same
version by construction — the parity suite pins it anyway.

Mirroring rules:

- polls carry ``since=<local version>``; the upstream feed answers
  "none" (current), a delta chain, or a full snapshot;
- a delta gap, CRC failure, or any apply error drops local delta state
  and re-polls with ``since=0`` — a FULL resync
  (``gateway_resyncs_total`` by reason). Resync is the bootstrap path:
  there is no partial-repair mode to get wrong;
- versions are MONOTONE through anything: ``publish_snapshot`` refuses
  to move the store backwards, so a flapping upstream or a replayed
  response can never un-publish;
- the moment a snapshot lands, the hot query set (top-K at default k,
  per family and the bare default) is PRE-RENDERED into the serve
  response cache (``ServeServer.warm``): the p99 path for those
  queries is one dict lookup + one ``sendall``, paid at publish time
  on the subscriber thread — never by a reader.

The first upstream is the PRIMARY: its store is what the gateway's
serve surface answers from. Additional upstreams mirror into their own
stores (``gateway.stores``) for embedders that want several streams
held by one process.
"""

from __future__ import annotations

# flowlint: lock-checked
# (each upstream's mirror state is touched only by its own poll thread
# — or by sync_once callers in tests, never both; the stores carry
# their own RCU contract; metrics are the registry's thread-safe types)
# flowlint: net-checked
# (subscription polls carry explicit timeouts: a wedged upstream must
# cost one bounded fetch per cadence, not a hung mirror thread)

import http.client
import threading
import time
from typing import Optional

from ..obs import REGISTRY, get_logger
from ..serve.snapshot import SnapshotStore
from ..utils.faults import FAULTS
from .delta import (DeltaError, DeltaGapError, apply_delta, decode_frames,
                    state_to_snapshot)
from .feed import SnapshotFeed

log = get_logger("gateway")

# Metric name/help specs live here once; the deploy honesty test
# resolves the Grafana gateway panels against a constructed gateway.
GATEWAY_METRICS = {
    "syncs": ("gateway_syncs_total",
              "flowgate subscription polls answered (label: "
              "kind=full|delta|none)"),
    "sync_bytes": ("gateway_sync_bytes_total",
                   "flowgate bytes shipped by the subscription feed "
                   "(label: kind=full|delta) — delta/full is the "
                   "fan-in-cost ratio"),
    "resyncs": ("gateway_resyncs_total",
                "flowgate full-snapshot resyncs forced by a delta "
                "chain break (label: reason=gap|crc|error)"),
    "upstream_restarts": ("gateway_upstream_restarts_total",
                          "flowgate polls whose reconstructed snapshot "
                          "was refused for being at or behind the "
                          "served mirror (label: upstream) — an "
                          "upstream RESTART republishing from a fresh "
                          "store; the stateless replica keeps serving "
                          "its pre-restart snapshot (restart it to "
                          "adopt the new stream)"),
    "poll_failures": ("gateway_poll_failures_total",
                      "flowgate subscription polls that failed in "
                      "transport (upstream down/unreachable) — the "
                      "mirror keeps serving its last snapshot"),
    "upstream_version": ("gateway_upstream_version",
                         "newest version the upstream feed advertised "
                         "(label: upstream) — minus "
                         "serve_snapshot_version = mirror lag"),
    "prerendered": ("gateway_prerendered_total",
                    "hot-query responses pre-rendered into the serve "
                    "cache at snapshot-landing time"),
    "upstreams": ("gateway_upstreams",
                  "configured upstream subscriptions"),
}


class _Upstream:
    """One subscription: transport + mirror state + local store."""

    def __init__(self, target, name: str, timeout: float):
        self.name = name
        self.timeout = timeout
        self._http: Optional[tuple[str, int]] = None
        self._feed: Optional[SnapshotFeed] = None
        if isinstance(target, str):
            host, _, port = target.rpartition(":")
            self._http = (host or "127.0.0.1", int(port))
        elif isinstance(target, SnapshotFeed):
            self._feed = target
        elif isinstance(target, SnapshotStore):
            self._feed = SnapshotFeed(target)
        else:
            raise TypeError(
                f"upstream must be 'host:port', a SnapshotStore or a "
                f"SnapshotFeed, got {type(target).__name__}")
        self.store = SnapshotStore()
        # flowlint: unguarded -- touched only by this upstream's own poll thread (or sync_once test callers, never both)
        self.state: Optional[dict] = None  # canonical mirror state
        # flowlint: unguarded -- same single-thread ownership as state
        self.conn: Optional[http.client.HTTPConnection] = None

    @property
    def version(self) -> int:
        return 0 if self.state is None else int(self.state["version"])

    def fetch(self, since: int) -> bytes:
        if self._feed is not None:
            return self._feed.frame_since(since)[2]
        if self.conn is None:
            self.conn = http.client.HTTPConnection(
                *self._http, timeout=self.timeout)
        try:
            # ETag-conditional poll (r19): the server tags every
            # subscription answer with the CURRENT feed version, so a
            # mirror that is already at `since` revalidates instead of
            # re-downloading the "none" frame — a quiet upstream costs
            # headers, not bytes. 304 means exactly what an empty frame
            # list means to _apply: nothing new.
            self.conn.request("GET", f"/sub/snapshot?since={since}",
                              headers={"If-None-Match": f'"sub-v{since}"'})
            resp = self.conn.getresponse()
            body = resp.read()
        except (OSError, http.client.HTTPException) as e:
            conn, self.conn = self.conn, None
            if conn is not None:
                conn.close()
            if isinstance(e, OSError):
                raise
            # an upstream dying MID-RESPONSE surfaces as
            # IncompleteRead/BadStatusLine — HTTPException, NOT an
            # OSError (the r17 member-transport lesson): normalize so
            # the poll loop's outage handling covers it instead of the
            # exception killing the mirror thread
            raise ConnectionError(
                f"upstream {self.name} died mid-response: {e!r}") from e
        if resp.status == 304:
            return b""  # already current: zero frames -> kind "none"
        if resp.status != 200:
            raise OSError(f"upstream {self.name} answered "
                          f"{resp.status} for /sub/snapshot")
        return body


class SnapshotGateway:
    """K-replica read tier, one instance: mirror upstream snapshot
    streams and serve the primary through a local store."""

    def __init__(self, upstreams, poll: float = 0.25,
                 timeout: float = 10.0, prerender: bool = True,
                 adopt_restart: bool = False, archive=None):
        if not upstreams:
            raise ValueError("at least one upstream is required")
        # -history.dir: a flowhistory ArchiveWriter riding the PRIMARY
        # mirror thread — every applied transition is archived, so the
        # replica's /query/range reaches past upstream RANGE_SLOTS and
        # ?at=/?version= time travel answers from the same process
        # flowlint: unguarded -- bound once at construction
        self.archive = archive
        # -gateway.adopt-restart: swap to an upstream's post-restart
        # stream automatically (availability) instead of holding the
        # pre-restart snapshot until an operator restarts this replica
        # (monotone reads — the default)
        self.adopt_restart = adopt_restart
        self.upstreams = [
            up if isinstance(up, _Upstream)
            else _Upstream(up, name=(up if isinstance(up, str)
                                     else f"inproc-{i}"), timeout=timeout)
            for i, up in enumerate(upstreams)]
        self.poll = poll
        self.prerender = prerender
        self.store = self.upstreams[0].store  # the PRIMARY serving store
        self.stores = {u.name: u.store for u in self.upstreams}
        # the serve surface to pre-render into; wired by serve_on() (the
        # server needs the store, which needs this object — two-phase)
        # flowlint: unguarded -- bound once at wiring, before start()
        self.server = None
        self._stop = threading.Event()  # flowlint: unguarded -- bound once
        # flowlint: unguarded -- bound once at start()
        self._threads: list[threading.Thread] = []
        self._m = {k: (REGISTRY.gauge(*v)
                       if k in ("upstream_version", "upstreams")
                       else REGISTRY.counter(*v))
                   for k, v in GATEWAY_METRICS.items()}
        self._m["upstreams"].set(len(self.upstreams))

    # ---- wiring ------------------------------------------------------------

    def serve_on(self, server) -> "SnapshotGateway":
        """Attach the ServeServer built over ``self.store`` so landing
        snapshots pre-render the hot query set into its cache."""
        self.server = server
        return self

    # ---- one mirror step (tests drive this deterministically) --------------

    def sync_once(self, index: int = 0) -> str:
        """One poll+apply for one upstream. Returns the sync kind
        ("none" | "delta" | "full" | "resync" | "error")."""
        up = self.upstreams[index]
        if FAULTS.active:  # flowchaos seam: a failed/injected poll —
            # the mirror keeps serving its previous snapshot
            FAULTS.check("gateway.poll")
        data = up.fetch(up.version)
        try:
            return self._apply(up, data)
        except DeltaGapError as e:
            return self._schedule_resync(up, "gap", e)
        except DeltaError as e:
            return self._schedule_resync(up, "crc", e)
        except (KeyError, ValueError, TypeError) as e:
            # a malformed tree from a version-skewed upstream: same
            # answer as damage — drop local state, take a full snapshot
            return self._schedule_resync(up, "error", e)

    def _schedule_resync(self, up: _Upstream, reason: str,
                         err: Exception) -> str:
        self._m["resyncs"].inc(reason=reason)
        log.warning("gateway upstream %s: %s (%s); full resync",
                    up.name, reason, err)
        up.state = None  # since=0 on the next poll -> full frame
        return "resync"

    def _apply(self, up: _Upstream, data: bytes) -> str:
        kind = "none"
        for tree in decode_frames(data):
            t = tree["t"]
            if t == "none":
                self._m["upstream_version"].set(int(tree["to"]),
                                                upstream=up.name)
                continue
            if t == "full":
                # chain continuity across a full resync is unknown, so
                # the archive (if any) anchors a fresh keyframe
                prev, up.state = None, tree["state"]
                kind = "full"
            elif t == "delta":
                if up.state is None:
                    raise DeltaGapError("delta frame with no local base")
                prev = up.state
                up.state = apply_delta(up.state, tree)
                if kind != "full":
                    kind = "delta"
            else:
                raise DeltaError(f"unknown frame kind {t!r}")
            if self.archive is not None and up is self.upstreams[0]:
                # archive the PRIMARY stream's transition before the
                # publish: the archived chain is exactly what the serve
                # surface answers from (record-and-replay parity)
                self.archive.record(prev, up.state)
            self._m["upstream_version"].set(up.version, upstream=up.name)
        self._m["syncs"].inc(kind=kind)
        if kind != "none" and self.archive is not None:
            self.archive.commit()  # group commit per poll, not per frame
        if kind != "none":
            self._m["sync_bytes"].inc(len(data), kind=kind)
            snap = up.store.publish_snapshot(state_to_snapshot(up.state))
            if snap is None:
                # the store refused: reconstructed version <= served
                # version. Deltas only move forward, so this is an
                # upstream RESTART (a fresh process republishing from
                # v1) — a new world, not a stale replay. The replica
                # stays monotone by keeping its pre-restart snapshot;
                # adopting the new stream is an operator action
                # (replicas are stateless — restart them), and this
                # counter is what pages it. It keeps incrementing
                # while the wedge persists, so increase() alerts see a
                # live signal, but the log warns only at the full-frame
                # restart moment, not every refused delta.
                self._m["upstream_restarts"].inc(upstream=up.name)
                if kind == "full" and self.adopt_restart:
                    # -gateway.adopt-restart: the operator chose
                    # availability — adopt the post-restart world now.
                    # Only on a FULL frame (a self-consistent snapshot;
                    # a refused delta still means our base is gone) and
                    # still counted above: adoption is never silent.
                    snap = up.store.adopt_snapshot(
                        state_to_snapshot(up.state))
                    if up is self.upstreams[0] and \
                            self.server is not None:
                        # the adopted world's version counter restarts:
                        # a new-world version can collide with an
                        # old-world cached response — drop them all
                        self.server.invalidate_cache()
                    log.warning(
                        "gateway upstream %s republished v%d at or "
                        "behind the served version — upstream restart "
                        "ADOPTED (-gateway.adopt-restart): serving the "
                        "new stream from v%d", up.name, up.version,
                        snap.version)
                    if up is self.upstreams[0] and \
                            self.server is not None and self.prerender:
                        self._m["prerendered"].inc(
                            self.server.warm(self._hot_targets(snap)))
                elif kind == "full":
                    log.warning(
                        "gateway upstream %s republished v%d at or "
                        "behind served v%d — upstream restart; replica "
                        "keeps serving its pre-restart snapshot "
                        "(restart this replica to adopt the new "
                        "stream)", up.name, up.version,
                        up.store.current.version)
            elif up is self.upstreams[0] and \
                    self.server is not None and self.prerender:
                self._m["prerendered"].inc(
                    self.server.warm(self._hot_targets(snap)))
        return kind

    @staticmethod
    def _hot_targets(snap) -> list[str]:
        """The queries every dashboard issues the moment a version
        lands: top-K at the published depth's default slice, bare and
        per model. Known at publish time — rendering them NOW is what
        moves them off the p99 path."""
        return ["/query/topk"] + [f"/query/topk?model={name}"
                                  for name in snap.families]

    # ---- mirror threads ----------------------------------------------------

    def start(self) -> "SnapshotGateway":
        self._threads = [
            threading.Thread(target=self._run, args=(i,),
                             name=f"gateway-sub-{u.name}", daemon=True)
            for i, u in enumerate(self.upstreams)]
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    def _run(self, index: int) -> None:
        up = self.upstreams[index]
        while not self._stop.is_set():
            try:
                self.sync_once(index)
            except OSError as e:
                # upstream down (or an injected gateway.poll fault):
                # count it and keep serving the mirrored snapshot —
                # staleness is visible (gateway_upstream_version stops
                # advancing), availability is not traded for it
                self._m["poll_failures"].inc()
                log.debug("gateway upstream %s poll failed: %s",
                          up.name, e)
            self._stop.wait(self.poll)
