"""flowgate snapshot delta codec.

A published :class:`~..serve.snapshot.Snapshot` is megabytes (the CMS
planes dominate), but consecutive versions are append-mostly: between
window closes only the open window's touched buckets and the freshest
top-K rows move. Shipping the whole snapshot at the ``-serve.refresh``
cadence would make gateway fan-in cost O(snapshot) per publish; this
codec makes it O(change):

- :func:`snapshot_state` lowers a snapshot to its **canonical state
  dict** — plain numpy arrays only (top-K row columns, the frozen
  uint64 CMS planes, range-slot row sets) plus JSON-safe metadata. The
  state dict is the unit of comparison AND of reconstruction:
  :func:`state_to_snapshot` rebuilds an immutable ``Snapshot`` whose
  arrays are bit-identical to the source's, which is what makes every
  gateway-served answer exact by construction.
- :func:`diff_states` emits a **delta**: per family the scalar metadata
  (tiny, always shipped), the ranked rows only when any column changed,
  and the CMS per depth row as either a **sparse dirty-column patch**
  (changed column indices + their values across all planes — hashed
  updates spread uniformly, so this is the append-mostly coding) or
  **dirty tiles** (``TILE_W``-wide column slabs, the dense-row
  fallback); comparison is uint64 equality — exact, no tolerance.
  A spread family's u8 register planes ride the SAME dirty-column
  coding (registers-last ``[D, W, m]`` viewed planes-first ``[m, D,
  W]`` — a bucket's m registers dirty together the way a CMS bucket's
  planes do), byte-equality compared.
  Range tables ship the authoritative slot list plus the row sets of
  new or changed slots; everything else is copied forward by reference
  on apply.
- Frames are ``FGWD1`` + ``u32 len | u32 crc32`` around a
  mesh-codec body (the same no-pickle JSON-tree + npz split the mesh
  envelope uses — dtype/shape/word exact on the uint64 envelope). A
  torn or corrupted frame raises :class:`DeltaError`; an out-of-order
  delta raises :class:`DeltaGapError`. Both are the subscriber's cue to
  fall back to a full-snapshot resync — never to guess.
"""

from __future__ import annotations

import struct
import zlib
from typing import Iterator, Optional

import numpy as np

from ..families import registry
from ..mesh import codec
from ..serve.snapshot import FamilyView, FrozenCms, Snapshot

MAGIC = b"FGWD1\n"
_HEAD = struct.Struct("<II")  # body_len, crc32(body)

# CMS dirty-tile width (uint64 words along the last plane axis), the
# DENSE-row coding: when most of a depth row changed, whole column
# slabs ship with one coordinate per TILE_W words.
TILE_W = 256

# Sparse-row threshold: hashed updates spread UNIFORMLY over a depth
# row, so even a few thousand touched buckets dirty almost every tile
# — tile granularity alone cannot expose append-mostly sparsity
# (measured: a 4096-flow trickle shipped ~full-size deltas). A row
# whose changed-column fraction is below this ships as (column
# indices, column values) instead; per changed column that costs
# 8 bytes of index + (P+1)*8 bytes of values, which beats the full
# row slab up to ~70% density for the 3-plane default.
SPARSE_FRAC = 0.5


class DeltaError(ValueError):
    """A torn, truncated, or CRC-failing frame — resync, don't guess."""


class DeltaGapError(DeltaError):
    """A delta whose ``from`` version does not match the local state —
    the chain has a hole (missed publish, evicted history); resync."""


# ---- snapshot <-> canonical state ------------------------------------------


def snapshot_state(snap: Snapshot) -> dict:
    """Lower one immutable snapshot to the canonical state dict. CMS
    planes are materialized here (``FrozenCms.get`` — the lazy f32→u64
    freeze runs on the CALLER's thread: the feed/reader side, never the
    dataplane, the same discipline as a first estimate reader)."""
    families = {}
    for name, f in snap.families.items():
        families[name] = {
            "kind": f.kind,
            "window_start": (None if f.window_start is None
                             else int(f.window_start)),
            "depth": int(f.depth),
            "key_lanes": int(f.key_lanes),
            "value_cols": list(f.value_cols),
            "rows": {c: np.asarray(v) for c, v in f.rows.items()},
            "cms": None if f.cms is None else np.asarray(f.cms.get()),
            "regs": None if f.regs is None else np.asarray(f.regs),
        }
    ranges = {
        table: [[int(slot), {c: np.asarray(v) for c, v in rows.items()}]
                for slot, rows in slots]
        for table, slots in snap.ranges.items()
    }
    return {
        "version": int(snap.version),
        "created": float(snap.created),
        "watermark": float(snap.watermark),
        "flows_seen": (None if snap.flows_seen is None
                       else int(snap.flows_seen)),
        "source": snap.source,
        "families": families,
        "ranges": ranges,
        "audit": dict(snap.audit),
    }


def state_to_snapshot(state: dict) -> Snapshot:
    """Rebuild the immutable read view from a canonical state dict.
    Arrays are used as-is (never copied): the reconstructed snapshot's
    answers are bit-identical to the source's because they ARE the same
    words."""
    families = {}
    for name, f in state["families"].items():
        cms = f["cms"]
        families[name] = FamilyView(
            name=name, kind=f["kind"], window_start=f["window_start"],
            depth=int(f["depth"]), rows=dict(f["rows"]),
            key_lanes=int(f["key_lanes"]),
            cms=None if cms is None else FrozenCms(value=cms),
            value_cols=tuple(f["value_cols"]),
            regs=f.get("regs"))
    ranges = {table: tuple((int(slot), dict(rows))
                           for slot, rows in slots)
              for table, slots in state["ranges"].items()}
    return Snapshot(
        version=int(state["version"]), created=float(state["created"]),
        watermark=float(state["watermark"]),
        flows_seen=state["flows_seen"], source=state["source"],
        families=families, ranges=ranges, audit=dict(state["audit"]))


# ---- diff / apply ----------------------------------------------------------


def _arrays_equal(a, b) -> bool:
    a, b = np.asarray(a), np.asarray(b)
    return a.dtype == b.dtype and a.shape == b.shape and \
        bool(np.array_equal(a, b))


def _rows_equal(a: dict, b: dict) -> bool:
    if set(a) != set(b):
        return False
    return all(_arrays_equal(a[c], b[c]) for c in a)


# Every plane layout the canonical state schema can carry: (state key,
# planes-first?). Registered families narrow this via delta_planes.
_ALL_PLANES = (("cms", False), ("regs", True))


def _plane_specs(kind: str) -> tuple:
    specs = registry.delta_planes(kind)
    if specs or registry.family_for_payload(kind) is not None:
        return specs
    # unregistered kind: diff every known plane layout — the gateway
    # must never guess a narrower schema for a family this build does
    # not know about
    return _ALL_PLANES


def _cms_diff(prev: np.ndarray,
              cur: np.ndarray) -> Optional[tuple[list, list]]:
    """Per-depth-row dirty coding: (sparse, tiles), or None when the
    shapes/dtypes force a full-plane ship. A mostly-clean row ships
    sparse ``[d, cols, vals]`` (``vals = cur[:, d, cols]`` — the
    column slice across every plane: a bucket's counts and sums dirty
    in lockstep); a dense row falls back to ``[d, w0, block]``
    TILE_W-wide slabs."""
    if prev.shape != cur.shape or prev.dtype != cur.dtype:
        return None
    sparse: list = []
    tiles: list = []
    depth, width = cur.shape[1], cur.shape[2]
    for d in range(depth):
        changed = (prev[:, d, :] != cur[:, d, :]).any(axis=0)
        cols = np.flatnonzero(changed)
        if cols.size == 0:
            continue
        if cols.size <= SPARSE_FRAC * width:
            sparse.append([int(d), cols.astype(np.int64),
                           np.ascontiguousarray(cur[:, d, cols])])
            continue
        for w0 in range(0, width, TILE_W):
            if changed[w0:w0 + TILE_W].any():
                tiles.append([int(d), int(w0), np.ascontiguousarray(
                    cur[:, d, w0:w0 + TILE_W])])
    return sparse, tiles


def diff_states(prev: dict, cur: dict) -> dict:
    """The delta tree from ``prev`` to ``cur`` (both canonical state
    dicts). The family and range-table maps in the delta are COMPLETE
    (their scalar metadata is tiny and carrying the full key set lets
    apply drop removed entries without a tombstone protocol); the
    arrays inside ship only where they changed.

    Which plane arrays a family diffs — and whether a plane is viewed
    planes-first (spread's registers-last ``[D, W, m]`` becomes ``[m,
    D, W]``: a bucket's m registers dirty together the way a CMS
    bucket's planes do) — comes from the family registry's
    ``delta_planes`` spec; unregistered kinds diff every known plane
    layout (never guess a narrower schema)."""
    families = {}
    for name, f in cur["families"].items():
        pf = prev["families"].get(name)
        entry = {
            "kind": f["kind"], "window_start": f["window_start"],
            "depth": f["depth"], "key_lanes": f["key_lanes"],
            "value_cols": list(f["value_cols"]),
        }
        if pf is None or not _rows_equal(pf["rows"], f["rows"]):
            entry["rows"] = f["rows"]
        for key, planes_first in _plane_specs(f["kind"]):
            val = f.get(key)
            pval = None if pf is None else pf.get(key)
            if val is None:
                if pf is None or pval is not None:
                    entry[key] = None
            elif pval is None:
                entry[key] = val
            else:
                diff = _cms_diff(
                    np.moveaxis(pval, 2, 0) if planes_first else pval,
                    np.moveaxis(val, 2, 0) if planes_first else val)
                if diff is None:
                    entry[key] = val
                else:
                    sparse, tiles = diff
                    if sparse:
                        entry[f"{key}_sparse"] = sparse
                    if tiles:
                        entry[f"{key}_tiles"] = tiles
                    # neither: apply carries the base plane forward
        families[name] = entry
    ranges = {}
    for table, slots in cur["ranges"].items():
        pslots = dict((int(s), rows)
                      for s, rows in prev["ranges"].get(table, []))
        chunks = {}
        for slot, rows in slots:
            old = pslots.get(int(slot))
            if old is None or not _rows_equal(old, rows):
                chunks[int(slot)] = rows
        ranges[table] = {"slots": [int(s) for s, _ in slots],
                         "chunks": chunks}
    delta = {
        "from": int(prev["version"]), "to": int(cur["version"]),
        "created": cur["created"], "watermark": cur["watermark"],
        "flows_seen": cur["flows_seen"], "source": cur["source"],
        "families": families, "ranges": ranges,
    }
    if cur["audit"] != prev["audit"]:
        delta["audit"] = cur["audit"]
    return delta


def apply_delta(prev: dict, delta: dict) -> dict:
    """``prev`` + one delta tree -> the next canonical state dict.
    Unchanged arrays are carried forward BY REFERENCE (states are
    immutable once built — the same RCU discipline as the snapshots
    they reconstruct). Raises :class:`DeltaGapError` on a chain hole."""
    if int(delta["from"]) != int(prev["version"]):
        raise DeltaGapError(
            f"delta chains from v{delta['from']} but local state is "
            f"v{prev['version']}")
    families = {}
    for name, entry in delta["families"].items():
        pf = prev["families"].get(name)
        rows = entry.get("rows")
        if rows is None:
            if pf is None:
                raise DeltaError(
                    f"delta introduces family {name!r} without rows")
            rows = pf["rows"]
        spec_keys = {k for k, _ in _plane_specs(entry["kind"])}
        planes = {}
        for key, planes_first in _ALL_PLANES:
            if key in entry:
                planes[key] = entry[key]
            elif f"{key}_tiles" in entry or f"{key}_sparse" in entry:
                base = None if pf is None else pf.get(key)
                if base is None:
                    raise DeltaError(
                        f"delta patches {key} planes for {name!r} with "
                        "no base planes")
                arr = base.copy()
                # patch through the planes-first view where the family
                # codes that way — the same words, addressed the way
                # _cms_diff coded them
                view = np.moveaxis(arr, 2, 0) if planes_first else arr
                for d, w0, block in entry.get(f"{key}_tiles", ()):
                    d, w0 = int(d), int(w0)
                    view[:, d, w0:w0 + block.shape[-1]] = block
                for d, cols, vals in entry.get(f"{key}_sparse", ()):
                    view[:, int(d), np.asarray(cols, np.int64)] = vals
                planes[key] = arr
            elif key in spec_keys:
                # unshipped + undiffed but diffable for this kind:
                # carried forward BY REFERENCE (states are immutable)
                planes[key] = None if pf is None else pf.get(key)
            else:
                # a plane this kind never carries (or a kind change):
                # never inherit another layout's words
                planes[key] = None
        families[name] = {
            "kind": entry["kind"], "window_start": entry["window_start"],
            "depth": int(entry["depth"]),
            "key_lanes": int(entry["key_lanes"]),
            "value_cols": list(entry["value_cols"]),
            "rows": rows, "cms": planes["cms"], "regs": planes["regs"],
        }
    ranges = {}
    for table, spec in delta["ranges"].items():
        pslots = dict((int(s), rows)
                      for s, rows in prev["ranges"].get(table, []))
        chunks = {int(s): rows for s, rows in spec["chunks"].items()}
        out = []
        for slot in spec["slots"]:
            slot = int(slot)
            rows = chunks.get(slot, pslots.get(slot))
            if rows is None:
                raise DeltaError(
                    f"delta names range slot {table}:{slot} it neither "
                    "ships nor the base holds")
            out.append([slot, rows])
        ranges[table] = out
    return {
        "version": int(delta["to"]), "created": float(delta["created"]),
        "watermark": float(delta["watermark"]),
        "flows_seen": delta["flows_seen"], "source": delta["source"],
        "families": families, "ranges": ranges,
        "audit": delta["audit"] if "audit" in delta else prev["audit"],
    }


# ---- frames ----------------------------------------------------------------


def _frame(tree: dict) -> bytes:
    body = codec.encode(tree)
    return MAGIC + _HEAD.pack(len(body), zlib.crc32(body)) + body


def encode_full(state: dict) -> bytes:
    return _frame({"t": "full", "to": int(state["version"]),
                   "state": state})


def encode_delta(prev: dict, cur: dict) -> bytes:
    return _frame({"t": "delta", **diff_states(prev, cur)})


def encode_none(version: int) -> bytes:
    """The "you are current" frame — a poll answer, so the subscriber
    can tell an idle upstream from a dead one."""
    return _frame({"t": "none", "to": int(version)})


def decode_frames(data: bytes) -> Iterator[dict]:
    """Yield every frame tree in ``data``. Raises :class:`DeltaError`
    on a bad magic, torn header/body, or CRC mismatch — subscription
    transports are expected to deliver whole responses, so any damage
    means resync, not salvage."""
    off = 0
    while off < len(data):
        if data[off:off + len(MAGIC)] != MAGIC:
            raise DeltaError("bad frame magic")
        off += len(MAGIC)
        head = data[off:off + _HEAD.size]
        if len(head) < _HEAD.size:
            raise DeltaError("torn frame header")
        body_len, crc = _HEAD.unpack(head)
        off += _HEAD.size
        body = data[off:off + body_len]
        if len(body) < body_len:
            raise DeltaError("torn frame body")
        if zlib.crc32(body) != crc:
            raise DeltaError("frame CRC mismatch")
        off += body_len
        yield codec.decode(body)
