"""flowgate consistent-hash ring + failover query client.

K stateless gateway replicas each hold the same immutable mirrored
snapshot, so ANY replica can answer ANY query — the ring is a cache-
affinity and load-spreading device, not a correctness one: routing a
repeated query to the same replica keeps hitting that replica's
``(version, query)`` response cache, and killing a replica moves only
its arc onto the survivors (the classic consistent-hashing property;
a modulo ring would remap almost every key).

:class:`GatewayClient` is the client half of the replication story:
route by query key, and on a transport failure mark the replica dead
for a cooldown and retry the SAME query on the next live arc — which is
what makes a replica kill invisible (zero 5xx: a dead socket is retried
elsewhere, never surfaced)."""

from __future__ import annotations

# flowlint: lock-checked
# (HashRing is immutable after construction; GatewayClient state is
# guarded by its _lock — tests drive it from N reader threads)
# flowlint: net-checked
# (every query carries an explicit timeout: a wedged replica must cost
# the client one bounded request, not a hang)

import bisect
import http.client
import threading
import time
import zlib

# Virtual nodes per replica: enough that 2-4 replica rings split load
# evenly (the estate's deployment size), cheap to build.
VNODES = 64


def _point(s: str) -> int:
    # crc32: stable across processes and Python builds (hash() is
    # per-process salted — two clients would disagree on the ring)
    return zlib.crc32(s.encode("utf-8", "surrogatepass"))


class HashRing:
    """Immutable consistent-hash ring over node name strings."""

    def __init__(self, nodes, vnodes: int = VNODES):
        self.nodes = tuple(dict.fromkeys(nodes))  # order-stable dedupe
        pts = sorted((_point(f"{n}#{i}"), n)
                     for n in self.nodes for i in range(vnodes))
        self._keys = [p for p, _ in pts]
        self._owners = [n for _, n in pts]

    def node_for(self, key: str, skip=()) -> str | None:
        """The first live node clockwise from the key's point.
        ``skip`` masks dead nodes — their arcs fall to the successors,
        which is exactly the replica-kill remap."""
        if not self._keys:
            return None
        i = bisect.bisect(self._keys, _point(key)) % len(self._keys)
        for step in range(len(self._keys)):
            n = self._owners[(i + step) % len(self._keys)]
            if n not in skip:
                return n
        return None


class GatewayClient:
    """Keep-alive query client over a gateway replica set."""

    def __init__(self, addrs, timeout: float = 10.0,
                 dead_for: float = 1.0, vnodes: int = VNODES,
                 monotone_wait: float = 0.5):
        self.ring = HashRing([a if isinstance(a, str) else f"{a[0]}:{a[1]}"
                              for a in addrs], vnodes=vnodes)
        self.timeout = timeout
        self.dead_for = dead_for
        self.monotone_wait = monotone_wait
        # flowlint: unguarded -- the lock itself; bound once
        self._lock = threading.Lock()
        self._dead: dict[str, float] = {}  # node -> retry-at  # guarded-by: _lock
        # flowguard: replicas that answered 503 + Retry-After are
        # DEGRADED, not dead — deprioritized until the advertised
        # retry time but still eligible as a last resort (an
        # overloaded replica can answer; a dead one cannot)
        self._degraded: dict[str, float] = {}  # node -> retry-at  # guarded-by: _lock
        self.retries = 0  # transport failovers taken  # guarded-by: _lock
        self.deprioritized = 0  # 503-driven reroutes taken  # guarded-by: _lock
        # session watermark for monotone reads: the highest snapshot
        # version any response carried. A failover target slightly
        # behind it is re-polled briefly (it mirrors the same upstream
        # and catches up within its poll cadence) instead of handing
        # the session a version that runs backwards.
        self.watermark = 0  # guarded-by: _lock
        self.stale_reads = 0  # monotone waits that timed out  # guarded-by: _lock
        self._tls = threading.local()

    def _skip(self) -> set:
        now = time.monotonic()
        with self._lock:
            for n, until in list(self._dead.items()):
                if until <= now:
                    del self._dead[n]
            return set(self._dead)

    def _mark_dead(self, node: str) -> None:
        with self._lock:
            self._dead[node] = time.monotonic() + self.dead_for
            self.retries += 1

    def _slow(self) -> set:
        now = time.monotonic()
        with self._lock:
            for n, until in list(self._degraded.items()):
                if until <= now:
                    del self._degraded[n]
            return set(self._degraded)

    def _mark_degraded(self, node: str, retry_after: float) -> None:
        with self._lock:
            self._degraded[node] = time.monotonic() + max(
                0.05, min(retry_after, 30.0))
            self.deprioritized += 1

    def _conn_for(self, node: str):
        # one connection per (thread, node): http.client connections are
        # not thread-safe, and the closed-loop client model is
        # one-request-at-a-time per thread anyway
        conns = getattr(self._tls, "conns", None)
        if conns is None:
            conns = self._tls.conns = {}
        conn = conns.get(node)
        if conn is None:
            host, _, port = node.rpartition(":")
            conn = conns[node] = http.client.HTTPConnection(
                host, int(port), timeout=self.timeout)
        return conn

    def get(self, path: str, key: str | None = None) -> tuple[int, bytes]:
        """One GET, routed by ``key`` (default: the path itself, so
        repeated queries pin to one replica's response cache). Tries
        every live replica before giving up — a dead replica costs a
        failover, never an error surfaced to the caller while any
        replica lives."""
        last_err: Exception | None = None
        last_503: tuple[int, bytes] | None = None
        tried: set[str] = set()
        for _ in range(max(1, len(self.ring.nodes))):
            # preference order: healthy first, then degraded (they DO
            # answer, just slowly), then through the dead set rather
            # than failing a query the survivors could serve
            node = self.ring.node_for(
                key or path, skip=self._skip() | self._slow() | tried)
            if node is None:
                node = self.ring.node_for(key or path,
                                          skip=self._skip() | tried)
            if node is None:
                node = self.ring.node_for(key or path, skip=tried)
            if node is None:
                break
            try:
                conn = self._conn_for(node)
                conn.request("GET", path)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status == 503:
                    ra = resp.getheader("Retry-After")
                    if ra is not None:
                        # flowguard overload shed: the replica is
                        # degraded, not dead — deprioritize it for the
                        # advertised interval and try another arc
                        try:
                            after = float(ra)
                        except ValueError:
                            after = 1.0
                        self._mark_degraded(node, after)
                        last_503 = (resp.status, body)
                        tried.add(node)
                        continue
                return resp.status, body
            except (OSError, http.client.HTTPException) as e:
                # HTTPException covers a replica killed MID-RESPONSE
                # (IncompleteRead/BadStatusLine are NOT OSErrors) —
                # the contract is "retried elsewhere, never surfaced"
                last_err = e
                tried.add(node)
                self._mark_dead(node)
                conns = getattr(self._tls, "conns", {})
                stale = conns.pop(node, None)
                if stale is not None:
                    stale.close()
        if last_503 is not None:
            # every replica is overloaded: surface the honest 503 (the
            # caller can retry after the advertised interval) — a shed
            # is an answer, a ConnectionError is an outage
            return last_503
        raise ConnectionError(
            f"no gateway replica answered {path!r}") from last_err

    def get_json(self, path: str, key: str | None = None,
                 monotone: bool = True, wait: float | None = None):
        """GET + JSON decode with MONOTONE READS: if the answering
        replica is behind the session's version watermark (a failover
        onto a mirror that has not polled past the dead replica's last
        version yet), briefly re-poll — the mirror catches up within
        its poll cadence. If it stays behind past ``wait``,
        availability wins: the stale answer is returned and counted
        (``stale_reads``), never an error."""
        import json

        deadline = time.monotonic() + (
            self.monotone_wait if wait is None else wait)
        while True:
            code, body = self.get(path, key=key)
            doc = json.loads(body) if body else None
            v = doc.get("version") if isinstance(doc, dict) else None
            if v is None or code != 200:
                return code, doc
            with self._lock:
                wm = self.watermark
                if not monotone or v >= wm:
                    self.watermark = max(wm, int(v))
                    return code, doc
            if time.monotonic() >= deadline:
                with self._lock:
                    self.stale_reads += 1
                return code, doc
            time.sleep(0.01)

    def close(self) -> None:
        conns = getattr(self._tls, "conns", {})
        for conn in conns.values():
            conn.close()
        conns.clear()
