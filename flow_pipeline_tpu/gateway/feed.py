"""flowgate subscription feed: the publisher side of delta shipping.

One :class:`SnapshotFeed` sits next to a :class:`~..serve.SnapshotStore`
and answers ``/sub/snapshot?since=V`` polls (serve/server.py routes
them here). It is lazy the same way ``FrozenCms`` is: nothing is
encoded until a subscriber asks, and the encode runs on the
SUBSCRIBER-FACING reader thread — the dataplane publish path never pays
a byte of it (``store.publish`` stays one pointer swap).

Per observed version the feed keeps ONE full frame plus a bounded chain
of delta frames between consecutively OBSERVED versions (a feed that is
polled slower than the publisher publishes simply produces coarser
deltas — the chain is over what the feed saw, and a subscriber's
``since`` either matches a chain link or gets the full frame). History
eviction, a subscriber older than the chain, or a brand-new subscriber
(``since=0``) all resolve to a full-snapshot ship — the resync path is
the bootstrap path, not a special case.
"""

from __future__ import annotations

# flowlint: lock-checked
# (polled from N subscriber HTTP threads; one lock guards the memoized
# state/frames. The store pointer read inside is the RCU-lock-free read
# every serve reader does.)

import threading
from collections import deque
from typing import Optional

from .delta import encode_delta, encode_full, encode_none, snapshot_state

# Delta-chain retention (observed version transitions). A subscriber
# further behind than this gets a full snapshot — at production poll
# cadences (sub-second) 64 transitions is tens of seconds of outage
# ridden on deltas.
FEED_HISTORY = 64

# ...and a cumulative BYTE budget on the same chain: under saturated
# ingest every CMS tile is dirty and a delta is ~full-snapshot sized
# (megabytes — bench.py records the ratio), so a count-only bound
# could hold 64 snapshots' worth of encoded bytes resident (the r17
# journal lesson, on RAM instead of disk). Evicting the oldest links
# past the budget just widens the full-resync window — the fallback
# every evicted subscriber already takes.
FEED_HISTORY_BYTES = 128 << 20


class SnapshotFeed:
    """Delta/full frame source for one snapshot store."""

    def __init__(self, store, history: int = FEED_HISTORY,
                 history_bytes: int = FEED_HISTORY_BYTES):
        self.store = store
        self.history_bytes = history_bytes
        # flowlint: unguarded -- the lock itself; bound once
        self._lock = threading.Lock()
        self._state: Optional[dict] = None  # guarded-by: _lock
        self._full: Optional[bytes] = None  # guarded-by: _lock
        # (from_version, to_version, frame bytes), consecutive by
        # construction: each append chains from the previous _state
        self._deltas: deque = deque(maxlen=history)  # guarded-by: _lock
        self._delta_bytes_held = 0  # guarded-by: _lock
        # shipping-cost ledger (bench reads it): per-transition encoded
        # sizes — the honest bytes-per-publish evidence for delta vs
        # full shipping
        self._stats = {"publishes": 0, "full_bytes": 0,  # guarded-by: _lock
                       "delta_bytes": 0, "deltas": 0}

    def _refresh_locked(self) -> None:
        snap = self.store.current
        if snap is None:
            return
        if self._state is not None and \
                snap.version <= self._state["version"]:
            return
        state = snapshot_state(snap)
        full = encode_full(state)
        self._stats["publishes"] += 1  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        self._stats["full_bytes"] += len(full)  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        if self._state is not None:
            frame = encode_delta(self._state, state)
            if len(self._deltas) == self._deltas.maxlen:
                # the append below will silently drop the oldest link
                self._delta_bytes_held -= len(self._deltas[0][2])  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
            self._deltas.append(
                (self._state["version"], state["version"], frame))
            self._delta_bytes_held += len(frame)  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
            while self._delta_bytes_held > self.history_bytes \
                    and self._deltas:
                self._delta_bytes_held -= len(self._deltas.popleft()[2])  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
            self._stats["deltas"] += 1  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
            self._stats["delta_bytes"] += len(frame)  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        self._state, self._full = state, full  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)

    def frame_since(self, since: int) -> tuple[str, int, bytes]:
        """(kind, current_version, frames) for one subscriber poll.
        ``kind``: "none" (already current), "delta" (a chain of >= 1
        delta frames), or "full" (bootstrap / gap / evicted history)."""
        with self._lock:
            self._refresh_locked()
            if self._state is None:
                return "none", 0, encode_none(0)
            cur = self._state["version"]
            if since == cur:
                return "none", cur, encode_none(cur)
            if since:
                frms = [frm for frm, _, _ in self._deltas]
                if since in frms:
                    # the deque links consecutively, so everything from
                    # the `since` link onward IS the exact chain to cur
                    chain = list(self._deltas)[frms.index(since):]
                    return "delta", cur, b"".join(f for _, _, f in chain)
            return "full", cur, self._full

    def stats(self) -> dict:
        """Copy of the shipping-cost ledger (+ per-publish averages)."""
        with self._lock:
            out = dict(self._stats)
        if out["publishes"]:
            out["full_bytes_per_publish"] = round(
                out["full_bytes"] / out["publishes"], 1)
        if out["deltas"]:
            out["delta_bytes_per_publish"] = round(
                out["delta_bytes"] / out["deltas"], 1)
        return out
