"""Schema DDL as code, for every storage target.

Shapes mirror the reference so its Grafana dashboards keep working:
- Postgres ``flows`` raw table: 14 columns + id (ref: compose/postgres/create.sh:5-24)
- ClickHouse ``flows_raw`` / ``flows_5m`` + materialized views
  (ref: compose/clickhouse/create.sh:36-110)
plus this framework's own aggregate tables (flows_5m rows arrive
pre-aggregated from the TPU, so the ClickHouse MV chain is optional).
"""

POSTGRES_FLOWS = """
CREATE TABLE IF NOT EXISTS flows (
    id             BIGSERIAL PRIMARY KEY,
    date_inserted  TIMESTAMP,
    time_flow      TIMESTAMP,
    type           INT,
    sampling_rate  BIGINT,
    src_as         BIGINT,
    dst_as         BIGINT,
    src_ip         INET,
    dst_ip         INET,
    bytes          BIGINT,
    packets        BIGINT,
    etype          INT,
    proto          INT,
    src_port       INT,
    dst_port       INT
);
"""

# bytes_scaled/packets_scaled: sampling-rate-corrected sums
# (sum over rows of value * max(sampling_rate, 1)) — what the reference
# computes at query time over raw rows (sum(bytes*sampling_rate), ref:
# compose/grafana/dashboards/viz.json:62); pre-aggregated serving must
# store it or the rate information is unrecoverable.
POSTGRES_FLOWS_5M = """
CREATE TABLE IF NOT EXISTS flows_5m (
    timeslot       BIGINT,
    src_as         BIGINT,
    dst_as         BIGINT,
    etype          INT,
    bytes          BIGINT,
    packets        BIGINT,
    count          BIGINT,
    bytes_scaled   BIGINT,
    packets_scaled BIGINT
);
"""

POSTGRES_TOP_TALKERS = """
CREATE TABLE IF NOT EXISTS top_talkers (
    timeslot  BIGINT,
    rank      INT,
    src_addr  TEXT,
    dst_addr  TEXT,
    src_port  INT,
    dst_port  INT,
    proto     INT,
    bytes     BIGINT,
    packets   BIGINT,
    count     BIGINT
);
"""

POSTGRES_TOP_SRC_IPS = """
CREATE TABLE IF NOT EXISTS top_src_ips (
    timeslot  BIGINT,
    rank      INT,
    src_addr  TEXT,
    bytes     BIGINT,
    packets   BIGINT,
    count     BIGINT
);
"""

POSTGRES_TOP_DST_IPS = """
CREATE TABLE IF NOT EXISTS top_dst_ips (
    timeslot  BIGINT,
    rank      INT,
    dst_addr  TEXT,
    bytes     BIGINT,
    packets   BIGINT,
    count     BIGINT
);
"""

POSTGRES_TOP_SRC_PORTS = """
CREATE TABLE IF NOT EXISTS top_src_ports (
    timeslot  BIGINT,
    rank      INT,
    src_port  INT,
    bytes     BIGINT,
    packets   BIGINT,
    count     BIGINT
);
"""

POSTGRES_TOP_DST_PORTS = """
CREATE TABLE IF NOT EXISTS top_dst_ports (
    timeslot  BIGINT,
    rank      INT,
    dst_port  INT,
    bytes     BIGINT,
    packets   BIGINT,
    count     BIGINT
);
"""

POSTGRES_DDOS_ALERTS = """
CREATE TABLE IF NOT EXISTS ddos_alerts (
    sub_window         BIGINT,
    bucket             INT,
    dst_addr           TEXT,
    rate               DOUBLE PRECISION,
    zscore             DOUBLE PRECISION,
    baseline_quantile  DOUBLE PRECISION
);
"""

# Full-fidelity raw archive (ref: compose/clickhouse/create.sh:36-62).
# Two deliberate divergences: SrcAddr/DstAddr/SamplerAddress are the IPv6
# domain type (16 bytes on disk, like the reference's FixedString(16))
# because rows arrive over JSONEachRow, where raw bytes cannot be
# round-tripped but IPv6 text can — IPv6NumToString-style queries keep
# working; and Date is
# MATERIALIZED server-side from TimeReceived instead of being shipped per
# row (the reference derives it in its flows_raw_view MV the same way).
CLICKHOUSE_FLOWS_RAW = """
CREATE TABLE IF NOT EXISTS flows_raw (
    Date Date MATERIALIZED toDate(toDateTime(TimeReceived)),
    TimeReceived UInt64,
    TimeFlowStart UInt64,
    SequenceNum UInt32,
    SamplingRate UInt64,
    SamplerAddress IPv6,
    SrcAddr IPv6,
    DstAddr IPv6,
    SrcAS UInt32,
    DstAS UInt32,
    EType UInt32,
    Proto UInt32,
    SrcPort UInt32,
    DstPort UInt32,
    Bytes UInt64,
    Packets UInt64
) ENGINE = MergeTree()
PARTITION BY Date
ORDER BY TimeReceived;
"""

CLICKHOUSE_TOP_TALKERS = """
CREATE TABLE IF NOT EXISTS top_talkers (
    timeslot UInt64,
    rank UInt32,
    src_addr String,
    dst_addr String,
    src_port UInt32,
    dst_port UInt32,
    proto UInt32,
    bytes UInt64,
    packets UInt64,
    count UInt64
) ENGINE = MergeTree()
ORDER BY (timeslot, rank);
"""

CLICKHOUSE_TOP_SRC_IPS = """
CREATE TABLE IF NOT EXISTS top_src_ips (
    timeslot UInt64,
    rank UInt32,
    src_addr String,
    bytes UInt64,
    packets UInt64,
    count UInt64
) ENGINE = MergeTree()
ORDER BY (timeslot, rank);
"""

CLICKHOUSE_TOP_DST_IPS = """
CREATE TABLE IF NOT EXISTS top_dst_ips (
    timeslot UInt64,
    rank UInt32,
    dst_addr String,
    bytes UInt64,
    packets UInt64,
    count UInt64
) ENGINE = MergeTree()
ORDER BY (timeslot, rank);
"""

CLICKHOUSE_TOP_SRC_PORTS = """
CREATE TABLE IF NOT EXISTS top_src_ports (
    timeslot UInt64,
    rank UInt32,
    src_port UInt32,
    bytes UInt64,
    packets UInt64,
    count UInt64
) ENGINE = MergeTree()
ORDER BY (timeslot, rank);
"""

CLICKHOUSE_TOP_DST_PORTS = """
CREATE TABLE IF NOT EXISTS top_dst_ports (
    timeslot UInt64,
    rank UInt32,
    dst_port UInt32,
    bytes UInt64,
    packets UInt64,
    count UInt64
) ENGINE = MergeTree()
ORDER BY (timeslot, rank);
"""

CLICKHOUSE_DDOS_ALERTS = """
CREATE TABLE IF NOT EXISTS ddos_alerts (
    sub_window UInt64,
    bucket UInt32,
    dst_addr String,
    rate Float64,
    zscore Float64,
    baseline_quantile Float64
) ENGINE = MergeTree()
ORDER BY sub_window;
"""

CLICKHOUSE_FLOWS_5M = """
CREATE TABLE IF NOT EXISTS flows_5m (
    Date Date,
    Timeslot DateTime,
    SrcAS UInt32,
    DstAS UInt32,
    EType UInt32,
    Bytes UInt64,
    Packets UInt64,
    Count UInt64,
    Bytes_scaled UInt64,
    Packets_scaled UInt64
) ENGINE = SummingMergeTree()
ORDER BY (Date, Timeslot, SrcAS, DstAS, EType);
"""

# Widened-schema migrations, issued at sink startup right after the
# CREATEs: CREATE TABLE IF NOT EXISTS silently keeps a pre-existing table
# WITHOUT the r4 *_scaled columns, so the first insert after an upgrade
# would fail (unknown JSONEachRow field in ClickHouse / undefined column
# in Postgres) and crash-loop the processor — the failure mode
# check_raw_schema exists to prevent for flows_raw (ADVICE r4). Both
# dialects support ADD COLUMN IF NOT EXISTS, so these are idempotent and
# free on a current schema.
POSTGRES_MIGRATIONS = (
    "ALTER TABLE flows_5m ADD COLUMN IF NOT EXISTS bytes_scaled BIGINT",
    "ALTER TABLE flows_5m ADD COLUMN IF NOT EXISTS packets_scaled BIGINT",
)
CLICKHOUSE_MIGRATIONS = (
    "ALTER TABLE flows_5m ADD COLUMN IF NOT EXISTS Bytes_scaled UInt64",
    "ALTER TABLE flows_5m ADD COLUMN IF NOT EXISTS Packets_scaled UInt64",
)

# Flush-table name -> column order, shared by every SQL sink (single source
# of truth; the sinks must not drift from each other or from the DDL above).
TABLE_COLUMNS = {
    "flows_5m": ["timeslot", "src_as", "dst_as", "etype", "bytes", "packets",
                 "count", "bytes_scaled", "packets_scaled"],
    "top_talkers": ["timeslot", "rank", "src_addr", "dst_addr", "src_port",
                    "dst_port", "proto", "bytes", "packets", "count"],
    "top_src_ips": ["timeslot", "rank", "src_addr", "bytes", "packets",
                    "count"],
    "top_dst_ips": ["timeslot", "rank", "dst_addr", "bytes", "packets",
                    "count"],
    "top_src_ports": ["timeslot", "rank", "src_port", "bytes", "packets",
                      "count"],
    "top_dst_ports": ["timeslot", "rank", "dst_port", "bytes", "packets",
                      "count"],
    "ddos_alerts": ["sub_window", "bucket", "dst_addr", "rate", "zscore",
                    "baseline_quantile"],
    "flows": ["time_flow", "type", "sampling_rate", "src_as", "dst_as",
              "src_ip", "dst_ip", "bytes", "packets", "etype", "proto",
              "src_port", "dst_port"],
}


RANKED_TABLES = {"top_talkers", "top_src_ips", "top_dst_ips",
                 "top_src_ports", "top_dst_ports"}


def assign_ranks(table: str, records: list[dict]) -> list[dict]:
    """Top-K tables' rows are emitted in rank order; materialize the rank."""
    if table in RANKED_TABLES:
        for rank, r in enumerate(records):
            r.setdefault("rank", rank)
    return records


SQLITE_TABLES = {
    "flows": """
CREATE TABLE IF NOT EXISTS flows (
    id            INTEGER PRIMARY KEY AUTOINCREMENT,
    date_inserted TEXT DEFAULT CURRENT_TIMESTAMP,
    time_flow     TEXT,
    type          INTEGER,
    sampling_rate INTEGER,
    src_as        INTEGER,
    dst_as        INTEGER,
    src_ip        TEXT,
    dst_ip        TEXT,
    bytes         INTEGER,
    packets       INTEGER,
    etype         INTEGER,
    proto         INTEGER,
    src_port      INTEGER,
    dst_port      INTEGER
);
""",
    "flows_5m": """
CREATE TABLE IF NOT EXISTS flows_5m (
    timeslot INTEGER, src_as INTEGER, dst_as INTEGER, etype INTEGER,
    bytes INTEGER, packets INTEGER, count INTEGER,
    bytes_scaled INTEGER, packets_scaled INTEGER
);
""",
    "top_talkers": """
CREATE TABLE IF NOT EXISTS top_talkers (
    timeslot INTEGER, rank INTEGER, src_addr TEXT, dst_addr TEXT,
    src_port INTEGER, dst_port INTEGER, proto INTEGER,
    bytes INTEGER, packets INTEGER, count INTEGER
);
""",
    "top_src_ips": """
CREATE TABLE IF NOT EXISTS top_src_ips (
    timeslot INTEGER, rank INTEGER, src_addr TEXT,
    bytes INTEGER, packets INTEGER, count INTEGER
);
""",
    "top_dst_ips": """
CREATE TABLE IF NOT EXISTS top_dst_ips (
    timeslot INTEGER, rank INTEGER, dst_addr TEXT,
    bytes INTEGER, packets INTEGER, count INTEGER
);
""",
    "top_src_ports": """
CREATE TABLE IF NOT EXISTS top_src_ports (
    timeslot INTEGER, rank INTEGER, src_port INTEGER,
    bytes INTEGER, packets INTEGER, count INTEGER
);
""",
    "top_dst_ports": """
CREATE TABLE IF NOT EXISTS top_dst_ports (
    timeslot INTEGER, rank INTEGER, dst_port INTEGER,
    bytes INTEGER, packets INTEGER, count INTEGER
);
""",
    "ddos_alerts": """
CREATE TABLE IF NOT EXISTS ddos_alerts (
    sub_window INTEGER, bucket INTEGER, dst_addr TEXT,
    rate REAL, zscore REAL, baseline_quantile REAL
);
""",
}
