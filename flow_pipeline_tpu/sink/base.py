"""Sink plumbing: row normalization + trivial sinks."""

from __future__ import annotations

import sys
from typing import Any

import numpy as np

from ..schema.batch import words_to_addr


def _addr_str(words) -> str:
    """[4] uint32 words -> printable address. IPv4-in-trailing-4-bytes
    renders dotted quad (the convention Grafana queries decode,
    ref: viz-ch.json IPv4NumToString(...substring(reverse(SrcAddr),13,4))."""
    raw = words_to_addr(np.asarray(words, dtype=np.uint32))
    if raw[:12] == b"\x00" * 12:
        return ".".join(str(b) for b in raw[12:])
    import ipaddress

    return str(ipaddress.IPv6Address(raw))


def rows_to_records(rows: Any) -> list[dict]:
    """Columnar flush output (dict of arrays) or a list of dicts -> list of
    flat records with printable addresses."""
    if isinstance(rows, list):  # e.g. DDoS alerts
        out = []
        for r in rows:
            r = dict(r)
            for k, v in list(r.items()):
                if isinstance(v, np.ndarray) and v.shape == (4,):
                    r[k] = _addr_str(v)
                elif isinstance(v, np.generic):
                    r[k] = v.item()
            out.append(r)
        return out
    names = list(rows.keys())
    n = len(rows[names[0]]) if names else 0
    records = []
    for i in range(n):
        if "valid" in rows and not rows["valid"][i]:
            continue
        rec = {}
        for name in names:
            if name == "valid":
                continue
            v = rows[name][i]
            if isinstance(v, np.ndarray):  # [4] address words
                rec[name] = _addr_str(v)
            else:
                rec[name] = v.item() if isinstance(v, np.generic) else v
        records.append(rec)
    return records


class MemorySink:
    """Accumulates records per table (tests)."""

    def __init__(self):
        self.tables: dict[str, list[dict]] = {}

    def write(self, table: str, rows) -> None:
        self.tables.setdefault(table, []).extend(rows_to_records(rows))


class StdoutSink:
    """Prints one line per record (demos)."""

    def __init__(self, stream=None, limit_per_flush: int = 20):
        self.stream = stream or sys.stdout
        self.limit = limit_per_flush

    def write(self, table: str, rows) -> None:
        records = rows_to_records(rows)
        for rec in records[: self.limit]:
            print(f"{table} {rec}", file=self.stream)
        if len(records) > self.limit:
            print(f"{table} ... {len(records) - self.limit} more rows",
                  file=self.stream)
