"""SQLite sink: a real queryable store from the stdlib.

Plays the Postgres role in zero-dependency deployments and tests; the table
shapes mirror the reference's Postgres schema (see sink.ddl.SQLITE_TABLES).
Known table names map to typed tables; unknown tables land in a generic
key-value journal so new models don't need schema changes to be observable.
"""

from __future__ import annotations

import json
import sqlite3
import threading

from . import ddl
from .base import rows_to_records

# flush-table name -> sqlite table + column order
_TABLE_COLUMNS = {
    "flows_5m": ("flows_5m",
                 ["timeslot", "src_as", "dst_as", "etype", "bytes", "packets",
                  "count"]),
    "top_talkers": ("top_talkers",
                    ["timeslot", "rank", "src_addr", "dst_addr", "src_port",
                     "dst_port", "proto", "bytes", "packets", "count"]),
    "ddos_alerts": ("ddos_alerts",
                    ["sub_window", "bucket", "dst_addr", "rate", "zscore",
                     "baseline_quantile"]),
    "flows": ("flows",
              ["time_flow", "type", "sampling_rate", "src_as", "dst_as",
               "src_ip", "dst_ip", "bytes", "packets", "etype", "proto",
               "src_port", "dst_port"]),
}


class SQLiteSink:
    def __init__(self, path: str = ":memory:"):
        # one connection guarded by a lock: sinks may be called from the
        # worker thread while tests query from the main thread
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            for stmt in ddl.SQLITE_TABLES.values():
                self._conn.executescript(stmt)
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS journal "
                "(table_name TEXT, record TEXT)"
            )
            self._conn.commit()

    def write(self, table: str, rows) -> None:
        records = rows_to_records(rows)
        if not records:
            return
        with self._lock:
            mapped = _TABLE_COLUMNS.get(table)
            if mapped is None:
                self._conn.executemany(
                    "INSERT INTO journal (table_name, record) VALUES (?, ?)",
                    [(table, json.dumps(r, default=str)) for r in records],
                )
            else:
                name, cols = mapped
                placeholders = ",".join("?" for _ in cols)
                collist = ",".join(f'"{c}"' for c in cols)
                if table == "top_talkers":
                    for rank, r in enumerate(records):
                        r.setdefault("rank", rank)
                self._conn.executemany(
                    f'INSERT INTO "{name}" ({collist}) VALUES ({placeholders})',
                    [tuple(r.get(c) for c in cols) for r in records],
                )
            self._conn.commit()

    def query(self, sql: str, params=()) -> list[tuple]:
        with self._lock:
            return list(self._conn.execute(sql, params))

    def close(self) -> None:
        with self._lock:
            self._conn.close()
