"""SQLite sink: a real queryable store from the stdlib.

Plays the Postgres role in zero-dependency deployments and tests; the table
shapes mirror the reference's Postgres schema (see sink.ddl.SQLITE_TABLES).
Known table names map to typed tables; unknown tables land in a generic
key-value journal so new models don't need schema changes to be observable.
"""

from __future__ import annotations

import json
import sqlite3
import threading

from . import ddl
from .base import rows_to_records


class SQLiteSink:
    def __init__(self, path: str = ":memory:"):
        # one connection guarded by a lock: sinks may be called from the
        # worker thread while tests query from the main thread
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            for stmt in ddl.SQLITE_TABLES.values():
                self._conn.executescript(stmt)
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS journal "
                "(table_name TEXT, record TEXT)"
            )
            self._migrate()
            self._conn.commit()

    def _migrate(self) -> None:
        """Upgrade pre-r4 files to the current flows_5m shape.

        CREATE TABLE IF NOT EXISTS is a no-op on an existing .db, so a
        file created before the sampling-scaled columns landed keeps the
        old schema and the first insert dies with "no column named
        bytes_scaled" — the crash-loop the Postgres/ClickHouse DDL
        already guards against (sink/ddl.py migrations). SQLite has no
        ADD COLUMN IF NOT EXISTS, so probe PRAGMA table_info first.
        Call under self._lock."""
        have = {row[1] for row in
                self._conn.execute("PRAGMA table_info(flows_5m)")}
        for col in ("bytes_scaled", "packets_scaled"):
            if have and col not in have:
                self._conn.execute(
                    f'ALTER TABLE flows_5m ADD COLUMN "{col}" INTEGER')

    def write(self, table: str, rows) -> None:
        records = rows_to_records(rows)
        if not records:
            return
        with self._lock:
            cols = ddl.TABLE_COLUMNS.get(table)
            if cols is None:
                self._conn.executemany(
                    "INSERT INTO journal (table_name, record) VALUES (?, ?)",
                    [(table, json.dumps(r, default=str)) for r in records],
                )
            else:
                ddl.assign_ranks(table, records)
                placeholders = ",".join("?" for _ in cols)
                collist = ",".join(f'"{c}"' for c in cols)
                self._conn.executemany(
                    f'INSERT INTO "{table}" ({collist}) VALUES ({placeholders})',
                    [tuple(r.get(c) for c in cols) for r in records],
                )
            self._conn.commit()

    def query(self, sql: str, params=()) -> list[tuple]:
        with self._lock:
            return list(self._conn.execute(sql, params))

    def close(self) -> None:
        with self._lock:
            self._conn.close()
