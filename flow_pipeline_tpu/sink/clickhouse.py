"""ClickHouse sink (gated on an HTTP endpoint).

Writes pre-aggregated flows_5m rows straight into the SummingMergeTree
table (ref: compose/clickhouse/create.sh:70-90) over the HTTP interface
using JSONEachRow — no driver dependency, just stdlib urllib. The
TPU engine replaces the Kafka-engine + MV chain, so only the final tables
are needed; partial rows for the same (Date, Timeslot, key) are summed by
the engine at merge time, which is exactly the late-data contract our
aggregator emits.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from . import ddl
from .base import rows_to_records


class ClickHouseSink:
    def __init__(self, url: str = "http://localhost:8123",
                 database: str = "default", timeout: float = 5.0,
                 create_tables: bool = True):
        self.url = url.rstrip("/")
        self.database = database
        self.timeout = timeout
        if create_tables:
            # a bare clickhouse-server has no schema; without this the first
            # flush 400s and the processor crash-loops
            for stmt in (ddl.CLICKHOUSE_FLOWS_RAW, ddl.CLICKHOUSE_FLOWS_5M,
                         ddl.CLICKHOUSE_TOP_TALKERS,
                         ddl.CLICKHOUSE_TOP_SRC_PORTS,
                         ddl.CLICKHOUSE_TOP_DST_PORTS,
                         ddl.CLICKHOUSE_DDOS_ALERTS):
                self._post(stmt)

    def _post(self, query: str, body: bytes = b"") -> None:
        req = urllib.request.Request(
            f"{self.url}/?database={self.database}&query="
            + urllib.parse.quote(query),
            data=body,
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            resp.read()

    def ping(self) -> bool:
        try:
            req = urllib.request.Request(f"{self.url}/ping")
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read().strip() == b"Ok."
        except (urllib.error.URLError, OSError):
            return False

    # flush-row keys -> ClickHouse column names (the tables use the
    # reference's CamelCase columns, ref: compose/clickhouse/create.sh:70-90)
    _FLOWS_5M_COLS = {
        "timeslot": "Timeslot",
        "src_as": "SrcAS",
        "dst_as": "DstAS",
        "etype": "EType",
        "bytes": "Bytes",
        "packets": "Packets",
        "count": "Count",
    }

    def write(self, table: str, rows) -> None:
        records = rows_to_records(rows)
        if not records:
            return
        ddl.assign_ranks(table, records)
        cols = ddl.TABLE_COLUMNS.get(table)
        if cols is not None:
            # Keep only DDL'd columns: flush rows carry extra keys (e.g.
            # the *_est CMS bounds) that JSONEachRow would reject as
            # unknown fields against the CREATEd tables.
            records = [{c: r.get(c) for c in cols if c in r} for r in records]
        if table == "flows_5m":
            records = [
                {self._FLOWS_5M_COLS.get(k, k): v for k, v in r.items()}
                for r in records
            ]
            for r in records:
                r.setdefault("Date", int(r.get("Timeslot", 0)) // 86400)
        body = "\n".join(json.dumps(r, default=str) for r in records).encode()
        self._post(f"INSERT INTO {table} FORMAT JSONEachRow", body)
