"""ClickHouse sink (gated on an HTTP endpoint).

Writes pre-aggregated flows_5m rows straight into the SummingMergeTree
table (ref: compose/clickhouse/create.sh:70-90) over the HTTP interface
using JSONEachRow — no driver dependency, just stdlib urllib. The
TPU engine replaces the Kafka-engine + MV chain, so only the final tables
are needed; partial rows for the same (Date, Timeslot, key) are summed by
the engine at merge time, which is exactly the late-data contract our
aggregator emits.
"""

from __future__ import annotations

# flowlint: net-checked
# (sink writes run on the worker/flusher hot path; a hung ClickHouse
# endpoint must surface as a timeout the retry ladder can handle, not
# an eternally blocked flush thread)

import ipaddress
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

import numpy as np

from . import ddl
from .base import rows_to_records
from ..schema.batch import words_to_addr


def raw_records(batch) -> list[dict]:
    """FlowBatch -> flows_raw rows (ref: compose/clickhouse/create.sh:36-62
    column names). Addresses render as IPv6 text for the IPv6 columns; all
    16 address bytes round-trip exactly. Date is MATERIALIZED server-side
    from TimeReceived (see ddl.CLICKHOUSE_FLOWS_RAW), so it is not built
    here — no per-row strftime in the archive hot loop."""
    c = batch.columns
    n = len(batch)
    src = np.asarray(c["src_addr"], dtype=np.uint32)
    dst = np.asarray(c["dst_addr"], dtype=np.uint32)
    smp = np.asarray(c["sampler_address"], dtype=np.uint32)
    out = []
    for i in range(n):
        out.append({
            "TimeReceived": int(c["time_received"][i]),
            "TimeFlowStart": int(c["time_flow_start"][i]),
            "SequenceNum": int(c["sequence_num"][i]),
            "SamplingRate": int(c["sampling_rate"][i]),
            "SamplerAddress": str(ipaddress.IPv6Address(words_to_addr(smp[i]))),
            "SrcAddr": str(ipaddress.IPv6Address(words_to_addr(src[i]))),
            "DstAddr": str(ipaddress.IPv6Address(words_to_addr(dst[i]))),
            "SrcAS": int(c["src_as"][i]),
            "DstAS": int(c["dst_as"][i]),
            "EType": int(c["etype"][i]),
            "Proto": int(c["proto"][i]),
            "SrcPort": int(c["src_port"][i]),
            "DstPort": int(c["dst_port"][i]),
            "Bytes": int(c["bytes"][i]),
            "Packets": int(c["packets"][i]),
        })
    return out


class ClickHouseSink:
    def __init__(self, url: str = "http://localhost:8123",
                 database: str = "default", timeout: float = 5.0,
                 create_tables: bool = True):
        self.url = url.rstrip("/")
        self.database = database
        self.timeout = timeout
        if create_tables:
            # a bare clickhouse-server has no schema; without this the first
            # flush 400s and the processor crash-loops
            for stmt in (ddl.CLICKHOUSE_FLOWS_RAW, ddl.CLICKHOUSE_FLOWS_5M,
                         ddl.CLICKHOUSE_TOP_TALKERS,
                         ddl.CLICKHOUSE_TOP_SRC_IPS,
                         ddl.CLICKHOUSE_TOP_DST_IPS,
                         ddl.CLICKHOUSE_TOP_SRC_PORTS,
                         ddl.CLICKHOUSE_TOP_DST_PORTS,
                         ddl.CLICKHOUSE_DDOS_ALERTS):
                self._post(stmt)
            for stmt in ddl.CLICKHOUSE_MIGRATIONS:
                self._post(stmt)

    def _post(self, query: str, body: bytes = b"") -> bytes:
        req = urllib.request.Request(
            f"{self.url}/?database={self.database}&query="
            + urllib.parse.quote(query),
            data=body,
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    def ping(self) -> bool:
        try:
            req = urllib.request.Request(f"{self.url}/ping")
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.read().strip() == b"Ok."
        except (urllib.error.URLError, OSError):
            return False

    # flush-row keys -> ClickHouse column names (the tables use the
    # reference's CamelCase columns, ref: compose/clickhouse/create.sh:70-90)
    _FLOWS_5M_COLS = {
        "timeslot": "Timeslot",
        "src_as": "SrcAS",
        "dst_as": "DstAS",
        "etype": "EType",
        "bytes": "Bytes",
        "packets": "Packets",
        "count": "Count",
        "bytes_scaled": "Bytes_scaled",
        "packets_scaled": "Packets_scaled",
    }

    def write(self, table: str, rows) -> None:
        records = rows_to_records(rows)
        if not records:
            return
        ddl.assign_ranks(table, records)
        cols = ddl.TABLE_COLUMNS.get(table)
        if cols is not None:
            # Keep only DDL'd columns: flush rows carry extra keys (e.g.
            # the *_est CMS bounds) that JSONEachRow would reject as
            # unknown fields against the CREATEd tables.
            records = [{c: r.get(c) for c in cols if c in r} for r in records]
        if table == "flows_5m":
            records = [
                {self._FLOWS_5M_COLS.get(k, k): v for k, v in r.items()}
                for r in records
            ]
            for r in records:
                r.setdefault("Date", int(r.get("Timeslot", 0)) // 86400)
        body = "\n".join(json.dumps(r, default=str) for r in records).encode()
        self._post(f"INSERT INTO {table} FORMAT JSONEachRow", body)

    # address columns every archive row ships; each must EXIST (an absent
    # column 400s JSONEachRow as unknown) and be type IPv6 (older DDLs
    # used FixedString(16); SamplerAddress is newer than both)
    _RAW_ADDR_COLS = ("SrcAddr", "DstAddr", "SamplerAddress")

    def check_raw_schema(self) -> None:
        """Fail fast with remediation if flows_raw predates the IPv6
        address columns or the SamplerAddress column: CREATE IF NOT
        EXISTS silently keeps an old schema, and the first archive insert
        would then 400 and crash-loop the processor with no hint why."""
        cols = ", ".join(f"'{c}'" for c in self._RAW_ADDR_COLS)
        try:
            out = self._post(
                "SELECT name, type FROM system.columns "
                "WHERE database = currentDatabase() AND table = 'flows_raw' "
                f"AND name IN ({cols}) FORMAT JSONEachRow"
            )
        except (urllib.error.URLError, OSError):
            return  # server unreachable: the insert path will surface it
        types = {
            r["name"]: r["type"]
            for r in (json.loads(l) for l in out.decode().splitlines() if l)
        }
        # a column that is entirely absent returns no row: presence must
        # be asserted explicitly, not just the type of what came back
        bad = [c for c in self._RAW_ADDR_COLS if types.get(c) != "IPv6"]
        if bad:
            raise RuntimeError(
                f"flows_raw columns {bad} are missing or not type IPv6 (a "
                "table created by an older DDL?); migrate with e.g. ALTER "
                "TABLE flows_raw ADD COLUMN IF NOT EXISTS SamplerAddress "
                "IPv6, MODIFY COLUMN SrcAddr IPv6, MODIFY COLUMN DstAddr "
                "IPv6 (or DROP the table) before enabling -archive.raw"
            )

    def archive_raw(self, batch) -> int:
        """Opt-in full-fidelity archive into flows_raw (the reference's
        raw-rows query path, ref: compose/clickhouse/create.sh:36-62;
        queried by its viz-ch.json). The worker calls this only on sinks
        that expose it and only when archiving is enabled."""
        records = raw_records(batch)
        if not records:
            return 0
        body = "\n".join(json.dumps(r) for r in records).encode()
        self._post("INSERT INTO flows_raw FORMAT JSONEachRow", body)
        return len(records)
