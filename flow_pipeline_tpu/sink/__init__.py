"""Sinks: flushed aggregate rows -> storage/serving edges.

The reference lands rows in Postgres (table ``flows``,
ref: compose/postgres/create.sh:5-24) or ClickHouse (``flows_raw`` +
``flows_5m``, ref: compose/clickhouse/create.sh:36-110) and lets Grafana
query them. Here:

- ``MemorySink`` / ``StdoutSink``: tests and demos.
- ``SQLiteSink``: a real queryable database from the stdlib, with
  reference-shaped tables — the zero-dependency stand-in for Postgres.
- ``PostgresSink`` / ``ClickHouseSink``: gated on their drivers; emit the
  same schemas so the reference's Grafana dashboards keep working.
- ``ResilientSink``: flowchaos retry + dead-letter wrapper around any of
  the above (``-sink.retries`` / ``-sink.deadletter``; replay with
  ``flowtpu-replay``).
- ``ddl``: the schema DDL for all targets, as code.

All sinks implement write(table, rows) and must tolerate repeated partial
rows per (window, key): the aggregator emits SummingMergeTree-style
partials for late data (see models.window_agg docstring).
"""

from .base import MemorySink, StdoutSink, rows_to_records
from .sqlite import SQLiteSink
from .postgres import PostgresSink
from .clickhouse import ClickHouseSink
from .resilient import ResilientSink, replay_deadletter
from . import ddl

__all__ = [
    "MemorySink",
    "StdoutSink",
    "SQLiteSink",
    "PostgresSink",
    "ClickHouseSink",
    "ResilientSink",
    "replay_deadletter",
    "rows_to_records",
    "ddl",
]
