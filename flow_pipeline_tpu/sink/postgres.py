"""Postgres sink (gated on psycopg2).

Reference-parity edge: the same ``flows`` table the Go inserter fills
(ref: compose/postgres/create.sh:5-24, inserter/inserter.go:95-106) plus
aggregate tables. Uses execute_values-style multi-row inserts — the
reference's row-at-a-time Exec is why it caps at a few thousand rows/sec
(ref: README.md:86-88).

SQL generation is separated from execution so tests cover the statements
without a server: ``insert_sql(table, records)`` returns (sql, args).
"""

from __future__ import annotations

from typing import Optional

from . import ddl
from .base import rows_to_records

_IMPORT_ERROR: Optional[str] = None
try:  # pragma: no cover - driver presence depends on environment
    import psycopg2  # type: ignore
except Exception as e:  # noqa: BLE001
    psycopg2 = None
    _IMPORT_ERROR = str(e)


_COLUMNS = {
    "flows_5m": ["timeslot", "src_as", "dst_as", "etype", "bytes", "packets",
                 "count"],
    "top_talkers": ["timeslot", "rank", "src_addr", "dst_addr", "src_port",
                    "dst_port", "proto", "bytes", "packets", "count"],
    "ddos_alerts": ["sub_window", "bucket", "dst_addr", "rate", "zscore",
                    "baseline_quantile"],
    "flows": ["time_flow", "type", "sampling_rate", "src_as", "dst_as",
              "src_ip", "dst_ip", "bytes", "packets", "etype", "proto",
              "src_port", "dst_port"],
}

DDL = {
    "flows": ddl.POSTGRES_FLOWS,
    "flows_5m": ddl.POSTGRES_FLOWS_5M,
    "top_talkers": ddl.POSTGRES_TOP_TALKERS,
    "ddos_alerts": ddl.POSTGRES_DDOS_ALERTS,
}


def available() -> bool:
    return psycopg2 is not None


def insert_sql(table: str, records: list[dict]) -> tuple[str, list]:
    """One multi-row INSERT statement for a known table: VALUES (...), (...),
    ... with flattened args — a single round trip per flush, not one per row
    (the reference's row-at-a-time Exec is its throughput ceiling). Quoted
    identifiers come from the static column table, never from user data."""
    cols = _COLUMNS[table]
    if table == "top_talkers":
        for rank, r in enumerate(records):
            r.setdefault("rank", rank)
    collist = ", ".join(f'"{c}"' for c in cols)
    row_ph = "(" + ", ".join(["%s"] * len(cols)) + ")"
    placeholders = ", ".join([row_ph] * len(records))
    sql = f'INSERT INTO "{table}" ({collist}) VALUES {placeholders}'
    args = [r.get(c) for r in records for c in cols]
    return sql, args


class PostgresSink:
    def __init__(self, dsn: str):
        if not available():
            raise RuntimeError(
                f"psycopg2 not importable ({_IMPORT_ERROR}); "
                "use SQLiteSink or MemorySink"
            )
        self._conn = psycopg2.connect(dsn)
        with self._conn, self._conn.cursor() as cur:
            for stmt in DDL.values():
                cur.execute(stmt)

    def write(self, table: str, rows) -> None:
        records = rows_to_records(rows)
        if not records or table not in _COLUMNS:
            return
        sql, args = insert_sql(table, records)
        with self._conn, self._conn.cursor() as cur:
            cur.execute(sql, args)

    def close(self) -> None:
        self._conn.close()
