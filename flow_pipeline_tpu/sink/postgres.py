"""Postgres sink (gated on psycopg2).

Reference-parity edge: the same ``flows`` table the Go inserter fills
(ref: compose/postgres/create.sh:5-24, inserter/inserter.go:95-106) plus
aggregate tables. Uses execute_values-style multi-row inserts — the
reference's row-at-a-time Exec is why it caps at a few thousand rows/sec
(ref: README.md:86-88).

SQL generation is separated from execution so tests cover the statements
without a server: ``insert_sql(table, records)`` returns (sql, args).
"""

from __future__ import annotations

from typing import Optional

from . import ddl
from .base import rows_to_records

_IMPORT_ERROR: Optional[str] = None
try:  # pragma: no cover - driver presence depends on environment
    import psycopg2  # type: ignore
except Exception as e:  # noqa: BLE001
    psycopg2 = None
    _IMPORT_ERROR = str(e)


_COLUMNS = ddl.TABLE_COLUMNS  # shared single source of truth (sink/ddl.py)

DDL = {
    "flows": ddl.POSTGRES_FLOWS,
    "flows_5m": ddl.POSTGRES_FLOWS_5M,
    "top_talkers": ddl.POSTGRES_TOP_TALKERS,
    "top_src_ips": ddl.POSTGRES_TOP_SRC_IPS,
    "top_dst_ips": ddl.POSTGRES_TOP_DST_IPS,
    "top_src_ports": ddl.POSTGRES_TOP_SRC_PORTS,
    "top_dst_ports": ddl.POSTGRES_TOP_DST_PORTS,
    "ddos_alerts": ddl.POSTGRES_DDOS_ALERTS,
}


def available() -> bool:
    return psycopg2 is not None


def insert_sql(table: str, records: list[dict]) -> tuple[str, list]:
    """One multi-row INSERT statement for a known table: VALUES (...), (...),
    ... with flattened args — a single round trip per flush, not one per row
    (the reference's row-at-a-time Exec is its throughput ceiling). Quoted
    identifiers come from the static column table, never from user data."""
    cols = _COLUMNS[table]
    ddl.assign_ranks(table, records)
    collist = ", ".join(f'"{c}"' for c in cols)
    row_ph = "(" + ", ".join(["%s"] * len(cols)) + ")"
    placeholders = ", ".join([row_ph] * len(records))
    sql = f'INSERT INTO "{table}" ({collist}) VALUES {placeholders}'
    args = [r.get(c) for r in records for c in cols]
    return sql, args


class PostgresSink:
    def __init__(self, dsn: str):
        if not available():
            raise RuntimeError(
                f"psycopg2 not importable ({_IMPORT_ERROR}); "
                "use SQLiteSink or MemorySink"
            )
        self._conn = psycopg2.connect(dsn)
        with self._conn, self._conn.cursor() as cur:
            for stmt in DDL.values():
                cur.execute(stmt)
            for stmt in ddl.POSTGRES_MIGRATIONS:
                cur.execute(stmt)

    def write(self, table: str, rows) -> None:
        records = rows_to_records(rows)
        if not records or table not in _COLUMNS:
            return
        sql, args = insert_sql(table, records)
        with self._conn, self._conn.cursor() as cur:
            cur.execute(sql, args)

    def close(self) -> None:
        self._conn.close()
