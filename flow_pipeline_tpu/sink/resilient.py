"""flowchaos sink resilience: bounded retries + replayable dead-letter.

Every sink ``write()`` in the repo was single-shot before r17: one
ClickHouse/Postgres blip became a ``FlushError`` that killed the worker
(the at-least-once contract turns an unwritten window into a replay —
correct, but a whole-process restart for a 200ms network hiccup).
:class:`ResilientSink` wraps any sink with the durability ladder the
reference pipeline's Kafka-everywhere design implies:

1. **Retry**: bounded exponential backoff + jitter
   (``utils/retry.py``) around the inner ``write()`` — transient
   faults (and injected ``sink.write`` faults) never surface.
2. **Dead-letter**: a batch that exhausts its retries is framed to
   ``<dir>/deadletter/`` as one atomic JSON file (records already
   normalized by ``rows_to_records`` — addresses stringified, numpy
   scalars unwrapped, so a spill is sink-agnostic) and the write
   RETURNS: the worker survives, commits past the batch, and the rows
   stay durable ON DISK instead of in a crash-looping process.
3. **Replay**: ``flowtpu-replay`` (cli ``replay`` subcommand) or
   :func:`replay_deadletter` re-ingests the spill into any sink spec,
   restoring row-set equality with a fault-free run — the
   ``make chaos-parity`` gate.

Without a dead-letter directory the wrapper retries and then RE-RAISES,
preserving the pre-r17 fail-the-step contract (offsets uncommitted,
replay on restart) for deployments that prefer crash-and-replay over
disk spill.

Metrics (registered at construction so dashboards resolve them):
``sink_write_retries_total{table}``, ``sink_write_failures_total{table}``
(exhausted batches), ``sink_deadletter_total{table}`` (spilled),
``sink_deadletter_depth`` (files currently on disk — the > 0 alert).
"""

from __future__ import annotations

# flowlint: durable-checked
# (the dead-letter spill is a durable surface: an acked spill must
# survive any crash — every write goes through utils/fsutil so the
# durability-protocol rule and the crash-point model checker see it)

import json
import os
import time
from typing import Optional, Sequence

from ..obs import REGISTRY, get_logger
from ..utils import fsutil
from ..utils.faults import FAULTS
from ..utils.retry import retry_call
from .base import rows_to_records

log = get_logger("sink")


class _TransientSinkError(Exception):
    """Wrapper marking an inner-sink exception as retryable: the retry
    filter must be a positive list (this + OSError for injected/real
    transport faults), never bare Exception — NON_RETRYABLE bugs pass
    through untouched. ``__cause__`` carries the real error."""

DEADLETTER_SUBDIR = "deadletter"

# Deterministic-bug exceptions: retrying these only triples their
# latency, and SPILLING them would park a poison batch at the head of
# the dead-letter queue (replay stops at the first failure to preserve
# order, so one poison file wedges every recoverable batch behind it).
# They re-raise immediately — fail the step loudly, offsets uncommitted,
# the crash-and-replay contract. Everything else (driver OperationalError,
# sqlite "database is locked", HTTP errors — many of which are NOT
# OSError subclasses) is treated as potentially transient: retried,
# then dead-lettered.
NON_RETRYABLE = (TypeError, ValueError, KeyError, IndexError,
                 AttributeError)

SINK_METRICS = {
    "retries": ("sink_write_retries_total",
                "sink write attempts retried after a transient failure "
                "(label: table)"),
    "failures": ("sink_write_failures_total",
                 "sink writes that exhausted their retry budget "
                 "(label: table)"),
    "dead": ("sink_deadletter_total",
             "batches spilled to the dead-letter directory "
             "(label: table)"),
    "depth": ("sink_deadletter_depth",
              "dead-letter files currently on disk awaiting replay"),
}


def _register_metrics() -> dict:
    return {
        "retries": REGISTRY.counter(*SINK_METRICS["retries"]),
        "failures": REGISTRY.counter(*SINK_METRICS["failures"]),
        "dead": REGISTRY.counter(*SINK_METRICS["dead"]),
        "depth": REGISTRY.gauge(*SINK_METRICS["depth"]),
    }


class ResilientSink:
    """Retry + dead-letter wrapper around one inner sink. The wrapper is
    transparent for the pass-through surfaces the worker probes
    (``archive_raw``/``check_raw_schema``/``query``/``tables``)."""

    def __init__(self, inner, retries: int = 4, backoff: float = 0.05,
                 backoff_max: float = 2.0, jitter: float = 0.25,
                 deadletter_dir: Optional[str] = None, sleep=time.sleep):
        self.inner = inner
        self.retries = max(1, int(retries))
        self.backoff = backoff
        self.backoff_max = backoff_max
        self.jitter = jitter
        self._sleep = sleep
        self._seq = 0
        self._m = _register_metrics()
        self.deadletter_dir = None
        if deadletter_dir:
            self.deadletter_dir = os.path.join(deadletter_dir,
                                               DEADLETTER_SUBDIR)
            os.makedirs(self.deadletter_dir, exist_ok=True)
            # a restart must report the backlog it inherited, not 0
            self._m["depth"].set(len(self._dlq_files()))

    # ---- pass-throughs (duck-typed sink surfaces) --------------------------

    def __getattr__(self, name):
        # archive_raw / check_raw_schema / query / tables / close:
        # resolved on the inner sink so the worker's feature probes see
        # the wrapped sink's real capabilities
        return getattr(self.inner, name)

    # ---- the resilient write ----------------------------------------------

    def write(self, table: str, rows) -> None:
        def attempt():
            if FAULTS.active:
                FAULTS.check("sink.write")
            try:
                self.inner.write(table, rows)
            except NON_RETRYABLE:
                # a deterministic bug, not an outage: no retry, no
                # poison spill — fail the step (see NON_RETRYABLE)
                raise
            except Exception as e:
                raise _TransientSinkError(e) from e

        def on_retry(i, exc, delay):
            self._m["retries"].inc(table=table)
            log.warning("sink write %s failed (%s); retry %d/%d in "
                        "%.2fs", table, exc.__cause__ or exc, i + 1,
                        self.retries - 1, delay)

        try:
            retry_call(attempt, attempts=self.retries, base=self.backoff,
                       cap=self.backoff_max, jitter=self.jitter,
                       retry_on=(_TransientSinkError, OSError),
                       sleep=self._sleep, on_retry=on_retry)
            return
        except NON_RETRYABLE:
            raise
        except Exception as e:  # noqa: BLE001 -- exhausted: dead-letter or re-raise
            self._m["failures"].inc(table=table)
            cause = e.__cause__ if isinstance(e, _TransientSinkError) \
                else e
            if self.deadletter_dir is None:
                raise cause from None
            self._spill(table, rows, cause)

    def _spill(self, table: str, rows, exc: BaseException) -> None:
        """Frame one exhausted batch to the dead-letter directory
        (atomic tmp+rename; records pre-normalized so replay is
        sink-agnostic). Never raises on the happy path — the whole
        point is that the worker survives."""
        records = rows_to_records(rows)
        self._seq += 1
        name = (f"{int(time.time() * 1000):013d}-{os.getpid()}-"
                f"{self._seq:06d}-{table}.dlq.json")
        path = os.path.join(self.deadletter_dir, name)
        doc = {"table": table, "records": records,
               "spilled_at": time.time(), "error": repr(exc),
               "version": 1}
        # the whole atomic-publish sentence in one call: write a temp,
        # fsync it, atomically replace, fsync the directory entry — a
        # power loss can never drop or tear a spill the worker already
        # committed past
        fsutil.write_bytes_durable(
            path, json.dumps(doc, default=str).encode("utf-8"))
        self._m["dead"].inc(table=table)
        self._m["depth"].set(len(self._dlq_files()))
        log.error("sink write %s exhausted %d attempts (%s); %d rows "
                  "dead-lettered to %s (replay with flowtpu-replay)",
                  table, self.retries, exc, len(records), path)

    def _dlq_files(self) -> list[str]:
        if self.deadletter_dir is None or \
                not os.path.isdir(self.deadletter_dir):
            return []
        return sorted(f for f in os.listdir(self.deadletter_dir)
                      if f.endswith(".dlq.json"))


def deadletter_files(root_dir: str) -> list[str]:
    """Absolute paths of the spill files under ``root_dir`` (accepts
    either the sink root or the deadletter/ subdir itself), oldest
    first (names sort by spill time)."""
    d = root_dir
    if os.path.basename(os.path.normpath(d)) != DEADLETTER_SUBDIR:
        d = os.path.join(d, DEADLETTER_SUBDIR)
    if not os.path.isdir(d):
        return []
    return [os.path.join(d, f) for f in sorted(os.listdir(d))
            if f.endswith(".dlq.json")]


def replay_deadletter(root_dir: str, sinks: Sequence,
                      delete: bool = True) -> tuple[int, int]:
    """Re-ingest every dead-letter file into ``sinks`` in spill order.
    A file is deleted only after EVERY sink accepted it (at-least-once:
    a replay crash re-replays — merging tables absorb repeats the same
    way they absorb worker replays). Returns (files_replayed,
    rows_replayed); the first failing file aborts the run so ordering
    is preserved for the next attempt."""
    files = deadletter_files(root_dir)
    n_rows = 0
    m = _register_metrics()
    for i, path in enumerate(files):
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        table, records = doc["table"], doc["records"]
        try:
            for sink in sinks:
                sink.write(table, records)
        except Exception as e:  # noqa: BLE001 -- stop at the first failure, keep order
            log.error("replay of %s failed (%s); %d file(s) left in "
                      "place", path, e, len(files) - i)
            raise
        n_rows += len(records)
        if delete:
            # flowlint: disable=durability-protocol -- deliberate: no dir-fsync after removing a replayed spill; a crash resurrects the file and it re-replays, which the at-least-once contract absorbs
            os.remove(path)
        log.info("replayed %d rows into %s from %s", len(records), table,
                 os.path.basename(path))
    m["depth"].set(len(deadletter_files(root_dir)))
    return len(files), n_rows
