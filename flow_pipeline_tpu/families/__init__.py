"""flowcensus: the SketchFamily registry (see registry.py)."""

from .registry import (  # noqa: F401
    FAMILIES,
    NON_FAMILY_KINDS,
    SketchFamily,
    audit_attrs,
    delta_planes,
    families,
    family,
    family_for_checkpoint,
    family_for_payload,
    family_for_snapshot,
    hook,
    resolve,
)
