"""flowcensus: the SketchFamily registry — one descriptor per sketch
family, owning every per-kind fact the layers used to hardcode.

ROADMAP item 4's friction ledger is the motivation: onboarding
flowspread ("one kernel + one monoid") meant hand-editing ~20 files of
per-kind ``elif`` ladders, and nothing but reviewer diligence caught a
family that silently missed one surface. This module is the cure the
repo already proved twice (``KNOWN_FLAGS`` for flags, ``ABI_ALLOWLIST``
for the C seam): a single literal source of truth, with a both-ways
coverage lint (``tools/flowlint/rules_family.py``, rule
``family-citizenship``) that statically parses THIS file and checks

- every registered family is a complete citizen of every dispatch
  surface (mesh merge, codec payload, serve capture, gateway delta,
  checkpoint, flags, docs, Makefile parity target, CI wiring,
  Grafana/alert presence), and
- conversely, any string-literal kind tag at a dispatch site that is
  NOT registered here is a finding (the abi-contract "stale allowlist
  entries are themselves findings" discipline applied to families).

Registration style matters: each ``register(SketchFamily(...))`` call
below uses keyword literals only, so the lint rule can read the whole
registry with ``ast.literal_eval``-grade confidence and a deleted
kwarg (the ``make lint-mutation`` smoke) stays syntactically valid
but visibly incomplete.

Hooks are "module:attr" string references resolved lazily via
:func:`resolve` — strings keep the registry import-cycle-free (the
engine, mesh, serve and gateway layers all import this module) AND
statically checkable (the lint rule verifies each target exists by
parsing the named module, no imports needed).
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass
from typing import Any, Optional

# Kind tags that legitimately appear at dispatch sites but are NOT
# mergeable sketch families — the lint rule treats any other
# unregistered literal as a finding, and (abi-contract discipline)
# flags entries HERE that no dispatch site mentions any more.
#
# - "ddos": a detector, not a family — no mesh payload, no gateway
#   delta, no /query surface of its own (alerts ride the sink).
# - "flowguard": the serve publisher's pseudo-model carrying guard
#   status rows; state lives in guard/, not in a sketch.
NON_FAMILY_KINDS = (
    "ddos",
    "flowguard",
)


@dataclass(frozen=True)
class SketchFamily:
    """Every per-kind fact one sketch family owns, in one place.

    Optional hook fields default to ``None`` so an incomplete
    registration still *parses* — completeness is the lint rule's job,
    not the interpreter's. ``ranked`` families (top-K surface) must
    additionally carry ``top_rows`` + both serve captures + an
    ``endpoint``; ``wagg`` is unranked (exact rows, served by slot
    range) and legitimately leaves those ``None``.
    """

    # ---- identity ------------------------------------------------------
    kind: str                       # mesh ModelSpec.kind / FamilyView.kind
    snapshot_kind: Optional[str] = None   # model.snapshot_kind tag
    checkpoint_kind: Optional[str] = None  # tag in worker checkpoints
    payload_kinds: tuple = ()       # mesh codec payload["kind"] values
    # ---- merge algebra -------------------------------------------------
    merge_monoid: Optional[str] = None  # "u64-sum" | "max" | "rank-fold" | "i64-sum"
    ranked: bool = True             # has a top-K surface
    state_attr: Optional[str] = None    # model attr holding mergeable state
    # ---- hooks ("module:attr" refs, resolved lazily) -------------------
    payload: Optional[str] = None   # model state -> mesh payload dict
    merge: Optional[str] = None     # fold payloads -> merged state
    top_rows: Optional[str] = None  # merged state -> ranked rows
    serve_capture: Optional[str] = None         # worker FamilyView parts
    serve_capture_merged: Optional[str] = None  # mesh FamilyView parts
    checkpoint_save: Optional[str] = None       # model -> state dict
    checkpoint_restore: Optional[str] = None    # state dict -> model
    # ---- gateway delta -------------------------------------------------
    # (snapshot-state key, planes-first?) per diffable plane array; the
    # gateway's sparse/tile delta coder iterates this instead of
    # hardcoding "cms" vs "regs" cases. planes-first=True means the
    # array is stored lanes-last (HLL regs: [depth, width, regs]) and
    # must be viewed plane-major for per-plane diffing.
    delta_planes: tuple = ()
    # ---- audit shadow --------------------------------------------------
    audit_attr: Optional[str] = None    # HostGroupPipeline attribute
    audit_class: Optional[str] = None   # "module:Class" shadow auditor
    # ---- native dataplane probes ---------------------------------------
    # (feature, C symbol, since-revision) triples the hostsketch
    # pipeline resolves at startup: available -> mark_native_serving,
    # absent under a native backend -> report_native_degradation.
    native_probes: tuple = ()
    # ---- citizenship surfaces the lint pins ----------------------------
    flag_namespace: Optional[str] = None  # KNOWN_FLAGS prefix, e.g. "spread."
    endpoint: Optional[str] = None        # serve route, e.g. "/query/spread"
    parity_target: Optional[str] = None   # Makefile bit-exactness gate
    doc_token: Optional[str] = None       # must appear in ARCHITECTURE.md
    obs_token: Optional[str] = None       # metric in Grafana/alerts surface


FAMILIES: dict[str, SketchFamily] = {}

_BY_SNAPSHOT: dict[str, SketchFamily] = {}
_BY_CHECKPOINT: dict[str, SketchFamily] = {}
_BY_PAYLOAD: dict[str, SketchFamily] = {}
_RESOLVED: dict[str, Any] = {}


def register(fam: SketchFamily) -> SketchFamily:
    if fam.kind in FAMILIES:
        raise ValueError(f"sketch family {fam.kind!r} registered twice")
    FAMILIES[fam.kind] = fam
    if fam.snapshot_kind:
        _BY_SNAPSHOT[fam.snapshot_kind] = fam
    if fam.checkpoint_kind:
        _BY_CHECKPOINT[fam.checkpoint_kind] = fam
    for pk in fam.payload_kinds:
        _BY_PAYLOAD[pk] = fam
    return fam


def families() -> tuple[SketchFamily, ...]:
    """All registered families, in registration order (deterministic —
    dispatch loops built on this stay bit-stable run to run)."""
    return tuple(FAMILIES.values())


def family(kind: str) -> SketchFamily:
    try:
        return FAMILIES[kind]
    except KeyError:
        raise KeyError(
            f"unknown sketch family kind {kind!r} (registered: "
            f"{sorted(FAMILIES)}; see flow_pipeline_tpu/families/"
            "registry.py)") from None


def family_for_snapshot(snapshot_kind: str) -> Optional[SketchFamily]:
    """Family owning a ``model.snapshot_kind`` tag, else None (callers
    keep their own unknown-kind handling — a loud skip at restore, a
    TypeError at capture)."""
    return _BY_SNAPSHOT.get(snapshot_kind)


def family_for_payload(payload_kind: str) -> Optional[SketchFamily]:
    return _BY_PAYLOAD.get(payload_kind)


def family_for_checkpoint(checkpoint_kind: str) -> Optional[SketchFamily]:
    """Family owning a checkpoint "kind" tag, else None (unknown tags
    skip restore silently — the pre-registry fall-through)."""
    return _BY_CHECKPOINT.get(checkpoint_kind)


def resolve(ref: str) -> Any:
    """Import-and-cache a "module:attr" hook reference."""
    hit = _RESOLVED.get(ref)
    if hit is None:
        mod, _, attr = ref.partition(":")
        hit = getattr(importlib.import_module(mod), attr)
        _RESOLVED[ref] = hit
    return hit


def hook(fam: SketchFamily, name: str) -> Any:
    """Resolved hook callable for one family field, or None when the
    family does not participate in that surface."""
    ref = getattr(fam, name)
    return resolve(ref) if ref else None


def audit_attrs() -> tuple[tuple[str, str], ...]:
    """(kind, HostGroupPipeline audit attribute) for every family with
    a shadow auditor — the guard pause/serve merge loops iterate this
    instead of naming `audit` and `spread_audit` one by one."""
    return tuple((f.kind, f.audit_attr) for f in FAMILIES.values()
                 if f.audit_attr)


def delta_planes(payload_kind: str) -> tuple:
    """(state key, planes-first?) plane specs for one gateway snapshot
    family kind; () for unregistered kinds (the gateway falls back to
    full-ship, never guesses a diff layout)."""
    fam = _BY_PAYLOAD.get(payload_kind)
    return fam.delta_planes if fam else ()


# ---------------------------------------------------------------------------
# The registry proper. Keyword literals ONLY — tools/flowlint/
# rules_family.py parses these calls with ast and enforces both-ways
# coverage; computed values would blind it.
# ---------------------------------------------------------------------------

register(SketchFamily(
    kind="hh",
    snapshot_kind="windowed_hh",
    checkpoint_kind="windowed_hh",
    payload_kinds=("hh", "hh_inv"),
    merge_monoid="u64-sum",
    ranked=True,
    state_attr="state",
    payload="flow_pipeline_tpu.mesh.codec:hh_payload",
    merge="flow_pipeline_tpu.mesh.merge:merge_hh",
    top_rows="flow_pipeline_tpu.mesh.merge:hh_top_rows",
    serve_capture="flow_pipeline_tpu.serve.publisher:hh_view_parts",
    serve_capture_merged="flow_pipeline_tpu.serve.publisher:hh_merged_view",
    checkpoint_save="flow_pipeline_tpu.engine.worker:save_hh_state",
    checkpoint_restore="flow_pipeline_tpu.engine.worker:restore_hh_state",
    delta_planes=(("cms", False),),
    audit_attr="audit",
    audit_class="flow_pipeline_tpu.obs.audit:SketchAudit",
    native_probes=(("fused", "ff_fused_update", "r10"),
                   ("invsketch", "hs_inv_update", "r16")),
    flag_namespace="hh.",
    endpoint="/query/topk",
    parity_target="invertible-parity",
    doc_token="`hh`",
    obs_token="sketch_hh_recall",
))

register(SketchFamily(
    kind="wagg",
    snapshot_kind=None,
    checkpoint_kind="window_agg",
    payload_kinds=("wagg",),
    merge_monoid="u64-sum",
    ranked=False,
    state_attr=None,
    payload="flow_pipeline_tpu.mesh.codec:wagg_payload",
    merge="flow_pipeline_tpu.mesh.merge:merge_wagg",
    top_rows="flow_pipeline_tpu.models.window_agg:wagg_rows",
    serve_capture=None,
    serve_capture_merged=None,
    checkpoint_save="flow_pipeline_tpu.engine.worker:save_wagg_state",
    checkpoint_restore="flow_pipeline_tpu.engine.worker:restore_wagg_state",
    delta_planes=(),
    audit_attr=None,
    audit_class=None,
    native_probes=(),
    flag_namespace="window.",
    endpoint="/query/range",
    parity_target="mesh-parity",
    doc_token="`wagg`",
    obs_token="flow_commit_watermark_seconds",
))

register(SketchFamily(
    kind="dense",
    snapshot_kind="windowed_dense",
    checkpoint_kind="windowed_dense",
    payload_kinds=("dense",),
    merge_monoid="i64-sum",
    ranked=True,
    state_attr="totals",
    payload="flow_pipeline_tpu.mesh.codec:dense_payload",
    merge="flow_pipeline_tpu.mesh.merge:merge_dense",
    top_rows="flow_pipeline_tpu.mesh.merge:dense_top_rows",
    serve_capture="flow_pipeline_tpu.serve.publisher:dense_view_parts",
    serve_capture_merged="flow_pipeline_tpu.serve.publisher:dense_merged_view",
    checkpoint_save="flow_pipeline_tpu.engine.worker:save_dense_state",
    checkpoint_restore="flow_pipeline_tpu.engine.worker:restore_dense_state",
    delta_planes=(),
    audit_attr=None,
    audit_class=None,
    native_probes=(),
    flag_namespace="sketch.",
    endpoint="/query/topk",
    parity_target="fused-parity",
    doc_token="`dense`",
    obs_token="serve_queries_total",
))

register(SketchFamily(
    kind="spread",
    snapshot_kind="windowed_spread",
    checkpoint_kind="windowed_spread",
    payload_kinds=("spread",),
    merge_monoid="max",
    ranked=True,
    state_attr="state",
    payload="flow_pipeline_tpu.mesh.codec:spread_payload",
    merge="flow_pipeline_tpu.mesh.merge:merge_spread",
    top_rows="flow_pipeline_tpu.mesh.merge:spread_top_rows",
    serve_capture="flow_pipeline_tpu.serve.publisher:spread_view_parts",
    serve_capture_merged="flow_pipeline_tpu.serve.publisher:spread_merged_view",
    checkpoint_save="flow_pipeline_tpu.engine.worker:save_spread_state",
    checkpoint_restore="flow_pipeline_tpu.engine.worker:restore_spread_state",
    delta_planes=(("regs", True),),
    audit_attr="spread_audit",
    audit_class="flow_pipeline_tpu.obs.audit:SpreadAudit",
    native_probes=(("spread", "hs_spread_update", "r21"),),
    flag_namespace="spread.",
    endpoint="/query/spread",
    parity_target="spread-parity",
    doc_token="`spread`",
    obs_token="spread_top_max",
))
