"""flowguard: end-to-end backpressure and deterministic overload shedding.

See :mod:`flow_pipeline_tpu.guard.controller` for the design story.
"""

from .controller import (GUARD_METRICS, GUARD_SAMPLE_SEED, GuardConfig,
                         GuardController, admission_mask, flow_key_lanes,
                         register_guard_metrics)

__all__ = [
    "GUARD_METRICS",
    "GUARD_SAMPLE_SEED",
    "GuardConfig",
    "GuardController",
    "admission_mask",
    "flow_key_lanes",
    "register_guard_metrics",
]
