"""flowguard: the per-stage overload controller.

Everything before r20 made the system exact when something *dies*;
nothing handled the other production failure shape — everything alive
but drowning. A slow sink or offered load past capacity meant unbounded
queue growth, unbounded watermark lag, and eventually OOM. flowguard
closes that hole with a deterministic degradation ladder:

- **Level 0** (the normal state): exact. Output is bit-identical to the
  oracle — the guard machinery costs one attribute read per batch when
  disarmed and one hash-free observe when armed-but-idle.
- **Level 1**: drop optional work. The sketchwatch audit cohort refresh
  pauses and the flowtrace ring stops recording — the instruments go
  quiet before any data does.
- **Level >= 2**: deterministic hash-sampled admission at keep rate
  ``1/2^(level-1)``. The shed set is a PURE FUNCTION of (flow key,
  level): the same splitmix multiply-shift hash family sketchwatch uses
  (obs/audit.py), minted from a DIFFERENT protocol seed so the shed set
  is uncorrelated with the audit cohort — the audit keeps measuring the
  extra error the sampling introduces, live. Admitted rows carry the
  scale factor in their ``sampling_rate`` column, which both the CMS
  (``scale_col``) and the window aggregator (``*_scaled`` outputs, the
  rate key lane) already honor — scaled estimates stay unbiased.

The ladder is driven by watermark lag (bus produce time -> worker pick
up, the age of the backlog head): past the ``-guard.lag`` budget the
controller steps DOWN one level per dwell period; once lag re-enters
the hysteresis band (``hysteresis * budget``) it steps back UP, again
one level per dwell — no flapping, no cliff.

Shed is never silent: ``guard_shed_total{stage,reason}`` counts every
dropped flow/query, ``flow_guard_level`` gauges the active level, and
snapshot metadata records the sampling level the read side serves
under. ``-guard.lag=0`` (the default) disarms the ladder entirely.
"""

from __future__ import annotations

# flowlint: lock-checked
# (ladder transitions are serialized by _lock; `level` is additionally
# readable lock-free from the ingest group thread — a racy-but-monotone
# int read, same discipline as FAULTS.active)

import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..obs import REGISTRY, get_logger
from ..obs.audit import _lane_mults, _sample_hash

log = get_logger("guard")

# The admission-hash protocol seed. DELIBERATELY distinct from
# obs.audit.AUDIT_SAMPLE_SEED: the shed set must be uncorrelated with
# the audit cohort, so sketchwatch keeps an unbiased exact shadow of
# the keys that survive admission and MEASURES the sampling error
# instead of having its cohort shed first.
GUARD_SAMPLE_SEED = 0x6A4D_BA1A

_GUARD_MULTS = _lane_mults(16, GUARD_SAMPLE_SEED)

# Metric name/help specs live here once; StreamWorker registers them
# eagerly so /metrics carries every guard family (as zeros) on every
# worker — the deploy honesty tests resolve the overload panels and the
# OverloadShedding alert against this surface.
GUARD_METRICS = {
    "level": ("flow_guard_level",
              "active flowguard degradation-ladder level (0 = exact, "
              "1 = optional work dropped, >=2 = hash-sampled admission "
              "at keep rate 1/2^(level-1))"),
    "lag": ("flow_guard_lag_seconds",
            "watermark lag the guard controller last observed (bus "
            "produce time -> worker pickup, age of the backlog head)"),
    "shed": ("guard_shed_total",
             "flows/queries shed by flowguard (labels: stage, reason) "
             "— every admission drop and serve-path rejection counts "
             "here; nothing is dropped silently"),
    "transitions": ("guard_transitions_total",
                    "flowguard ladder level changes (label: "
                    "direction=down|up; down = degrading)"),
    "buffer_bytes": ("guard_buffer_bytes",
                     "bytes resident in a bounded ingest stage buffer "
                     "(label: stage) — memory is bounded by "
                     "construction; this is the live occupancy"),
}

_GUARD_GAUGES = frozenset({"level", "lag", "buffer_bytes"})


def register_guard_metrics() -> dict:
    """Register (or fetch) every flowguard metric family on the global
    registry. Idempotent; returns {spec key: metric}."""
    out = {}
    for key, spec in GUARD_METRICS.items():
        if key in _GUARD_GAUGES:
            out[key] = REGISTRY.gauge(*spec)
        else:
            out[key] = REGISTRY.counter(*spec)
    return out


def flow_key_lanes(columns) -> np.ndarray:
    """[N, 11] uint32 admission-key lanes for a batch's columns: the
    5-tuple (src_addr, dst_addr, src_port, dst_port, proto). The SAME
    lanes on every worker and every mesh member, so one flow sheds
    identically network-wide — per-member partials stay a monoid under
    sampling."""
    n = len(columns["proto"])
    lanes = np.empty((n, 11), dtype=np.uint32)
    lanes[:, 0:4] = columns["src_addr"]
    lanes[:, 4:8] = columns["dst_addr"]
    lanes[:, 8] = columns["src_port"]
    lanes[:, 9] = columns["dst_port"]
    lanes[:, 10] = columns["proto"]
    return lanes


def admission_mask(columns, shift: int) -> np.ndarray:
    """[N] bool: which rows survive admission at sampling shift ``s``
    (keep rate 1/2^s). A pure function of (flow key, s) — reproducible
    across reruns, processes, and mesh members. shift<=0 keeps all."""
    if shift <= 0:
        return np.ones(len(columns["proto"]), dtype=bool)
    h = _sample_hash(flow_key_lanes(columns), _GUARD_MULTS)
    return (h & np.uint32((1 << shift) - 1)) == np.uint32(0)


@dataclass(frozen=True)
class GuardConfig:
    """Ladder tuning. ``lag_budget`` <= 0 disarms the controller — the
    default, so every exact-parity path runs untouched."""

    lag_budget: float = 0.0   # seconds of watermark lag tolerated
    max_level: int = 6        # ladder ceiling (keep rate 1/32 at 6)
    hysteresis: float = 0.5   # step up when lag < hysteresis * budget
    dwell: float = 5.0        # min seconds between ladder transitions


class GuardController:
    """The degradation-ladder state machine for one worker.

    ``observe(lag)`` runs on the worker thread per batch (and with lag
    0.0 on idle polls, so recovery does not need traffic); ``level`` is
    read lock-free from the ingest group thread by the admission
    wrapper — a stale read sheds one batch at the previous level, which
    the scale factor still accounts for exactly.
    """

    def __init__(self, config: GuardConfig = GuardConfig()):
        self.config = config
        if config.max_level < 1:
            raise ValueError(
                f"guard max_level must be >= 1, got {config.max_level}")
        m = register_guard_metrics()
        self.m_level = m["level"]
        self.m_lag = m["lag"]
        self.m_shed = m["shed"]
        self.m_transitions = m["transitions"]
        # flowlint: unguarded -- transitions serialized by _lock; lock-free readers (group thread) see a racy-but-monotone int whose staleness is absorbed by the per-row scale factor
        self.level = 0
        # flowlint: unguarded -- the lock itself; bound once
        self._lock = threading.Lock()
        self._last_change = 0.0      # guarded-by: _lock
        self._shed_rows = 0          # guarded-by: _lock
        self._max_level_seen = 0     # guarded-by: _lock

    @property
    def armed(self) -> bool:
        return self.config.lag_budget > 0.0

    @property
    def sample_shift(self) -> int:
        """Admission sampling shift s at the current level (keep rate
        1/2^s): level 0 and 1 admit everything; level L>=2 is L-1."""
        return max(0, self.level - 1)

    @property
    def drop_optional(self) -> bool:
        """Level >= 1: audit cohort refresh and trace ring pause."""
        return self.level >= 1

    # ---- ladder ------------------------------------------------------------

    def observe(self, lag: float, now: Optional[float] = None) -> int:
        """Feed one watermark-lag measurement; returns the (possibly
        stepped) level. One transition per dwell period in either
        direction; recovery needs lag back INSIDE the hysteresis band,
        not merely under budget — no flapping at the boundary."""
        if not self.armed:
            return 0
        now = time.monotonic() if now is None else now
        self.m_lag.set(lag)
        cfg = self.config
        with self._lock:
            level = self.level
            if now - self._last_change < cfg.dwell:
                return level
            if lag > cfg.lag_budget and level < cfg.max_level:
                self.level = level + 1
                self._last_change = now
                self._max_level_seen = max(self._max_level_seen,
                                           self.level)
                new = self.level
                direction = "down"
            elif lag < cfg.hysteresis * cfg.lag_budget and level > 0:
                self.level = level - 1
                self._last_change = now
                new = self.level
                direction = "up"
            else:
                return level
        self.m_level.set(new)
        self.m_transitions.inc(direction=direction)
        log.warning("flowguard level %d -> %d (lag %.2fs, budget %.2fs)",
                    level, new, lag, cfg.lag_budget)
        return new

    # ---- admission ---------------------------------------------------------

    def admit(self, batch):
        """Deterministic hash-sampled admission for one FlowBatch at the
        current level. Returns (admitted batch, rows shed). The admitted
        batch keeps the FULL offset range (shed rows still commit — they
        were consumed and accounted, not lost), and its survivors'
        ``sampling_rate`` is multiplied by 2^shift so every downstream
        scale-aware estimate stays unbiased."""
        shift = self.sample_shift
        if shift <= 0 or len(batch) == 0:
            return batch, 0
        mask = admission_mask(batch.columns, shift)
        dropped = int(len(batch) - mask.sum())
        if dropped == 0:
            return batch, 0
        admitted = batch.take(mask)
        # absent-rate rows (rate 0) scale as rate 1 — the same
        # max(rate, 1) convention the HH scale plane applies
        sr = admitted.columns["sampling_rate"]
        np.maximum(sr, np.uint64(1), out=sr)
        sr *= np.uint64(1 << shift)
        self.m_shed.inc(dropped, stage="ingest", reason="admission")
        with self._lock:
            self._shed_rows += dropped
        return admitted, dropped

    def count_shed(self, n: int, stage: str, reason: str) -> None:
        """Account ``n`` shed items at a non-admission stage (the serve
        accept queue, a deadline miss). Never silent."""
        if n <= 0:
            return
        self.m_shed.inc(n, stage=stage, reason=reason)
        with self._lock:
            self._shed_rows += n

    # ---- snapshot metadata -------------------------------------------------

    def meta(self) -> dict:
        """JSON-safe guard state for snapshot/window metadata: readers
        can tell which sampling level the answer they hold was built
        under."""
        with self._lock:
            return {
                "level": self.level,
                "sample_shift": self.sample_shift,
                "max_level_seen": self._max_level_seen,
                "shed_total": self._shed_rows,
                "lag_budget": self.config.lag_budget,
            }
