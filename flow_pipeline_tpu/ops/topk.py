"""Device-resident top-K candidate table.

Heavy-hitter identity tracking with fixed-shape, sort-based merges — the
TPU-idiomatic replacement for the (inherently sequential) space-saving
algorithm. The table holds ``capacity`` (key, value-vector) rows; each batch
round merges the batch's unique keys into the table:

    concat(table, candidates) -> lexicographic sort by key
    -> segment-sum duplicate keys -> rank by primary value -> keep top C

Guarantee (Misra-Gries flavored): per-round dropped mass is bounded by the
rank-C value, so any key whose true total dominates survives rounds. The
paired CMS (ops.cms) provides count estimates with an eps*N bound, so the
table only needs to not lose identities — the "invertible sketch"
decomposition (candidate set + counter array) from the heavy-hitter
literature (see PAPERS.md).

Merging two tables (cross-chip, at window close) is the same op with the
second table as candidates — associative up to ties, so it rides an
all_gather + fold over the mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .segment import sort_groupby_float

# numpy scalar, NOT jnp: a module-level jnp constant would initialize the
# XLA backend at import time, which breaks jax.distributed.initialize
# (multi-host bootstrap must precede any backend init).
SENTINEL = np.uint32(0xFFFFFFFF)


def topk_init(capacity: int, key_width: int, planes: int):
    """Empty table: sentinel keys, zero values."""
    keys = jnp.full((capacity, key_width), SENTINEL, dtype=jnp.uint32)
    vals = jnp.zeros((capacity, planes), dtype=jnp.float32)
    return keys, vals


def topk_merge(table_keys, table_vals, cand_keys, cand_vals, cand_valid):
    """Merge candidate rows into the table; returns (keys, vals) of the same
    capacity, ranked by vals[:, 0] descending.

    table_keys: [C, W] uint32 (sentinel rows = empty slots)
    table_vals: [C, P] float32
    cand_keys:  [N, W] uint32 unique keys (e.g. from sort_groupby)
    cand_vals:  [N, P] values (summed per key); plane 0 is the ranking metric
    cand_valid: [N] bool
    """
    c = table_keys.shape[0]
    table_valid = jnp.any(table_keys != SENTINEL, axis=1)
    # The all-sentinel key tuple is UNREPRESENTABLE in this table: sentinel
    # keys mark empty slots, so admitting a real all-1s key (e.g. the ff..ff
    # IPv6 address as raw lanes) would let it steal a capacity slot while
    # being invisible to topk_extract and zeroed on the next merge. Drop it
    # here, explicitly — the exact aggregation path (ops.segment) still
    # counts it; only the approximate top-K table excludes this one key.
    cand_valid = cand_valid & jnp.any(cand_keys != SENTINEL, axis=1)
    all_keys = jnp.concatenate([table_keys, cand_keys.astype(jnp.uint32)], axis=0)
    all_vals = jnp.concatenate(
        [table_vals, cand_vals.astype(jnp.float32)], axis=0
    )
    all_valid = jnp.concatenate([table_valid, cand_valid], axis=0)

    uniq, sums, counts = sort_groupby_float(all_keys, all_vals, all_valid)

    real = counts > 0
    primary = jnp.where(real, sums[:, 0], -jnp.inf)
    top = jnp.argsort(-primary)[:c]
    new_keys = jnp.where(real[top][:, None], uniq[top], SENTINEL)
    new_vals = jnp.where(real[top][:, None], sums[top], 0.0)
    return new_keys, new_vals


def topk_merge_est(table_keys, table_vals, cand_keys, cand_sums, cand_est,
                   cand_valid):
    """topk_merge with space-saving admission: a key ALREADY in the table
    is incremented by its batch sums (``cand_sums``), while a NEW key
    enters with its CMS estimate (``cand_est``) — the estimate covers the
    key's pre-entry mass (the paired CMS counts every row of the stream),
    so table values upper-bound true totals with CMS-bounded error
    instead of silently under-counting late entrants. This is the
    admission rule of the space-saving algorithm, expressed as the same
    fixed-shape sort/segment merge (candidate and table keys are each
    unique, so every group has at most one row of each kind).

    Not for table-table folds (cross-chip window close): there both
    sides' values are already totals — use topk_merge, which sums.
    """
    c = table_keys.shape[0]
    p = table_vals.shape[1]
    table_valid = jnp.any(table_keys != SENTINEL, axis=1)
    cand_valid = cand_valid & jnp.any(cand_keys != SENTINEL, axis=1)
    all_keys = jnp.concatenate(
        [table_keys, cand_keys.astype(jnp.uint32)], axis=0)
    tz = jnp.zeros_like(table_vals)
    cz = jnp.zeros((cand_keys.shape[0], p), jnp.float32)
    # planes: [table mass P | batch sums P | entry est P | is_table 1]
    t_rows = jnp.concatenate(
        [table_vals, tz, tz, jnp.ones((c, 1), jnp.float32)], axis=1)
    c_rows = jnp.concatenate(
        [cz, cand_sums.astype(jnp.float32), cand_est.astype(jnp.float32),
         jnp.zeros((cand_keys.shape[0], 1), jnp.float32)], axis=1)
    all_vals = jnp.concatenate([t_rows, c_rows], axis=0)
    all_valid = jnp.concatenate([table_valid, cand_valid], axis=0)

    uniq, sums, counts = sort_groupby_float(all_keys, all_vals, all_valid)
    resident = sums[:, 3 * p] > 0
    vals = sums[:, :p] + jnp.where(
        resident[:, None], sums[:, p:2 * p], sums[:, 2 * p:3 * p])
    real = counts > 0
    primary = jnp.where(real, vals[:, 0], -jnp.inf)
    top = jnp.argsort(-primary)[:c]
    new_keys = jnp.where(real[top][:, None], uniq[top], SENTINEL)
    new_vals = jnp.where(real[top][:, None], vals[top], 0.0)
    return new_keys, new_vals


def topk_extract(table_keys, table_vals, k: int):
    """Host-facing: top-k rows (already ranked). Returns (keys, vals, valid)."""
    valid = jnp.any(table_keys != SENTINEL, axis=1)
    return table_keys[:k], table_vals[:k], valid[:k]
