"""Per-bucket EWMA baselines (mean + variance) for anomaly detection.

Keys are hashed into a fixed bucket array (power-of-two size, 128-aligned);
per-window rates are scatter-added, then the window close folds the rate
into exponentially weighted mean/variance per bucket. z-scores against the
EW baseline drive the DDoS spike detector (BASELINE.json config #5:
"per-DstAddr EWMA + quantile-sketch on Packets").

State is a pair of [M] float32 arrays (mean, var) plus the in-progress
window's [M] rate accumulator — all psum/merge-friendly: rate accumulators
sum across shards; mean/var fold happens once per window on the merged rate.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..schema.keys import hash_words


def ewma_init(n_buckets: int):
    """(mean, var, initialized) arrays."""
    return (
        jnp.zeros(n_buckets, jnp.float32),
        jnp.zeros(n_buckets, jnp.float32),
        jnp.zeros(n_buckets, jnp.bool_),
    )


def bucket_of(keys, n_buckets: int, seed: int = 0x5EED):
    """[N, W] key lanes -> [N] int32 bucket ids."""
    return (hash_words(keys, seed=seed) % jnp.uint32(n_buckets)).astype(jnp.int32)


def rate_accumulate(rates, buckets, values, valid):
    """Scatter-add per-flow values into the window's per-bucket rate array."""
    v = jnp.where(valid, values.astype(jnp.float32), 0.0)
    return rates.at[buckets].add(v)


def ewma_fold(state, rates, alpha: float):
    """Close a window: fold observed per-bucket rates into the EW baseline.

    West's EW update: d = x - mean; mean += a*d; var = (1-a)*(var + a*d^2).
    Buckets never seen before initialize mean to their first rate (no
    cold-start alarm on the first observation).
    """
    mean, var, seen = state
    a = jnp.float32(alpha)
    d = rates - mean
    new_mean = jnp.where(seen, mean + a * d, rates)
    new_var = jnp.where(seen, (1.0 - a) * (var + a * d * d), jnp.zeros_like(var))
    new_seen = seen | (rates > 0)
    return new_mean, new_var, new_seen


def zscores(state, rates, min_sigma: float = 1.0, rel_sigma: float = 0.25):
    """Per-bucket z-score of the current window's rate vs the EW baseline.

    The denominator is floored at both ``min_sigma`` (absolute; quiet
    buckets) and ``rel_sigma * mean`` (relative; before the EW variance has
    converged, natural fluctuation scales with the mean — without this floor
    the first few windows alarm on noise)."""
    mean, var, seen = state
    sigma = jnp.maximum(jnp.sqrt(var), jnp.float32(min_sigma))
    sigma = jnp.maximum(sigma, jnp.float32(rel_sigma) * mean)
    z = (rates - mean) / sigma
    return jnp.where(seen, z, 0.0)
