"""Count-min sketch ops.

The CMS is the framework's replacement for ClickHouse's SummingMergeTree
when key cardinality is too high for exact aggregation (the 38-byte 5-tuple
space; ref north star: BASELINE.json). Layout is TPU-first:

- counts: [planes, depth, width] float32. ``planes`` are the metrics
  (bytes, packets, count). float32 keeps scatter-adds on native lanes;
  integer sums stay exact below 2^24 per cell per batch and the parity gate
  is 1%, far above float32's relative error. ``width`` should be a multiple
  of 128 (lane tiling).
- Updates are pre-aggregated: callers first collapse the batch to unique
  keys (ops.segment.sort_groupby), so each key touches each depth row once
  per batch. This slashes scatter conflicts and makes conservative update
  meaningful within a batch.
- Merge across chips is element-wise sum (count-min is a commutative
  monoid), i.e. a plain ``psum`` over the mesh — the ICI replacement for
  ClickHouse's merge-time partial-sum combine.

Bucket choice per depth uses the murmur3 word-lane hash (schema.keys) with
a distinct seed per row.
"""

from __future__ import annotations

# flowlint: uint64-exact
# (bucket hashing must stay exact unsigned arithmetic — a signed cast
# here skews every estimate; see docs/STATIC_ANALYSIS.md)

from functools import partial

import jax
import jax.numpy as jnp

from ..schema.keys import hash_words


def cms_init(planes: int, depth: int, width: int) -> jnp.ndarray:
    """Fresh sketch. width should be a multiple of 128."""
    return jnp.zeros((planes, depth, width), dtype=jnp.float32)


def cms_buckets(keys, depth: int, width: int):
    """Per-depth bucket indices for key word-lanes.

    keys: [N, W] uint32 lanes. Returns [depth, N] int32 in [0, width).
    Seeds 0..depth-1 give independent rows."""
    cols = []
    for d in range(depth):  # depth is small + static: unrolled
        h = hash_words(keys, seed=d)
        # flowlint: disable=uint64-discipline -- bucket INDICES in [0, width < 2^31), not counters; scatter wants int32
        cols.append((h % jnp.uint32(width)).astype(jnp.int32))
    return jnp.stack(cols, axis=0)


def cms_add(counts, keys, values, valid=None):
    """Linear (mergeable) update with pre-aggregated per-key values.

    counts: [P, D, W] float32 sketch.
    keys:   [N, W_k] uint32 unique key lanes.
    values: [N, P] per-key addends (cast to float32).
    valid:  [N] bool mask (e.g. rows < n_groups from sort_groupby).
    """
    p, d, w = counts.shape
    buckets = cms_buckets(keys, d, w)  # [D, N]
    vals = values.astype(jnp.float32)
    if valid is not None:
        vals = jnp.where(valid[:, None], vals, 0.0)
    for di in range(d):
        # [P, N] scatter-add into row di; XLA lowers to sorted scatter.
        counts = counts.at[:, di, buckets[di]].add(vals.T)
    return counts


def cms_query(counts, keys):
    """Point estimate: min over depth rows. Returns [N, P] float32 (upper
    bound of the true sums for linear updates)."""
    p, d, w = counts.shape
    buckets = cms_buckets(keys, d, w)  # [D, N]
    ests = []
    for di in range(d):
        ests.append(counts[:, di, buckets[di]])  # [P, N]
    return jnp.min(jnp.stack(ests, axis=0), axis=0).T  # [N, P]


def cms_add_conservative(counts, keys, values, valid=None):
    """Conservative update: raise each cell only to (current min estimate +
    addend). Tighter estimates than linear add; still an upper bound. Merge
    by + remains a valid upper bound but loses the CU tightness.

    Same shapes as cms_add. Keys must be unique within the call (use
    sort_groupby first) — duplicate keys would under-count.
    """
    p, d, w = counts.shape
    buckets = cms_buckets(keys, d, w)  # [D, N]
    vals = values.astype(jnp.float32)
    if valid is not None:
        vals = jnp.where(valid[:, None], vals, 0.0)
    # current estimate before update
    est = cms_query(counts, keys)  # [N, P]
    target = est + vals  # [N, P] the CU ceiling for this key
    for di in range(d):
        # cell must become at least `target`, but never decrease.
        counts = counts.at[:, di, buckets[di]].max(target.T)
    return counts


def cms_merge(*sketches):
    """Combine per-shard sketches (element-wise sum)."""
    out = sketches[0]
    for s in sketches[1:]:
        out = out + s
    return out


def cms_relative_error(depth: int, width: int, total: float) -> float:
    """Standard CMS guarantee: err <= e/width * total with prob 1-e^-depth."""
    import math

    return math.e / width * total
