"""Log-bucket quantile histogram (DDSketch-flavored).

Relative-error quantiles over a stream of non-negative values, as a fixed
[B] counter array: value v lands in bucket floor(log_gamma(v)) + offset,
clamped. Guarantees quantile estimates within a multiplicative
(1 +/- rel_err) like DDSketch, with a TPU-trivial layout: updating is a
scatter-add, merging is +, querying is a cumsum scan (host or device).

Used by the DDoS model to turn "is this dst's packet rate extreme?" into a
quantile threshold over the population of per-bucket rates.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np


class QuantileSketchSpec:
    """Static parameters: relative error -> gamma and bucket count."""

    def __init__(self, rel_err: float = 0.01, max_value: float = 1e12, n_buckets: int | None = None):
        self.rel_err = rel_err
        self.gamma = (1 + rel_err) / (1 - rel_err)
        self.log_gamma = math.log(self.gamma)
        # bucket 0 holds zeros/sub-1 values; buckets 1.. hold log ranges
        need = int(math.ceil(math.log(max_value) / self.log_gamma)) + 2
        self.n_buckets = n_buckets or need

    def init(self):
        return jnp.zeros(self.n_buckets, jnp.float32)

    def bucket_of(self, values):
        """[N] values -> [N] int32 bucket ids (device-safe)."""
        v = jnp.maximum(values.astype(jnp.float32), 1e-9)
        idx = jnp.ceil(jnp.log(v) / jnp.float32(self.log_gamma)).astype(jnp.int32) + 1
        idx = jnp.where(values <= 1.0, 1, idx)  # [0,1] -> bucket 1
        idx = jnp.where(values <= 0.0, 0, idx)  # zeros -> bucket 0
        return jnp.clip(idx, 0, self.n_buckets - 1)

    def add(self, hist, values, weights=None, valid=None):
        w = jnp.ones_like(values, jnp.float32) if weights is None else weights.astype(jnp.float32)
        if valid is not None:
            w = jnp.where(valid, w, 0.0)
        return hist.at[self.bucket_of(values)].add(w)

    def value_of_bucket(self, idx):
        """Representative (upper-bound) value of bucket idx (numpy/host)."""
        idx = np.asarray(idx)
        val = self.gamma ** (idx.astype(np.float64) - 1)
        return np.where(idx <= 0, 0.0, np.where(idx == 1, 1.0, val))

    def quantile(self, hist, q: float) -> float:
        """Host-side quantile query: smallest bucket value covering q mass."""
        h = np.asarray(hist, dtype=np.float64)
        total = h.sum()
        if total <= 0:
            return 0.0
        cum = np.cumsum(h)
        idx = int(np.searchsorted(cum, q * total, side="left"))
        idx = min(idx, self.n_buckets - 1)
        return float(self.value_of_bucket(np.array([idx]))[0])
