"""Device ops: the TPU compute kernels of the framework.

Everything here is jit-safe, static-shape, 32-bit-lane code. The design
replaces ClickHouse's C++ aggregation engine (the reference's only "native
kernel", ref: compose/clickhouse/create.sh:70-110) with XLA/Pallas:

- ``segment``   sort-based exact groupby (lexicographic multi-key lax.sort
                + segment reductions) — the workhorse behind exact windowed
                aggregation and candidate extraction
- ``cms``       count-min sketch update/query/merge (+ conservative update)
- ``topk``      device-resident top-K candidate table (space-saving style
                merge with bounded error)
- ``ewma``      per-bucket EWMA for anomaly baselines
- ``quantile``  log-bucket histogram (DDSketch-flavored) quantiles
"""

from .segment import sort_groupby
from .cms import (
    cms_init,
    cms_add,
    cms_add_conservative,
    cms_query,
    cms_merge,
    cms_buckets,
)
from .topk import topk_init, topk_merge, topk_extract
from .ewma import ewma_init, ewma_fold, zscores, bucket_of, rate_accumulate
from .quantile import QuantileSketchSpec

__all__ = [
    "sort_groupby",
    "cms_init",
    "cms_add",
    "cms_add_conservative",
    "cms_query",
    "cms_merge",
    "cms_buckets",
    "topk_init",
    "topk_merge",
    "topk_extract",
    "ewma_init",
    "ewma_fold",
    "zscores",
    "bucket_of",
    "rate_accumulate",
    "QuantileSketchSpec",
]
