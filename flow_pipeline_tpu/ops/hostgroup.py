"""Host-side exact groupby for CPU-backend deployments.

On a TPU the fused per-batch step pre-aggregates with the device sort
network (ops.segment / engine.fused) — the idiomatic choice there, since
host<->HBM round trips cost more than the sort. On a CPU-only box the
trade inverts: the "device" IS the host, XLA:CPU lowers ``lax.sort`` to a
single-threaded comparison sort (~11 ms for 32k rows x 2 hash lanes on
one core, measured), while numpy's introsort over one u64 hash lane does
the same grouping in ~0.6 ms. So the CPU engine groups HERE, in numpy,
and ships only the compact group tables to the XLA step (CMS updates,
top-K table merges, dense scatters) — engine.hostfused wires it up.

Exactness: grouping identity starts from the 64-bit key hash (same
constants as ops.segment.hash_lanes' pair, composed into one u64), but
unlike the device path the result is ALWAYS exact — a full-key
verification pass catches hash collisions and re-sorts lexicographically
(numpy has no static-shape constraint, so the fallback is synchronous
and cheap instead of a deferred device flag).
"""

from __future__ import annotations

import numpy as np

# Same decorrelated multiplier/seed pairs as ops.segment (_HASH_MULT /
# _HASH_SEED) so host and device grouping hash identically — not load-
# bearing (each path verifies or flags its own collisions) but it keeps
# cross-path debugging sane.
_MULTS = (np.uint32(0x9E3779B1), np.uint32(0x85EBCA77))
_SEEDS = (np.uint32(0x2545F491), np.uint32(0x27220A95))


def hash_u64(lanes: np.ndarray) -> np.ndarray:
    """[N, W] uint32 key lanes -> [N] uint64 murmur-style hash.

    Two independent 32-bit mixes (rotl-13 lane fold + fmix32 finalizer,
    mirroring ops.segment.hash_lanes) packed high/low into one u64 so a
    single ``np.argsort`` orders rows by the full 64-bit identity.
    """
    n, w = lanes.shape
    out = []
    with np.errstate(over="ignore"):  # uint32 wraparound is the algorithm
        for mult, seed in zip(_MULTS, _SEEDS):
            h = np.full(n, seed, np.uint32)
            for i in range(w):
                h = (h ^ lanes[:, i]) * mult
                h = (h << np.uint32(13)) | (h >> np.uint32(19))
            h ^= h >> np.uint32(16)
            h *= np.uint32(0x85EBCA6B)
            h ^= h >> np.uint32(13)
            h *= np.uint32(0xC2B2AE35)
            h ^= h >> np.uint32(16)
            out.append(h)
    return (out[0].astype(np.uint64) << np.uint64(32)) | out[1]


def native_group_available() -> bool:
    """Whether the native hash-group kernel (native.hash_group: same
    64-bit hash, radix sort + collision verify in one C pass) can serve
    as grouping backend. Callers opt in per call via ``native=True``
    (--ingest.native_group); the pure-numpy path stays the reference
    implementation the oracle tests pin down."""
    from .. import native

    return native.group_available()


def _empty_groups(w: int, planes: list[np.ndarray]):
    return (np.zeros((0, w), np.uint32),
            [np.zeros((0,) + p.shape[1:],
                      np.float64 if np.issubdtype(p.dtype, np.floating)
                      else np.uint64) for p in planes],
            np.zeros(0, np.int64))


def _lex_regroup(lanes: np.ndarray):
    """Exact lexicographic grouping — the 64-bit-collision fallback."""
    n = lanes.shape[0]
    perm = np.lexsort(lanes.T[::-1])
    sl = lanes[perm]
    boundary = np.empty(n, dtype=bool)
    boundary[0] = True
    np.any(sl[1:] != sl[:-1], axis=1, out=boundary[1:])
    return perm, np.flatnonzero(boundary)


def grouping_perm(lanes: np.ndarray, exact: bool, h: np.ndarray = None,
                  native: bool = False):
    """Row permutation + group starts for hash grouping of ``lanes``.

    The factored-out heart of group_by_key, reused by the sharded path
    (ingest.shard, which precomputes ``h`` per shard) and anything else
    that wants the grouping without the sums. Returns (perm, starts).
    """
    n = lanes.shape[0]
    if native and h is None:
        from .. import native as native_lib

        if native_lib.group_available():  # else: numpy fallback below
            perm, starts, collided = native_lib.hash_group(lanes)
            if exact and collided:
                return _lex_regroup(lanes)
            return perm, starts
    if h is None:
        h = hash_u64(lanes)
    perm = np.argsort(h)  # introsort; stability irrelevant (identity = hash)
    sh = h[perm]
    boundary = np.empty(n, dtype=bool)
    boundary[0] = True
    np.not_equal(sh[1:], sh[:-1], out=boundary[1:])
    starts = np.flatnonzero(boundary)
    if exact:
        # verify every row against its group's representative key; fall
        # back to the full lexicographic sort on a 64-bit hash collision
        # (~n^2/2^65 per batch) — exactness is unconditional on this path
        sl = lanes[perm]
        seg = np.cumsum(boundary) - 1
        if (sl != sl[starts][seg]).any():
            return _lex_regroup(lanes)
    return perm, starts


def reduce_groups(lanes: np.ndarray, planes: list[np.ndarray],
                  perm: np.ndarray, starts: np.ndarray):
    """(uniq, sums, counts) for a grouping permutation from grouping_perm.

    Each plane is summed per group with ``np.add.reduceat`` in float64
    (floating inputs) or uint64 (integer inputs) — callers cast the
    results down themselves."""
    n = perm.shape[0]
    uniq = lanes[perm[starts]]
    counts = np.diff(np.append(starts, n)).astype(np.int64)
    sums = []
    for p in planes:
        acc_dtype = (np.float64 if np.issubdtype(p.dtype, np.floating)
                     else np.uint64)
        sums.append(np.add.reduceat(p[perm].astype(acc_dtype), starts,
                                    axis=0))
    return uniq, sums, counts


def group_by_key(lanes: np.ndarray, planes: list[np.ndarray],
                 exact: bool = True, native: bool = False):
    """Groupby-sum of ``planes`` by row-tuples of ``lanes``.

    Args:
      lanes:  [N, W] uint32 key lanes.
      planes: list of [N] or [N, P] arrays, summed per group
              (see reduce_groups for the accumulator dtypes).
      exact:  verify every row against its group's representative key and
              fall back to a full lexicographic sort on a 64-bit hash
              collision (~n^2/2^65 per batch). Exactness-contract callers
              (flows_5m) keep the default; sketch callers pass False and
              accept the same merge-two-tuples failure mode their device
              twin (ops.segment.hash_groupby_float) documents — skipping
              the verify saves the [N, W] gather+compare (~15% of the
              groupby at 12 lanes).
      native: use the C hash-group kernel when built (collision verify is
              free there, so ``exact`` costs nothing extra); silently
              numpy when the library is missing — callers gate defaults
              on native_group_available().

    Returns (uniq [G, W] uint32, sums list matching ``planes``,
    counts [G] int64). Group order is hash order (arbitrary but
    deterministic); no consumer in this framework orders by key.
    """
    n, w = lanes.shape
    if n == 0:
        return _empty_groups(w, planes)
    perm, starts = grouping_perm(lanes, exact, native=native)
    return reduce_groups(lanes, planes, perm, starts)


def select_lanes(key_cols: tuple, widths: dict[str, int],
                 subset: tuple) -> list[int]:
    """Lane indices of ``subset`` columns inside the concatenated lane
    layout of ``key_cols`` (addresses occupy ``widths[name]`` lanes).
    Raises KeyError when a subset column is absent — callers decide
    between cascading from a parent group table and grouping raw rows."""
    offsets = {}
    off = 0
    for name in key_cols:
        offsets[name] = off
        off += widths[name]
    out: list[int] = []
    for name in subset:
        start = offsets[name]  # KeyError -> not a subset
        out.extend(range(start, start + widths[name]))
    return out
