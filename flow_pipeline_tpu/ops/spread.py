"""Distinct-count (spread) sketch ops — the jnp twin of the flowspread
family (-spread.enabled).

flowspread answers "how many DISTINCT elements did this key touch?" —
the cardinality companion to the volume sketches: superspreaders
(src -> distinct dst addrs) and port scans (src -> distinct dst ports).
The reference points are the streaming spread top-K surface of
PAPERS.md 2511.16797 and the compact register layouts of 2504.16896;
the layout here is a CMS-of-HLLs over the estate's murmur3 bucket
discipline:

    regs: [depth, width, m] uint8      (m registers per bucket)
    bucket_d(key) = hash_words(key_lanes, seed=d) % width   (ops.cms twin)
    r             = hash_words(elem_lanes, SPREAD_REG_SEED) % m
    rho           = clz32(hash_words(elem_lanes, SPREAD_RHO_SEED)) + 1
    update:  regs[d, bucket_d, r] = max(regs[d, bucket_d, r], rho)

Every update is an integer element-wise max, which makes the state a
commutative, associative, IDEMPOTENT monoid:

  - merge across shards/workers is element-wise u8 max — exact by
    construction (max(max(A,B),C) = max over the union), the spread
    mirror of the CMS u64 sum monoid;
  - update order cannot change the state, and duplicate elements are
    free (idempotence), so pre-grouping the batch to unique
    (key, element) pairs is bit-identical to raw row-at-a-time updates;
  - all arithmetic is uint32 hashing + uint8 max — no floats in the
    state, so the three twins (this module, hostsketch/engine.py
    np_spread_*, native hs_spread_update) are trivially bit-exact and,
    unlike ops.invsketch, NO x64 mode is needed.

Estimation (``spread_estimate``) is decode-at-read, host-side float64:
standard HLL harmonic mean with linear-counting small-range correction,
then min over depth rows (each row is an independent estimate; min
bounds bucket-collision inflation, the cardinality analogue of the
count-min min). Only the u8 register state needs three-way parity —
every serve path (worker, mesh coordinator, delta-fed gateway) decodes
through this ONE numpy function, so byte-identical registers give
byte-identical /query/spread answers.
"""

from __future__ import annotations

# flowlint: uint64-exact
# (register updates are pure uint32 hash -> uint8 max arithmetic; a
# signed cast or float promotion breaks three-way twin parity)
# flowlint: lock-checked
# (pure functions over immutable jnp arrays — no shared state, no
# locks; the marker pins that discipline machine-checked)

import jax.numpy as jnp
import numpy as np

from ..schema.keys import hash_words
from .cms import cms_buckets

# Element-hash protocol constants — mirrored bit-for-bit by
# hostsketch/engine.py np_spread_update and native hs_spread_update.
# Both are far outside the per-depth bucket seed range 0..depth-1, so
# the register-index and rho streams are independent of the bucket rows.
SPREAD_REG_SEED = 0x9E3779B9
SPREAD_RHO_SEED = 0x85EBCA6B

# rho for a zero hash: all 32 bits "leading zeros" + 1. With uint8
# registers saturation is unreachable (rho <= 33 << 255) but merge/max
# stays well-defined at 255 anyway (tests pin the edge).
SPREAD_RHO_ZERO = 33


def spread_init(depth: int, width: int, m: int) -> jnp.ndarray:
    """Fresh register planes: [depth, width, m] uint8 zeros."""
    return jnp.zeros((depth, width, m), dtype=jnp.uint8)


def _bit_length_u32(h):
    """Vectorized integer bit_length of uint32 (0 -> 0), by binary
    search over shifts — identical integer steps in all three twins."""
    h = h.astype(jnp.uint32)
    n = jnp.zeros(h.shape, dtype=jnp.uint32)
    for shift in (16, 8, 4, 2, 1):
        big = (h >> jnp.uint32(shift)) != 0
        n = jnp.where(big, n + jnp.uint32(shift), n)
        h = jnp.where(big, h >> jnp.uint32(shift), h)
    return n + jnp.where(h != 0, jnp.uint32(1), jnp.uint32(0))


def spread_update(regs, keys, elems, valid=None):
    """Scatter-max update with (key, element) rows.

    regs:  [D, W, m] uint8 register planes.
    keys:  [N, W_k] uint32 key lanes.
    elems: [N, W_e] uint32 element lanes (counted dimension).
    valid: [N] bool mask (padded rows contribute rho=0, a no-op under
           max since registers are >= 0).
    """
    d, w, m = regs.shape
    buckets = cms_buckets(keys, d, w)  # [D, N] int32
    # flowlint: disable=uint64-discipline -- register INDICES in [0, m < 2^31); scatter wants int32
    r = (hash_words(elems, seed=SPREAD_REG_SEED)
         % jnp.uint32(m)).astype(jnp.int32)
    h2 = hash_words(elems, seed=SPREAD_RHO_SEED)
    rho = (jnp.uint32(SPREAD_RHO_ZERO) - _bit_length_u32(h2)).astype(jnp.uint8)
    if valid is not None:
        rho = jnp.where(valid, rho, jnp.uint8(0))
    for di in range(d):
        regs = regs.at[di, buckets[di], r].max(rho)
    return regs


def spread_merge(*states):
    """Element-wise max fold — the exact merge monoid (commutative,
    associative, idempotent)."""
    out = states[0]
    for s in states[1:]:
        out = jnp.maximum(out, s)
    return out


# ---------------------------------------------------------------------------
# Decode — host-side float64, shared by EVERY serve path. Pure function
# of the u8 registers; numpy on purpose (deterministic float64 ops, no
# XLA fusion reordering), so identical registers decode to identical
# bytes on worker, mesh coordinator and gateway replicas alike.

def _hll_alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


_TWO32 = float(1 << 32)


def spread_estimate(rows: np.ndarray) -> np.ndarray:
    """HLL estimate per register row.

    rows: [..., m] uint8 registers. Returns [...] float64: harmonic-mean
    raw estimate with linear-counting small-range correction (E <= 2.5m
    with empty registers present) and the 32-bit large-range correction.
    """
    rows = np.asarray(rows, dtype=np.uint8)
    m = rows.shape[-1]
    alpha = _hll_alpha(m)
    # flowlint: disable=uint64-discipline -- u8 register VALUES in [0, 255] widened for negation; ldexp exponents, not counters
    inv = np.ldexp(1.0, -rows.astype(np.int64))  # exact 2^-reg in f64
    est = alpha * m * m / np.sum(inv, axis=-1)
    zeros = np.count_nonzero(rows == 0, axis=-1)
    small = (est <= 2.5 * m) & (zeros > 0)
    with np.errstate(divide="ignore"):
        lc = m * np.log(np.where(zeros > 0, m / np.maximum(zeros, 1), 1.0))
    est = np.where(small, lc, est)
    large = est > _TWO32 / 30.0
    est = np.where(large, -_TWO32 * np.log1p(-np.minimum(est, _TWO32 * 0.99999)
                                             / _TWO32), est)
    return est


def spread_decode(regs: np.ndarray, buckets: np.ndarray) -> np.ndarray:
    """Point estimates for pre-hashed buckets: min over depth rows.

    regs: [D, W, m] uint8. buckets: [D, N] integer bucket indices.
    Returns [N] float64 spread estimates.
    """
    regs = np.asarray(regs)
    d = regs.shape[0]
    ests = [spread_estimate(regs[di, np.asarray(buckets[di])])
            for di in range(d)]
    return np.minimum.reduce(ests)
