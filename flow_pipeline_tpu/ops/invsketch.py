"""Invertible-sketch ops — the jnp twin of the invertible heavy-hitter
family (-hh.sketch=invertible).

The invertible sketch (PAPERS.md 1910.10441's recover-keys-from-the-
sketch model, linearized onto the uint64-exact envelope) replaces the
whole admission path — top-K candidate table, admission CMS queries,
table prefilter — with ONE pure per-bucket fold over the same murmur3
buckets ops.cms uses:

    cms[p, d, b]    += addend_u64(vals[p])           (all planes, plain)
    keysum[d, b, l] += key[l] * cnt                  (wrap mod 2^64)
    keycheck[d, b]  += inv_key_hash(key) * cnt       (wrap mod 2^64)

Every cell is a plain uint64 wrap sum, so the state is LINEAR in the
stream: merge across shards/chips is an element-wise u64 sum, and heavy
keys are recovered from the sketch itself at window close by peeling
pure buckets (``inv_decode``). Conservative update is deliberately not
offered — decode divides by the count cell, which must be the bucket's
exact sum.

dtype note: the key-recovery planes are uint64 BY CONSTRUCTION (a lane
times a count does not fit any smaller exact dtype), so this module
requires jax x64 mode (``jax.experimental.enable_x64`` or the
``jax_enable_x64`` config) — the init helper raises a clear error
otherwise. The production home of this family is the host dataplane
(hostsketch/engine.py numpy twin + native/hostsketch.cc, reached
through ``ff_fused_update``); this jnp twin is the parity reference for
x64-enabled devices and tests/test_invsketch.py pins all three
bit-exact.
"""

from __future__ import annotations

# flowlint: uint64-exact
# (every plane is an exact unsigned monoid; one signed cast or float
# promotion and decode's divide-and-verify arithmetic is garbage)
# flowlint: lock-checked
# (pure functions over immutable jnp arrays — no shared state, no
# locks; the marker pins that discipline machine-checked)

import jax.numpy as jnp
import numpy as np

from ..schema.keys import hash_words
from .cms import cms_buckets

# Checksum-hash protocol constants — mirrored bit-for-bit by
# hostsketch/engine.py np_inv_key_hash and native inv_key_hash.
INV_HASH_SEED = 0x9E3779B97F4A7C15
INV_HASH_M1 = 0xFF51AFD7ED558CCD
INV_HASH_M2 = 0xC4CEB9FE1A85EC53

# Largest float32 strictly below 2^64 (hostsketch.state._U64_CAP's twin).
_U64_CAP = jnp.float32(1.8446742e19)


def _require_x64(arr) -> None:
    if arr.dtype != jnp.uint64:
        raise TypeError(
            "invertible-sketch planes must be uint64; enable jax x64 "
            "mode (jax.experimental.enable_x64) — without it jnp "
            "silently downcasts to uint32 and every cell past 2^32 is "
            f"garbage (got {arr.dtype})")


def inv_init(planes: int, depth: int, width: int, key_width: int):
    """Fresh invertible state: (cms [P, D, W], keysum [D, W, kw],
    keycheck [D, W]) — all uint64 zeros."""
    cms = jnp.zeros((planes, depth, width), dtype=jnp.uint64)
    _require_x64(cms)
    return (cms,
            jnp.zeros((depth, width, key_width), dtype=jnp.uint64),
            jnp.zeros((depth, width), dtype=jnp.uint64))


def inv_key_hash(keys) -> jnp.ndarray:
    """[N] uint64 checksum hash over [N, W] uint32 key lanes (wrap
    arithmetic mod 2^64)."""
    h = jnp.full(keys.shape[0], INV_HASH_SEED, dtype=jnp.uint64)
    _require_x64(h)
    for lane in range(keys.shape[1]):
        h = h ^ keys[:, lane].astype(jnp.uint64)
        h = h * jnp.uint64(INV_HASH_M1)
        h = h ^ (h >> jnp.uint64(33))
    h = h * jnp.uint64(INV_HASH_M2)
    h = h ^ (h >> jnp.uint64(29))
    return h


def _addend_u64(vals) -> jnp.ndarray:
    """f32 addends -> u64 with the hostsketch clamp (negatives/NaN
    contribute nothing; at/past 2^64 — inf included — clamps to
    UINT64_MAX exactly like native addend_u64)."""
    v = vals.astype(jnp.float32)
    v = jnp.where(jnp.isnan(v) | (v <= 0), jnp.float32(0.0), v)
    big = v >= jnp.float32(2.0**64)
    v = jnp.minimum(v, _U64_CAP)
    return jnp.where(big, jnp.uint64(0xFFFFFFFFFFFFFFFF),
                     v.astype(jnp.uint64))


def inv_update(cms, keysum, keycheck, keys, values, valid=None):
    """One pre-aggregated update step (jit-able): the jnp twin of
    np_inv_update / native hs_inv_update.

    keys [N, kw] uint32 unique key rows; values [N, P] addends with the
    count plane LAST; valid [N] bool mask. Returns the new
    (cms, keysum, keycheck)."""
    _require_x64(cms)
    p, d, w = cms.shape
    buckets = cms_buckets(keys, d, w)  # [D, N] — the CMS bucket scheme
    add = _addend_u64(values)
    if valid is not None:
        add = jnp.where(valid[:, None], add, jnp.uint64(0))
    cnt = add[:, -1]
    check = inv_key_hash(keys) * cnt
    lanes_u64 = keys.astype(jnp.uint64) * cnt[:, None]
    for di in range(d):
        cms = cms.at[:, di, buckets[di]].add(add.T)
        keysum = keysum.at[di, buckets[di], :].add(lanes_u64)
        keycheck = keycheck.at[di, buckets[di]].add(check)
    return cms, keysum, keycheck


def inv_merge(*states):
    """Combine per-shard invertible states: element-wise u64 wrap sum of
    every plane — the whole mesh-merge story for this family."""
    cms, keysum, keycheck = states[0]
    for c, ks, kc in states[1:]:
        cms = cms + c
        keysum = keysum + ks
        keycheck = keycheck + kc
    return cms, keysum, keycheck


def inv_decode(cms, keysum, keycheck):
    """Heavy-key recovery by peeling pure buckets — the jnp twin of
    np_inv_decode (vectorized purity scan per round in jnp; the
    peel-round loop is data-dependent and runs on the host). Returns
    numpy (keys [K, kw] u32, vals [K, P] u64) in canonical
    lexicographic key order — array-equal to the numpy and native
    decodes (the recoverable set is peel-order independent)."""
    _require_x64(cms)
    p, depth, width = cms.shape
    kw = keysum.shape[2]
    out_keys: list[np.ndarray] = []
    out_vals: list[np.ndarray] = []
    seen: set[bytes] = set()
    cand = np.asarray(cms[-1] != 0)
    while cand.any():
        cnt = cms[-1]  # [D, W]
        safe = jnp.where(cnt != 0, cnt, jnp.uint64(1))
        q = keysum // safe[:, :, None]  # [D, W, kw]
        ok = (cnt != 0) & (q * safe[:, :, None] == keysum).all(axis=2) \
            & (q <= jnp.uint64(0xFFFFFFFF)).all(axis=2)
        qk = q.astype(jnp.uint32)
        cols = jnp.arange(width, dtype=jnp.uint32)
        for di in range(depth):
            row_keys = qk[di]  # [W, kw]
            h = inv_key_hash(row_keys) * safe[di]
            ok = ok.at[di].set(
                ok[di] & (h == keycheck[di])
                & (hash_words(row_keys, seed=di)
                   % jnp.uint32(width) == cols))
        ok_np = np.asarray(ok) & cand
        d_idx, b_idx = np.nonzero(ok_np)
        if not len(d_idx):
            break
        dec = np.asarray(qk)[d_idx, b_idx]  # [m, kw]
        kview = np.ascontiguousarray(dec).view(
            [("", np.uint32)] * kw).reshape(-1)
        _, first = np.unique(kview, return_index=True)
        picked = [i for i in sorted(first)
                  if kview[i].tobytes() not in seen]
        if not picked:
            break
        for i in picked:
            seen.add(kview[i].tobytes())
        picked = np.asarray(picked)
        dec_keys = np.ascontiguousarray(dec[picked])
        cms_np = np.asarray(cms)
        dec_vals = np.stack(
            [cms_np[pi, d_idx[picked], b_idx[picked]] for pi in range(p)],
            axis=1)
        out_keys.append(dec_keys)
        out_vals.append(dec_vals)
        # peel: subtract each decoded key's exact contribution from its
        # bucket in every depth row (wrap), then rescan touched buckets
        jkeys = jnp.asarray(dec_keys)
        jvals = jnp.asarray(dec_vals)
        dcnt = jvals[:, -1]
        check = inv_key_hash(jkeys) * dcnt
        lanes_u64 = jkeys.astype(jnp.uint64) * dcnt[:, None]
        touched = np.zeros((depth, width), bool)
        for di in range(depth):
            bb = hash_words(jkeys, seed=di) % jnp.uint32(width)
            cms = cms.at[:, di, bb].add(
                jnp.uint64(0) - jvals.T)  # wrap subtract
            keysum = keysum.at[di, bb, :].add(jnp.uint64(0) - lanes_u64)
            keycheck = keycheck.at[di, bb].add(jnp.uint64(0) - check)
            touched[di, np.asarray(bb)] = True
        cand = touched & np.asarray(cms[-1] != 0)
    if not out_keys:
        return (np.zeros((0, kw), np.uint32), np.zeros((0, p), np.uint64))
    keys = np.concatenate(out_keys)
    vals = np.concatenate(out_vals)
    order = np.lexsort(keys.T[::-1])
    return (np.ascontiguousarray(keys[order]),
            np.ascontiguousarray(vals[order]))
