"""Pallas CMS update kernels: scatter as dense tile math on the MXU/VPU.

XLA lowers ``counts.at[buckets].add(v)`` / ``.max(v)`` to scatters, which
the TPU executes with serialized conflict handling. The TPU-native
formulation turns both CMS updates into dense per-tile work:

- linear add:  onehot[n, w] = (bucket[n] == w) built against the tile's
  column range on the VPU, then ``counts[p, d, tile] += vals.T @ onehot``
  — one [P,N]x[N,T] matmul per grid cell on the MXU.
- conservative update: the per-key ceiling ``target = est + vals`` is
  computed first (the estimate gather is already fast under XLA — it is
  scatters, not gathers, that serialize), then a max-scatter kernel
  raises each tile cell to ``max over keys in cell of target`` by
  streaming N in chunks through a masked VPU max-reduce.

Both kernels use the SAME bucket scheme as ops.cms (cms_buckets): they are
drop-in replacements for cms_add / cms_add_conservative on the same sketch
state, and ops.cms.cms_query serves either path. State stays in VMEM per
grid cell via input/output aliasing.

Correctness is tested in interpret mode on CPU (tests/test_cms_pallas.py);
bench.py cms compares the XLA and Pallas paths on hardware, and
models.heavy_hitter dispatches on HeavyHitterConfig.cms_impl.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .cms import cms_buckets, cms_query

_LANE = 128  # TPU lane width; width tiles are multiples of this


def _add_kernel(buckets_ref, vals_ref, counts_ref, out_ref, *, tile: int):
    """Grid cell (d, j): accumulate depth row d's contributions to columns
    [j*tile, (j+1)*tile). Buckets are precomputed once outside the kernel
    (hashing per grid cell would redo width/tile times the work)."""
    j = pl.program_id(1)

    bucket = buckets_ref[0, :]  # [N] this depth row's bucket per key
    vals = vals_ref[:]  # [N, P] float32 (0 for invalid rows)

    col0 = j * tile
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)  # [1,T]
    onehot = (bucket[:, None] == cols).astype(jnp.float32)  # [N, T]
    update = jnp.dot(vals.T, onehot,
                     preferred_element_type=jnp.float32)  # [P, T]
    out_ref[:] = counts_ref[:] + update[:, None, :]  # [P, 1, T]


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def cms_add_pallas(counts, keys, values, valid=None, *, tile: int = 2048,
                   interpret: bool = False):
    """Linear CMS update via the one-hot MXU kernel; drop-in for
    ops.cms.cms_add (same bucket scheme, same state, query with
    ops.cms.cms_query)."""
    p, d, w = counts.shape
    if w % tile:
        raise ValueError(f"width {w} must be a multiple of tile {tile}")
    vals = values.astype(jnp.float32)
    if valid is not None:
        vals = jnp.where(valid[:, None], vals, 0.0)
    buckets = cms_buckets(keys, d, w)  # [D, N], hashed exactly once

    grid = (d, w // tile)
    return pl.pallas_call(
        functools.partial(_add_kernel, tile=tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, buckets.shape[1]), lambda di, j: (di, 0)),
            pl.BlockSpec(vals.shape, lambda di, j: (0, 0)),  # vals: full
            pl.BlockSpec((p, 1, tile), lambda di, j: (0, di, j)),
        ],
        out_specs=pl.BlockSpec((p, 1, tile), lambda di, j: (0, di, j)),
        out_shape=jax.ShapeDtypeStruct(counts.shape, jnp.float32),
        input_output_aliases={2: 0},  # accumulate in place
        interpret=interpret,
    )(buckets, vals, counts)


def _max_kernel(buckets_ref, target_ref, counts_ref, out_ref, *,
                tile: int, chunk: int):
    """Grid cell (d, j): raise columns [j*tile, (j+1)*tile) of depth row d
    to the max target of any key hashing there. N is streamed in chunks so
    the [chunk, tile] mask stays VMEM-resident."""
    j = pl.program_id(1)
    n, p = target_ref.shape

    col0 = j * tile
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)  # [1,T]

    def body(c, acc):
        # [C] bucket slice, [C, P] targets for this chunk of keys
        bucket = jax.lax.dynamic_slice(buckets_ref[0, :], (c * chunk,),
                                       (chunk,))
        tgt = jax.lax.dynamic_slice(target_ref[:], (c * chunk, 0),
                                    (chunk, p))
        mask = bucket[:, None] == cols  # [C, T]
        # per plane: max over the chunk's keys of (in-cell ? target : 0);
        # cells are >= 0, so 0 never raises anything
        planes = [
            jnp.max(jnp.where(mask, tgt[:, pi][:, None], 0.0), axis=0)
            for pi in range(p)
        ]
        return jnp.maximum(acc, jnp.stack(planes, axis=0))  # [P, T]

    acc = jax.lax.fori_loop(0, n // chunk, body, counts_ref[:, 0, :])
    out_ref[:] = acc[:, None, :]


@functools.partial(jax.jit,
                   static_argnames=("tile", "chunk", "interpret"))
def cms_add_conservative_pallas(counts, keys, values, valid=None, *,
                                tile: int = 512, chunk: int = 512,
                                interpret: bool = False):
    """Conservative CMS update; drop-in for ops.cms.cms_add_conservative.

    The current-estimate gather runs in XLA (gathers do not serialize);
    only the conflict-prone scatter-max is a Pallas kernel. Keys must be
    unique within the call (sort_groupby first), matching the XLA path's
    contract."""
    p, d, w = counts.shape
    n = keys.shape[0]
    if w % tile:
        raise ValueError(f"width {w} must be a multiple of tile {tile}")
    buckets = cms_buckets(keys, d, w)  # [D, N]
    est = cms_query(counts, keys)  # [N, P]
    target = est + values.astype(jnp.float32)  # the CU ceiling per key
    if valid is not None:
        # invalid rows must not raise any cell (their est alone could);
        # a 0 target is inert — cells are >= 0 and only move via max
        target = jnp.where(valid[:, None], target, 0.0)
    if n % chunk:
        # pad the streamed dimension to a chunk multiple with inert rows
        # (zero targets) so chunk stays large for ANY batch size instead
        # of collapsing to gcd(n, chunk)
        pad = chunk - n % chunk
        buckets = jnp.pad(buckets, ((0, 0), (0, pad)))
        target = jnp.pad(target, ((0, pad), (0, 0)))

    grid = (d, w // tile)
    return pl.pallas_call(
        functools.partial(_max_kernel, tile=tile, chunk=chunk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, buckets.shape[1]), lambda di, j: (di, 0)),
            pl.BlockSpec(target.shape, lambda di, j: (0, 0)),
            pl.BlockSpec((p, 1, tile), lambda di, j: (0, di, j)),
        ],
        out_specs=pl.BlockSpec((p, 1, tile), lambda di, j: (0, di, j)),
        out_shape=jax.ShapeDtypeStruct(counts.shape, jnp.float32),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(buckets, target, counts)
