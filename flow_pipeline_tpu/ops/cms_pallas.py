"""Pallas CMS update kernel: scatter-add as one-hot matmul on the MXU.

XLA lowers ``counts.at[buckets].add(v)`` to a scatter, which the TPU
executes with serialized conflict handling. The TPU-native formulation
turns the histogram update into dense linear algebra:

    onehot[n, w] = (bucket[n] == w)          # VPU compare vs iota
    counts[p, d, :] += vals[:, p] @ onehot   # [P,N] x [N,W] on the MXU

The kernel fuses, per (depth, width-tile) grid cell: murmur3 bucket hashing
of the key word-lanes (seeded per depth), one-hot construction against the
tile's column range, and the accumulate matmul. State stays in VMEM across
the grid via input/output aliasing; nothing round-trips to HBM between
depth rows.

This mirrors the update semantics of ops.cms.cms_add exactly (linear,
mergeable). Use ``cms_add_pallas`` as a drop-in replacement; bench.py can
compare both paths on hardware. Correctness is tested in interpret mode on
CPU (tests/test_cms_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..schema.keys import hash_words

_LANE = 128  # TPU lane width; width tiles are multiples of this


def _kernel(buckets_ref, vals_ref, counts_ref, out_ref, *, tile: int):
    """Grid cell (d, j): accumulate depth row d's contributions to columns
    [j*tile, (j+1)*tile). Buckets are precomputed once on the host side of
    the jit (hashing all keys per grid cell would redo width/tile times the
    work on the VPU)."""
    j = pl.program_id(1)

    bucket = buckets_ref[0, :]  # [N] this depth row's bucket per key
    vals = vals_ref[:]  # [N, P] float32 (0 for invalid rows)

    col0 = j * tile
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)  # [1,T]
    onehot = (bucket[:, None] == cols).astype(jnp.float32)  # [N, T]
    update = jnp.dot(vals.T, onehot,
                     preferred_element_type=jnp.float32)  # [P, T]
    out_ref[:] = counts_ref[:] + update[:, None, :]  # [P, 1, T]


def cms_buckets_mixed(keys, depth: int, width: int):
    """Bucket indices matching the kernel's depth-mixing scheme (host/query
    side twin). [depth, N] int32."""
    h = hash_words(jnp.asarray(keys).astype(jnp.uint32), seed=0)
    rows = []
    for d in range(depth):
        hd = hash_words(
            jnp.stack([h, jnp.full_like(h, jnp.uint32(d))], axis=-1), seed=0
        )
        rows.append((hd % jnp.uint32(width)).astype(jnp.int32))
    return jnp.stack(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def cms_add_pallas(counts, keys, values, valid=None, *, tile: int = 2048,
                   interpret: bool = False):
    """Linear CMS update via the one-hot MXU kernel.

    counts: [P, D, W] float32; keys: [N, Wk] int lanes; values: [N, P].
    Bucket placement uses the depth-mixed murmur scheme (cms_buckets_mixed),
    which differs from ops.cms.cms_buckets seeding but has identical
    statistical properties; query with cms_query_mixed.
    """
    p, d, w = counts.shape
    if w % tile:
        raise ValueError(f"width {w} must be a multiple of tile {tile}")
    vals = values.astype(jnp.float32)
    if valid is not None:
        vals = jnp.where(valid[:, None], vals, 0.0)
    buckets = cms_buckets_mixed(keys, d, w)  # [D, N], hashed exactly once

    grid = (d, w // tile)
    return pl.pallas_call(
        functools.partial(_kernel, tile=tile),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, buckets.shape[1]), lambda di, j: (di, 0)),
            pl.BlockSpec(vals.shape, lambda di, j: (0, 0)),  # vals: full
            pl.BlockSpec((p, 1, tile), lambda di, j: (0, di, j)),
        ],
        out_specs=pl.BlockSpec((p, 1, tile), lambda di, j: (0, di, j)),
        out_shape=jax.ShapeDtypeStruct(counts.shape, jnp.float32),
        input_output_aliases={2: 0},  # accumulate in place
        interpret=interpret,
    )(buckets, vals, counts)


def cms_query_mixed(counts, keys):
    """Point estimates under the kernel's bucket scheme. [N, P] float32."""
    p, d, w = counts.shape
    buckets = cms_buckets_mixed(keys, d, w)
    ests = [counts[:, di, buckets[di]] for di in range(d)]
    return jnp.min(jnp.stack(ests, axis=0), axis=0).T
