"""Sort-based exact groupby on device.

TPU has no efficient general scatter-with-conflicts; the idiomatic exact
grouping is: lexicographic multi-key sort (``lax.sort`` with num_keys=W,
O(n log^2 n) bitonic network, all MXU/VPU-friendly) -> boundary detection ->
segment reductions. Shapes are static: a batch of N rows yields N segment
slots with a scalar count of how many are real.

This one op gives the framework exact per-batch partial aggregates, which
the host (or a psum across chips) merges per window — the same
partial-merge trick ClickHouse's SummingMergeTree uses at merge time
(ref: compose/clickhouse/create.sh:70-90), but batched and on-device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def sort_groupby(keys, values, valid):
    """Exact groupby-sum of ``values`` by row-tuples of ``keys``.

    Args:
      keys:   [N, W] integer lanes (bit-cast to uint32), lexicographic key.
      values: [N, V] int32 per-row addends (e.g. bytes, packets).
      valid:  [N] bool; invalid rows contribute nothing.

    Returns:
      unique_keys: [N, W] uint32 — row i < n_groups holds the i-th group key.
      sums:        [N, V] int32 — per-group value sums.
      counts:      [N] int32 — per-group row counts.
      n_groups:    [] int32 — number of real groups; rows >= n_groups are
                   padding (keys all-1s, sums/counts zero).

    Caveat: invalid rows are sent to the all-0xFFFFFFFF key, so a *valid*
    row whose whole key tuple is all-1s (e.g. the ff..ff address in a raw
    address-keyed layout) lands in the same sorted segment as the padding
    rows. That is still correct: padding rows contribute 0 to sums/counts,
    so the group survives the ``counts > 0`` reality test with exact values
    and its reported key IS the all-1s tuple. The only residual ambiguity
    is that such a group is indistinguishable from padding by key alone —
    reality is judged by counts, never by key. (Consumers that DO use the
    sentinel key as an empty-slot marker — ops.topk — cannot represent it
    and drop it explicitly; see topk_merge.)
    """
    n, w = keys.shape
    ku = keys.astype(jnp.uint32)
    sentinel = jnp.uint32(0xFFFFFFFF)
    ku = jnp.where(valid[:, None], ku, sentinel)
    vals = jnp.where(valid[:, None], values.astype(jnp.int32), 0)
    cnt = valid.astype(jnp.int32)

    # Payload rides as ONE iota lane, then a post-sort gather: the sort
    # network's cost scales with operand count, while gathers are ~free
    # (measured 20.8ms -> 17.5ms for the 11-lane master sort at 16k rows).
    operands = [ku[:, i] for i in range(w)] + [lax.iota(jnp.int32, n)]
    sorted_ops = lax.sort(operands, num_keys=w)
    perm = sorted_ops[w]
    sk = jnp.stack(sorted_ops[:w], axis=1)  # [N, W] sorted keys
    sv = vals[perm]  # [N, V]
    sc = cnt[perm]  # [N]

    prev = jnp.concatenate([jnp.full((1, w), sentinel, jnp.uint32), sk[:-1]], axis=0)
    is_boundary = jnp.any(sk != prev, axis=1)
    is_boundary = is_boundary.at[0].set(True)
    seg_ids = jnp.cumsum(is_boundary.astype(jnp.int32)) - 1  # [N]

    sums = jax.ops.segment_sum(sv, seg_ids, num_segments=n)
    counts = jax.ops.segment_sum(sc, seg_ids, num_segments=n)
    # Keys are constant within a segment: max == the key.
    unique_keys = jax.ops.segment_max(sk, seg_ids, num_segments=n)

    # A group is real iff it holds at least one valid row. Judging by
    # counts (not by key != sentinel) keeps a valid all-1s key tuple
    # countable: its rows share a segment with padding, but padding adds 0
    # to counts/sums. All-padding groups have counts == 0 and sort last,
    # so real groups occupy a contiguous prefix and n_groups is exact.
    group_real = counts > 0
    n_groups = jnp.sum(group_real.astype(jnp.int32))
    sums = jnp.where(group_real[:, None], sums, 0)
    unique_keys = jnp.where(group_real[:, None], unique_keys, sentinel)
    return unique_keys, sums, counts, n_groups


def presorted_segments(sorted_keys):
    """Segment ids for rows ALREADY in lexicographic key order.

    The boundary-detect + prefix-sum half of sort_groupby, factored out so
    one multi-key sort can serve several groupbys: rows sorted by key
    lanes (k1..kn) are, by lexicographic order, also grouped by every
    PREFIX (k1..kj) — pass ``sorted_keys[:, :j]`` to group by the prefix
    without re-sorting (engine.fused shares one 11-lane sort between the
    5-tuple and src-address models this way).

    Args: sorted_keys [N, W] uint32. Returns seg_ids [N] int32.
    """
    n, w = sorted_keys.shape
    sentinel = jnp.uint32(0xFFFFFFFF)
    prev = jnp.concatenate(
        [jnp.full((1, w), sentinel, jnp.uint32), sorted_keys[:-1]], axis=0
    )
    is_boundary = jnp.any(sorted_keys != prev, axis=1)
    is_boundary = is_boundary.at[0].set(True)
    return jnp.cumsum(is_boundary.astype(jnp.int32)) - 1


def presorted_groupby_float(sorted_keys, sorted_vals, sorted_cnt, width=None):
    """Groupby of presorted float payload rows by the first ``width`` key
    lanes. Same return contract as sort_groupby_float: (uniq [N,width]
    uint32, sums [N,P] float32, counts [N] int32), reality judged by
    counts > 0 (see sort_groupby's sentinel caveat)."""
    n = sorted_keys.shape[0]
    sk = sorted_keys if width is None else sorted_keys[:, :width]
    seg_ids = presorted_segments(sk)
    sums = jax.ops.segment_sum(sorted_vals, seg_ids, num_segments=n)
    counts = jax.ops.segment_sum(sorted_cnt, seg_ids, num_segments=n)
    uniq = jax.ops.segment_max(sk, seg_ids, num_segments=n)
    real = counts > 0
    sums = jnp.where(real[:, None], sums, 0.0)
    uniq = jnp.where(real[:, None], uniq, jnp.uint32(0xFFFFFFFF))
    counts = jnp.where(real, counts, 0)
    return uniq, sums, counts


def sort_rows_float(keys, values, valid):
    """Lexicographic multi-key sort with float payload riding along — the
    sort half of sort_groupby_float. Invalid rows get all-sentinel keys
    (they sort last) and zeroed payload/count.

    Returns (sorted_keys [N,W] uint32, sorted_vals [N,P] float32,
    sorted_cnt [N] int32); feed to presorted_groupby_float (optionally
    per key prefix) to finish the groupby."""
    n, w = keys.shape
    sentinel = jnp.uint32(0xFFFFFFFF)
    ku = jnp.where(valid[:, None], keys.astype(jnp.uint32), sentinel)
    fv = jnp.where(valid[:, None], values.astype(jnp.float32), 0.0)
    cnt = valid.astype(jnp.int32)
    # iota payload + post-sort gather (see sort_groupby): cheaper than
    # carrying every value plane through the sort network
    operands = [ku[:, i] for i in range(w)] + [lax.iota(jnp.int32, n)]
    sorted_ops = lax.sort(operands, num_keys=w)
    perm = sorted_ops[w]
    sk = jnp.stack(sorted_ops[:w], axis=1)
    return sk, fv[perm], cnt[perm]


def sort_groupby_float(keys, values, valid):
    """sort_groupby with float32 value planes.

    Value magnitudes beyond int32 (saturated uint32 byte counters, float
    sketch sums) can't ride the int32 path; here the float planes travel
    through the multi-key sort as bit-cast int32 payload lanes and are
    segment-summed in float domain. Same return contract as sort_groupby
    but sums is float32 and the n_groups scalar is replaced by per-row
    ``counts > 0`` validity (the all-sentinel group is zeroed).

    Returns (unique_keys [N,W] uint32, sums [N,P] float32, counts [N] int32).
    """
    # counts>0 alone decides reality (see sort_groupby): a valid all-1s
    # key shares the padding segment but padding contributes 0 to counts,
    # so the group — and its exact float sums — survive.
    return presorted_groupby_float(*sort_rows_float(keys, values, valid))
