"""Sort-based exact groupby on device.

TPU has no efficient general scatter-with-conflicts; the idiomatic exact
grouping is: lexicographic multi-key sort (``lax.sort`` with num_keys=W,
O(n log^2 n) bitonic network, all MXU/VPU-friendly) -> boundary detection ->
segment reductions. Shapes are static: a batch of N rows yields N segment
slots with a scalar count of how many are real.

This one op gives the framework exact per-batch partial aggregates, which
the host (or a psum across chips) merges per window — the same
partial-merge trick ClickHouse's SummingMergeTree uses at merge time
(ref: compose/clickhouse/create.sh:70-90), but batched and on-device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def sort_groupby(keys, values, valid):
    """Exact groupby-sum of ``values`` by row-tuples of ``keys``.

    Args:
      keys:   [N, W] integer lanes (bit-cast to uint32), lexicographic key.
      values: [N, V] int32 per-row addends (e.g. bytes, packets).
      valid:  [N] bool; invalid rows contribute nothing.

    Returns:
      unique_keys: [N, W] uint32 — row i < n_groups holds the i-th group key.
      sums:        [N, V] int32 — per-group value sums.
      counts:      [N] int32 — per-group row counts.
      n_groups:    [] int32 — number of real groups; rows >= n_groups are
                   padding (keys all-1s, sums/counts zero).

    Caveat: invalid rows are sent to the all-0xFFFFFFFF key, so a *valid*
    row whose whole key tuple is all-1s (e.g. the ff..ff address in a raw
    address-keyed layout) lands in the same sorted segment as the padding
    rows. That is still correct: padding rows contribute 0 to sums/counts,
    so the group survives the ``counts > 0`` reality test with exact values
    and its reported key IS the all-1s tuple. The only residual ambiguity
    is that such a group is indistinguishable from padding by key alone —
    reality is judged by counts, never by key. (Consumers that DO use the
    sentinel key as an empty-slot marker — ops.topk — cannot represent it
    and drop it explicitly; see topk_merge.)
    """
    n, w = keys.shape
    ku = keys.astype(jnp.uint32)
    sentinel = jnp.uint32(0xFFFFFFFF)
    ku = jnp.where(valid[:, None], ku, sentinel)
    vals = jnp.where(valid[:, None], values.astype(jnp.int32), 0)
    cnt = valid.astype(jnp.int32)

    # Payload rides as ONE iota lane, then a post-sort gather: the sort
    # network's cost scales with operand count, while gathers are ~free
    # (measured 20.8ms -> 17.5ms for the 11-lane master sort at 16k rows).
    operands = [ku[:, i] for i in range(w)] + [lax.iota(jnp.int32, n)]
    sorted_ops = lax.sort(operands, num_keys=w)
    perm = sorted_ops[w]
    sk = jnp.stack(sorted_ops[:w], axis=1)  # [N, W] sorted keys
    sv = vals[perm]  # [N, V]
    sc = cnt[perm]  # [N]

    prev = jnp.concatenate([jnp.full((1, w), sentinel, jnp.uint32), sk[:-1]], axis=0)
    is_boundary = jnp.any(sk != prev, axis=1)
    is_boundary = is_boundary.at[0].set(True)
    seg_ids = jnp.cumsum(is_boundary.astype(jnp.int32)) - 1  # [N]

    sums = jax.ops.segment_sum(sv, seg_ids, num_segments=n)
    counts = jax.ops.segment_sum(sc, seg_ids, num_segments=n)
    # Keys are constant within a segment: max == the key.
    unique_keys = jax.ops.segment_max(sk, seg_ids, num_segments=n)

    # A group is real iff it holds at least one valid row. Judging by
    # counts (not by key != sentinel) keeps a valid all-1s key tuple
    # countable: its rows share a segment with padding, but padding adds 0
    # to counts/sums. All-padding groups have counts == 0 and sort last,
    # so real groups occupy a contiguous prefix and n_groups is exact.
    group_real = counts > 0
    n_groups = jnp.sum(group_real.astype(jnp.int32))
    sums = jnp.where(group_real[:, None], sums, 0)
    unique_keys = jnp.where(group_real[:, None], unique_keys, sentinel)
    return unique_keys, sums, counts, n_groups


def presorted_segments(sorted_keys):
    """Segment ids for rows ALREADY in lexicographic key order.

    The boundary-detect + prefix-sum half of sort_groupby, factored out so
    one multi-key sort can serve several groupbys: rows sorted by key
    lanes (k1..kn) are, by lexicographic order, also grouped by every
    PREFIX (k1..kj) — pass ``sorted_keys[:, :j]`` to group by the prefix
    without re-sorting (engine.fused shares one 11-lane sort between the
    5-tuple and src-address models this way).

    Args: sorted_keys [N, W] uint32. Returns seg_ids [N] int32.
    """
    n, w = sorted_keys.shape
    sentinel = jnp.uint32(0xFFFFFFFF)
    prev = jnp.concatenate(
        [jnp.full((1, w), sentinel, jnp.uint32), sorted_keys[:-1]], axis=0
    )
    is_boundary = jnp.any(sorted_keys != prev, axis=1)
    is_boundary = is_boundary.at[0].set(True)
    return jnp.cumsum(is_boundary.astype(jnp.int32)) - 1


def presorted_groupby_float(sorted_keys, sorted_vals, sorted_cnt, width=None):
    """Groupby of presorted float payload rows by the first ``width`` key
    lanes. Same return contract as sort_groupby_float: (uniq [N,width]
    uint32, sums [N,P] float32, counts [N] int32), reality judged by
    counts > 0 (see sort_groupby's sentinel caveat)."""
    n = sorted_keys.shape[0]
    sk = sorted_keys if width is None else sorted_keys[:, :width]
    seg_ids = presorted_segments(sk)
    sums = jax.ops.segment_sum(sorted_vals, seg_ids, num_segments=n)
    counts = jax.ops.segment_sum(sorted_cnt, seg_ids, num_segments=n)
    uniq = jax.ops.segment_max(sk, seg_ids, num_segments=n)
    real = counts > 0
    sums = jnp.where(real[:, None], sums, 0.0)
    uniq = jnp.where(real[:, None], uniq, jnp.uint32(0xFFFFFFFF))
    counts = jnp.where(real, counts, 0)
    return uniq, sums, counts


# numpy, NOT jnp: a module-level jnp constant would initialize the JAX
# backend at import time (breaking jax.distributed.initialize ordering
# in multi-host workers — engine modules import this one transitively)
_SENTINEL = np.uint32(0xFFFFFFFF)

# Two decorrelated odd multipliers (golden-ratio / murmur-style constants)
# for the paired 32-bit mixes that form the 64-bit grouping hash.
_HASH_MULT = (0x9E3779B1, 0x85EBCA77)
_HASH_SEED = (0x2545F491, 0x27220A95)


def _fmix32(h):
    """murmur3 finalizer: full-avalanche 32-bit mix."""
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> jnp.uint32(16))


def hash_lanes(keys):
    """Two independent 32-bit mixes of each [N, W] uint32 key row.

    Together they form a 64-bit grouping hash: the probability that two
    DISTINCT key tuples in one batch agree on both lanes is ~n^2/2^65
    (~1e-11 at n=32k). Lane-count independence is what makes hash-grouped
    sorts cheap: ``lax.sort`` cost scales with operand count, so sorting
    (h1, h2) beats sorting the raw 4-11 key lanes ~2-4x on both CPU and
    the TPU bitonic network.

    Returns (h1, h2), each [N] uint32.
    """
    n, w = keys.shape
    ku = keys.astype(jnp.uint32)
    out = []
    for mult, seed in zip(_HASH_MULT, _HASH_SEED):
        h = jnp.full(n, seed, jnp.uint32)
        m = jnp.uint32(mult)
        for i in range(w):
            h = (h ^ ku[:, i]) * m
            h = ((h << jnp.uint32(13)) | (h >> jnp.uint32(19)))  # rotl 13
        out.append(_fmix32(h))
    return out[0], out[1]


def hash_sort(keys, valid):
    """Sort rows by the 64-bit hash of their key tuple.

    The cheap half of hash_groupby, factored out so callers with custom
    payload plumbing (engine.fused's dual-mask dst family) can ride one
    hash sort. Invalid rows hash to the all-1s sentinel pair and sort
    last, exactly like sort_groupby's sentinel keys.

    Returns (sorted_hashes [N, 2] uint32, perm [N] int32): gather any
    per-row payload with ``payload[perm]``.
    """
    n = keys.shape[0]
    h1, h2 = hash_lanes(keys)
    h1 = jnp.where(valid, h1, _SENTINEL)
    h2 = jnp.where(valid, h2, _SENTINEL)
    out = lax.sort([h1, h2, lax.iota(jnp.int32, n)], num_keys=2)
    return jnp.stack(out[:2], axis=1), out[2]


def _hash_grouped(sorted_hashes, sorted_keys, sorted_vals, sorted_cnt,
                  detect: bool):
    """Segment reductions over rows already hash-sorted.

    ``sorted_keys`` are the ORIGINAL key lanes gathered through the sort
    permutation (invalid rows replaced by the sentinel tuple). Group
    identity is judged on the hash pair; the reported unique key is the
    per-group segment_min of the real keys, so padding (all-sentinel)
    never wins a mixed group. With ``detect`` the returned flag is True
    iff some group contained two DIFFERENT real key tuples — a 64-bit
    hash collision — letting exactness-critical callers fall back to the
    lexicographic path for that batch.
    """
    n = sorted_hashes.shape[0]
    seg_ids = presorted_segments(sorted_hashes)
    sums = jax.ops.segment_sum(sorted_vals, seg_ids, num_segments=n)
    counts = jax.ops.segment_sum(sorted_cnt, seg_ids, num_segments=n)
    uniq = jax.ops.segment_min(sorted_keys, seg_ids, num_segments=n)
    real = counts > 0
    sums = jnp.where(real[:, None], sums, jnp.zeros_like(sums[:1]))
    uniq = jnp.where(real[:, None], uniq, _SENTINEL)
    counts = jnp.where(real, counts, 0)
    if not detect:
        return uniq, sums, counts, None
    rep_rows = uniq[seg_ids]  # [N, W] group representative per row
    mismatch = jnp.any(sorted_keys != rep_rows, axis=1) & (sorted_cnt > 0)
    return uniq, sums, counts, jnp.any(mismatch)


def hash_groupby_float(keys, values, valid, detect: bool = False):
    """sort_groupby_float semantics via the 64-bit hash sort.

    Same return contract as sort_groupby_float — (unique_keys [N, W]
    uint32, sums [N, P] float32, counts [N] int32), reality judged by
    counts > 0 — but groups are ordered by hash, not lexicographically
    (no consumer in this framework orders by key), and two distinct
    tuples colliding in the full 64-bit hash (~n^2/2^65 per batch) are
    merged into one group whose reported key is the lane-wise min. The
    approximate models (heavy-hitter tables, whose CMS planes already
    merge colliding keys by design) absorb that; exactness-contract
    callers pass detect=True and re-run the batch through
    sort_groupby(_float) when the returned flag fires.

    With detect=True returns (uniq, sums, counts, collided: bool scalar).
    """
    ku = jnp.where(valid[:, None], keys.astype(jnp.uint32), _SENTINEL)
    fv = jnp.where(valid[:, None], values.astype(jnp.float32), 0.0)
    cnt = valid.astype(jnp.int32)
    sh, perm = hash_sort(keys, valid)
    uniq, sums, counts, collided = _hash_grouped(
        sh, ku[perm], fv[perm], cnt[perm], detect)
    if detect:
        return uniq, sums, counts, collided
    return uniq, sums, counts


def hash_groupby(keys, values, valid):
    """sort_groupby semantics (int32 planes + n_groups) via the hash sort,
    plus a collision flag — the exact aggregator's fast path.

    Returns (unique_keys, sums, counts, n_groups, collided). Real groups
    occupy a contiguous slot prefix exactly as in sort_groupby (padding
    hashes to the sentinel pair and sorts last), so ``keys[:n_groups]``
    device slicing keeps working. Callers MUST honor ``collided`` (re-run
    via sort_groupby) to preserve bit-exactness; see hash_groupby_float
    for the probability argument.
    """
    ku = jnp.where(valid[:, None], keys.astype(jnp.uint32), _SENTINEL)
    vals = jnp.where(valid[:, None], values.astype(jnp.int32), 0)
    cnt = valid.astype(jnp.int32)
    sh, perm = hash_sort(keys, valid)
    uniq, sums, counts, collided = _hash_grouped(
        sh, ku[perm], vals[perm], cnt[perm], True)
    n_groups = jnp.sum((counts > 0).astype(jnp.int32))
    return uniq, sums, counts, n_groups, collided


def sort_rows_float(keys, values, valid):
    """Lexicographic multi-key sort with float payload riding along — the
    sort half of sort_groupby_float. Invalid rows get all-sentinel keys
    (they sort last) and zeroed payload/count.

    Returns (sorted_keys [N,W] uint32, sorted_vals [N,P] float32,
    sorted_cnt [N] int32); feed to presorted_groupby_float (optionally
    per key prefix) to finish the groupby."""
    n, w = keys.shape
    sentinel = jnp.uint32(0xFFFFFFFF)
    ku = jnp.where(valid[:, None], keys.astype(jnp.uint32), sentinel)
    fv = jnp.where(valid[:, None], values.astype(jnp.float32), 0.0)
    cnt = valid.astype(jnp.int32)
    # iota payload + post-sort gather (see sort_groupby): cheaper than
    # carrying every value plane through the sort network
    operands = [ku[:, i] for i in range(w)] + [lax.iota(jnp.int32, n)]
    sorted_ops = lax.sort(operands, num_keys=w)
    perm = sorted_ops[w]
    sk = jnp.stack(sorted_ops[:w], axis=1)
    return sk, fv[perm], cnt[perm]


def sort_groupby_float(keys, values, valid):
    """sort_groupby with float32 value planes.

    Value magnitudes beyond int32 (saturated uint32 byte counters, float
    sketch sums) can't ride the int32 path; here the float planes travel
    through the multi-key sort as bit-cast int32 payload lanes and are
    segment-summed in float domain. Same return contract as sort_groupby
    but sums is float32 and the n_groups scalar is replaced by per-row
    ``counts > 0`` validity (the all-sentinel group is zeroed).

    Returns (unique_keys [N,W] uint32, sums [N,P] float32, counts [N] int32).
    """
    # counts>0 alone decides reality (see sort_groupby): a valid all-1s
    # key shares the padding segment but padding contributes 0 to counts,
    # so the group — and its exact float sums — survive.
    return presorted_groupby_float(*sort_rows_float(keys, values, valid))
