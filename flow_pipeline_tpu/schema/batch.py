"""Columnar FlowBatch: struct-of-arrays layout for TPU-friendly batches.

Design notes (TPU-first):

- Flows arrive as protobuf records; the device wants dense, fixed-width,
  same-dtype lanes. We decode straight into a struct-of-arrays where every
  column is a length-N numpy array and 16-byte addresses become ``[N, 4]``
  uint32 word lanes (big-endian word order, so IPv4-in-trailing-4-bytes —
  the collector convention, ref: compose/clickhouse/create.sh:44-45 — lands
  in word 3).
- All device-bound columns are (u)int32: TPU vector lanes are 32-bit and JAX
  defaults to 32-bit ints. Timestamps are seconds-since-epoch and fit uint32;
  per-flow Bytes/Packets are bounded by sample size (<64 KiB) and fit too.
  Window *accumulators* widen to higher precision on device (see models/).
- Batches carry their source offset range ``(partition, first_offset,
  last_offset)`` so sketch snapshots can record exactly which input they
  cover (at-least-once resume; the reference loses up to flush.count-1 rows
  by marking offsets before flush, ref: inserter/inserter.go:188 — we fix
  that by design).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional

import numpy as np

from .message import FlowMessage
from . import wire

# Column name -> numpy dtype for 1-D columns. Address columns are [N,4] uint32.
# Fields that are uint64 on the wire (timestamps, sampling rate, byte/packet
# counts — ref: pb-ext/flow.proto uint64 fields) keep 64 bits host-side and
# narrow at the device boundary (see device_columns).
COLUMNS: dict[str, np.dtype] = {
    "type": np.dtype(np.uint32),
    "time_received": np.dtype(np.uint64),
    "sampling_rate": np.dtype(np.uint64),
    "sequence_num": np.dtype(np.uint32),
    "time_flow_start": np.dtype(np.uint64),
    "time_flow_end": np.dtype(np.uint64),
    "bytes": np.dtype(np.uint64),
    "packets": np.dtype(np.uint64),
    "src_as": np.dtype(np.uint32),
    "dst_as": np.dtype(np.uint32),
    "in_if": np.dtype(np.uint32),
    "out_if": np.dtype(np.uint32),
    "proto": np.dtype(np.uint32),
    "src_port": np.dtype(np.uint32),
    "dst_port": np.dtype(np.uint32),
    "ip_tos": np.dtype(np.uint32),
    "forwarding_status": np.dtype(np.uint32),
    "ip_ttl": np.dtype(np.uint32),
    "tcp_flags": np.dtype(np.uint32),
    "etype": np.dtype(np.uint32),
    "icmp_type": np.dtype(np.uint32),
    "icmp_code": np.dtype(np.uint32),
    "ipv6_flow_label": np.dtype(np.uint32),
    "flow_direction": np.dtype(np.uint32),
}

ADDR_COLUMNS = ("src_addr", "dst_addr", "sampler_address")


def lane_width(name: str) -> int:
    """Device lanes a column occupies: addresses are 4 uint32 words, scalars 1.
    The single source of truth for key packing/unpacking widths."""
    return 4 if name in ADDR_COLUMNS else 1


def addr_to_words(addr: bytes) -> np.ndarray:
    """16-byte address -> 4 big-endian uint32 words. Short input (e.g. a raw
    IPv4) is left-padded to 16 bytes, matching the trailing-bytes embedding."""
    b = addr[-16:].rjust(16, b"\x00")
    return np.frombuffer(b, dtype=">u4").astype(np.uint32)


def words_to_addr(words: np.ndarray) -> bytes:
    return np.asarray(words, dtype=">u4").tobytes()


@dataclass
class FlowBatch:
    """A batch of N flows in struct-of-arrays layout.

    ``columns`` maps each 1-D column name to a length-N array (dtypes per
    COLUMNS); ``src_addr``/``dst_addr``/``sampler_address`` are [N,4] uint32.

    Normalization: the columnar form is fixed-width, so an absent address
    (``b""`` on the wire) and the all-zero address ``::`` are the same value
    here — exactly the collapse the reference's FixedString(16) storage makes
    (ref: compose/clickhouse/create.sh:44-45). ``to_messages`` yields 16-byte
    addresses for every row.
    """

    columns: dict[str, np.ndarray]
    partition: int = 0
    first_offset: int = -1
    last_offset: int = -1
    # flowtrace chunk id, minted at decode (transport.consumer) — ties
    # one chunk's spans together across the feed/group/worker/flusher
    # threads. -1 = not traced (batches built outside the consume path).
    chunk_id: int = -1
    # flowguard lag signal: wall clock when the batch's OLDEST message
    # was produced onto the bus (0.0 = transport does not stamp, e.g.
    # Kafka — the guard then has no lag signal and stays at level 0).
    # now - produced_at is the age of the backlog head: the watermark
    # lag the -guard.lag budget is measured against.
    produced_at: float = 0.0

    # ---- construction -----------------------------------------------------

    @staticmethod
    def empty(n: int = 0) -> "FlowBatch":
        cols = {name: np.zeros(n, dtype=dt) for name, dt in COLUMNS.items()}
        for name in ADDR_COLUMNS:
            cols[name] = np.zeros((n, 4), dtype=np.uint32)
        return FlowBatch(cols)

    @staticmethod
    def from_messages(msgs: Iterable[FlowMessage]) -> "FlowBatch":
        msgs = list(msgs)
        batch = FlowBatch.empty(len(msgs))
        cols = batch.columns
        masks = {name: (1 << (8 * dt.itemsize)) - 1 for name, dt in COLUMNS.items()}
        for i, m in enumerate(msgs):
            for name in COLUMNS:
                # Mask to column width: oversized varints from a peer must not
                # kill the ingest path (numpy 2.x raises OverflowError).
                cols[name][i] = getattr(m, name) & masks[name]
            for name in ADDR_COLUMNS:
                cols[name][i] = addr_to_words(getattr(m, name))
        return batch

    @staticmethod
    def from_wire(data: bytes, framed: bool = True) -> "FlowBatch":
        """Decode a byte stream of FlowMessages into a batch. Uses the native
        C++ columnar decoder when built, else the pure-Python codec."""
        from .. import native  # local import: native is optional

        if framed and native.available():
            return native.decode_stream(data)
        msgs = wire.decode_frames(data) if framed else [wire.decode_message(data)]
        return FlowBatch.from_messages(msgs)

    def to_wire(self) -> bytes:
        """Length-prefixed frame stream for the whole batch — the single
        place that picks the native bulk encoder over the pure-Python path
        (mirrors from_wire)."""
        from .. import native  # local import: native is optional

        if native.available():
            return native.encode_stream(self)
        return wire.encode_stream(self.to_messages())

    # ---- views ------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.columns["bytes"])

    def to_messages(self) -> list[FlowMessage]:
        out = []
        for i in range(len(self)):
            m = FlowMessage()
            for name in COLUMNS:
                setattr(m, name, int(self.columns[name][i]))
            for name in ADDR_COLUMNS:
                setattr(m, name, words_to_addr(self.columns[name][i]))
            out.append(m)
        return out

    def device_columns(self, names: Optional[Iterable[str]] = None) -> dict:
        """Columns as int32-lane numpy arrays ready for device put (TPU lanes
        are 32-bit and JAX defaults to 32-bit ints).

        uint32 columns are bit-cast to int32 (raw words); uint64 columns are
        saturated to 2^32-1 then narrowed — timestamps in seconds fit uint32
        until 2106, and per-flow byte/packet counts above 4.29e9 clamp rather
        than wrap (window accumulators re-widen on device). May alias the
        batch's memory; treat as read-only."""
        if names is None:
            names = list(COLUMNS) + list(ADDR_COLUMNS)
        out = {}
        for name in names:
            arr = self.columns[name]
            if arr.dtype == np.uint64:
                arr = np.minimum(arr, np.uint64(0xFFFFFFFF)).astype(np.uint32)
            out[name] = arr.view(np.int32) if arr.dtype == np.uint32 else arr
        return out

    def nbytes(self) -> int:
        """Resident column bytes — the flowguard per-stage buffer
        accounting unit (guard_buffer_bytes)."""
        return sum(v.nbytes for v in self.columns.values())

    def take(self, mask: np.ndarray) -> "FlowBatch":
        """Rows selected by a boolean mask, as fresh arrays. The offset
        range is PRESERVED UNCHANGED: flowguard admission uses this, and
        the rows the mask drops were still consumed from the bus — their
        offsets must keep committing or a restart would replay (and
        double-shed-account) them."""
        cols = {k: v[mask] for k, v in self.columns.items()}
        return FlowBatch(cols, self.partition, self.first_offset,
                         self.last_offset, self.chunk_id,
                         self.produced_at)

    def slice(self, start: int, stop: int) -> "FlowBatch":
        stop = min(stop, len(self))  # offsets must cover only real rows
        cols = {k: v[start:stop] for k, v in self.columns.items()}
        first = self.first_offset + start if self.first_offset >= 0 else -1
        last = self.first_offset + stop - 1 if self.first_offset >= 0 else -1
        return FlowBatch(cols, self.partition, first, last, self.chunk_id,
                         self.produced_at)

    def pad_to(self, n: int) -> tuple["FlowBatch", np.ndarray]:
        """Pad to length n (static shapes for jit); returns (batch, valid mask).
        Padding rows are all-zero, which every kernel treats as weight-0.
        When already exactly n long, the same batch is returned (no copy) —
        treat the result as read-only."""
        cur = len(self)
        if cur > n:
            raise ValueError(f"batch of {cur} cannot pad to {n}")
        mask = np.zeros(n, dtype=bool)
        mask[:cur] = True
        if cur == n:
            return self, mask
        cols = {}
        for k, v in self.columns.items():
            shape = (n,) + v.shape[1:]
            padded = np.zeros(shape, dtype=v.dtype)
            padded[:cur] = v
            cols[k] = padded
        return FlowBatch(cols, self.partition, self.first_offset,
                         self.last_offset, self.chunk_id,
                         self.produced_at), mask

    @staticmethod
    def concat(batches: list["FlowBatch"]) -> "FlowBatch":
        if not batches:
            return FlowBatch.empty(0)
        cols = {
            k: np.concatenate([b.columns[k] for b in batches])
            for k in batches[0].columns
        }
        return FlowBatch(
            cols,
            batches[0].partition,
            batches[0].first_offset,
            batches[-1].last_offset,
            produced_at=batches[0].produced_at,
        )
