"""Schema core: the FlowMessage record, its protobuf wire codec, and the
columnar (struct-of-arrays) FlowBatch layout that feeds the TPU.

Wire-compatible with the reference schema (ref: pb-ext/flow.proto:7-65) so
that producers/consumers of the reference pipeline interoperate unchanged.
"""

from .message import FlowMessage, FlowType, FIELDS
from .wire import (
    encode_message,
    decode_message,
    encode_frame,
    decode_frames,
    encode_stream,
)
from .batch import FlowBatch, COLUMNS


def __getattr__(name):
    # Lazy: .keys pulls in jax; pure wire-codec consumers (collector-side
    # producers) must not pay a multi-second jax import.
    if name in ("hash_words", "hash_columns", "pack_addr_words"):
        from . import keys

        return getattr(keys, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "FlowMessage",
    "FlowType",
    "FIELDS",
    "encode_message",
    "decode_message",
    "encode_frame",
    "decode_frames",
    "encode_stream",
    "FlowBatch",
    "COLUMNS",
    "hash_words",
    "hash_columns",
    "pack_addr_words",
]
