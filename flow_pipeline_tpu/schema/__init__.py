"""Schema core: the FlowMessage record, its protobuf wire codec, and the
columnar (struct-of-arrays) FlowBatch layout that feeds the TPU.

Wire-compatible with the reference schema (ref: pb-ext/flow.proto:7-65) so
that producers/consumers of the reference pipeline interoperate unchanged.
"""

from .message import FlowMessage, FlowType, FIELDS
from .wire import (
    encode_message,
    decode_message,
    encode_frame,
    decode_frames,
    encode_stream,
)
from .batch import FlowBatch, COLUMNS
from .keys import hash_words, hash_columns, pack_addr_words

__all__ = [
    "FlowMessage",
    "FlowType",
    "FIELDS",
    "encode_message",
    "decode_message",
    "encode_frame",
    "decode_frames",
    "encode_stream",
    "FlowBatch",
    "COLUMNS",
    "hash_words",
    "hash_columns",
    "pack_addr_words",
]
