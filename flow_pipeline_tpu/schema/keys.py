"""Key packing and hashing for sketch kernels.

The sketch keys are tuples over flow columns — e.g. the 5-tuple
(SrcAddr, DstAddr, SrcPort, DstPort, Proto) or the AS pair (SrcAS, DstAS)
(ref: BASELINE.json configs; ClickHouse groups by (SrcAS, DstAS, EType),
ref: compose/clickhouse/create.sh:96-110). On TPU we never materialize the
38-byte tuple: each key column is already a uint32 word lane, and we mix the
word lanes with a murmur3-style finalizer into one 32-bit hash per flow,
re-seeded per sketch row. All arithmetic is uint32 with natural wraparound —
pure VPU element-wise work that XLA fuses into the surrounding kernel.
"""

from __future__ import annotations

# flowlint: uint64-exact
# (murmur3 word-lane hashing is pure uint32 wraparound arithmetic; a
# signed cast or defaulted dtype silently changes every hash)

from typing import Sequence

import jax.numpy as jnp
import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_FMIX1 = np.uint32(0x85EBCA6B)
_FMIX2 = np.uint32(0xC2B2AE35)


def _rotl(x, r):
    return (x << r) | (x >> (32 - r))


def hash_words(words, seed: int = 0):
    """murmur3_x86_32 over uint32 word lanes.

    words: [..., W] array (any integer dtype; bit-cast to uint32).
    Returns uint32 [...] hash. Works under jit and inside Pallas kernels
    (element-wise uint32 ops only).
    """
    w = jnp.asarray(words)
    w = w.astype(jnp.uint32) if w.dtype != jnp.uint32 else w
    h = jnp.full(w.shape[:-1], jnp.uint32(seed), dtype=jnp.uint32)
    nwords = w.shape[-1]
    for i in range(nwords):  # static unroll: W is a compile-time constant
        k = w[..., i]
        k = k * _C1
        k = _rotl(k, 15)
        k = k * _C2
        h = h ^ k
        h = _rotl(h, 13)
        h = h * jnp.uint32(5) + jnp.uint32(0xE6546B64)
    h = h ^ jnp.uint32(nwords * 4)
    h = h ^ (h >> 16)
    h = h * _FMIX1
    h = h ^ (h >> 13)
    h = h * _FMIX2
    h = h ^ (h >> 16)
    return h


def hash_columns(cols: dict, names: Sequence[str], seed: int = 0):
    """Hash a key tuple given device columns. Address columns ([N,4]) expand
    to 4 words; scalar columns to 1. Word order is the tuple order, so the
    same names+seed give identical hashes host- and device-side."""
    lanes = []
    for name in names:
        arr = jnp.asarray(cols[name])
        arr = arr.astype(jnp.uint32) if arr.dtype != jnp.uint32 else arr
        if arr.ndim == 1:
            lanes.append(arr[:, None])
        else:
            lanes.append(arr)
    words = jnp.concatenate(lanes, axis=-1)
    return hash_words(words, seed)


def pack_addr_words(addr_words) -> np.ndarray:
    """Host-side: [N,4] uint32 -> structured void view usable as dict keys /
    np.unique input for exact oracles."""
    a = np.ascontiguousarray(np.asarray(addr_words, dtype=np.uint32))
    return a.view([("w", np.uint32, 4)]).reshape(-1)


def hash_words_np(words: np.ndarray, seed: int = 0) -> np.ndarray:
    """Numpy twin of hash_words for host-side verification."""
    w = np.asarray(words, dtype=np.uint32)
    with np.errstate(over="ignore"):
        h = np.full(w.shape[:-1], np.uint32(seed), dtype=np.uint32)
        nwords = w.shape[-1]
        for i in range(nwords):
            k = w[..., i].copy()
            k *= _C1
            k = ((k << np.uint32(15)) | (k >> np.uint32(17))).astype(np.uint32)
            k *= _C2
            h ^= k
            h = ((h << np.uint32(13)) | (h >> np.uint32(19))).astype(np.uint32)
            h = h * np.uint32(5) + np.uint32(0xE6546B64)
        h ^= np.uint32(nwords * 4)
        h ^= h >> np.uint32(16)
        h *= _FMIX1
        h ^= h >> np.uint32(13)
        h *= _FMIX2
        h ^= h >> np.uint32(16)
    return h
