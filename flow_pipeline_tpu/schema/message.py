"""The FlowMessage record type.

One flow observation as emitted by a collector (sFlow/NetFlow/IPFIX decode)
or by the synthetic generator. The field set and proto3 field numbers are the
wire contract shared with the reference pipeline (ref: pb-ext/flow.proto:7-65);
every producer/consumer in this framework speaks exactly this schema so stock
components (GoFlow, ClickHouse Kafka-engine tables, the reference inserter)
interoperate with ours on the same bus.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class FlowType(enum.IntEnum):
    """Flow protocol that produced the record (ref: pb-ext/flow.proto:9-15)."""

    FLOWUNKNOWN = 0
    SFLOW_5 = 1
    NETFLOW_V5 = 2
    NETFLOW_V9 = 3
    IPFIX = 4


# Wire field table: (proto field number, attribute name, wire kind).
# Kind is either "varint" (all integer/enum fields) or "bytes" (addresses).
# Numbers must never change: they are the on-the-wire contract
# (ref: pb-ext/flow.proto:16-64).
FIELDS: tuple[tuple[int, str, str], ...] = (
    (1, "type", "varint"),
    (2, "time_received", "varint"),
    (3, "sampling_rate", "varint"),
    (4, "sequence_num", "varint"),
    (5, "time_flow_end", "varint"),
    (6, "src_addr", "bytes"),
    (7, "dst_addr", "bytes"),
    (9, "bytes", "varint"),
    (10, "packets", "varint"),
    (11, "sampler_address", "bytes"),
    (14, "src_as", "varint"),
    (15, "dst_as", "varint"),
    (18, "in_if", "varint"),
    (19, "out_if", "varint"),
    (20, "proto", "varint"),
    (21, "src_port", "varint"),
    (22, "dst_port", "varint"),
    (23, "ip_tos", "varint"),
    (24, "forwarding_status", "varint"),
    (25, "ip_ttl", "varint"),
    (26, "tcp_flags", "varint"),
    (30, "etype", "varint"),
    (31, "icmp_type", "varint"),
    (32, "icmp_code", "varint"),
    (37, "ipv6_flow_label", "varint"),
    (38, "time_flow_start", "varint"),
    (42, "flow_direction", "varint"),
)

FIELD_BY_NUMBER = {num: (name, kind) for num, name, kind in FIELDS}


@dataclass
class FlowMessage:
    """A single flow record. All integers are non-negative; addresses are
    16-byte strings (IPv4 embedded per the collector's convention: the
    reference stores IPv4 in the trailing 4 bytes of a FixedString(16),
    ref: compose/clickhouse/create.sh:44-45 + viz-ch.json IPv4 extraction).
    """

    type: int = FlowType.FLOWUNKNOWN
    time_received: int = 0
    sampling_rate: int = 0
    sequence_num: int = 0
    time_flow_start: int = 0
    time_flow_end: int = 0
    src_addr: bytes = b""
    dst_addr: bytes = b""
    sampler_address: bytes = b""
    bytes: int = 0
    packets: int = 0
    src_as: int = 0
    dst_as: int = 0
    in_if: int = 0
    out_if: int = 0
    proto: int = 0
    src_port: int = 0
    dst_port: int = 0
    ip_tos: int = 0
    forwarding_status: int = 0
    ip_ttl: int = 0
    tcp_flags: int = 0
    etype: int = 0
    icmp_type: int = 0
    icmp_code: int = 0
    ipv6_flow_label: int = 0
    flow_direction: int = 0
