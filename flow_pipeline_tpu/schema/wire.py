"""Protobuf wire codec for FlowMessage — dependency-free.

Implements just enough of the proto3 wire format (varints + length-delimited
bytes) to encode/decode FlowMessage records and the length-prefixed framing
the reference pipeline uses for ClickHouse consumption (the producer writes
"messages with their lengths", ref: mocker/mocker.go:95-102, README.md:104).

This pure-Python path is the correctness reference; the performance path for
bulk decode is the native C++ columnar decoder in ``native/`` (see
flow_pipeline_tpu.schema.batch.FlowBatch.from_wire).
"""

from __future__ import annotations

from .message import FlowMessage, FIELDS, FIELD_BY_NUMBER

_WT_VARINT = 0
_WT_LEN = 2


def _put_varint(out: bytearray, value: int) -> None:
    if value < 0:
        raise ValueError("varint fields must be non-negative")
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _get_varint(buf: bytes | memoryview, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            # Canonical proto parsers truncate to 64 bits; match them.
            return result & 0xFFFFFFFFFFFFFFFF, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def encode_message(msg: FlowMessage) -> bytes:
    """Serialize one FlowMessage. Proto3 semantics: zero/empty fields are
    omitted from the wire."""
    out = bytearray()
    for num, name, kind in FIELDS:
        value = getattr(msg, name)
        if kind == "varint":
            if value:
                _put_varint(out, (num << 3) | _WT_VARINT)
                _put_varint(out, int(value))
        else:
            if value:
                _put_varint(out, (num << 3) | _WT_LEN)
                _put_varint(out, len(value))
                out += value
    return bytes(out)


def decode_message(data: bytes | memoryview) -> FlowMessage:
    """Parse one FlowMessage. Unknown fields are skipped (forward compat);
    unknown wire types raise."""
    msg = FlowMessage()
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = _get_varint(data, pos)
        num, wt = tag >> 3, tag & 0x7
        if wt == _WT_VARINT:
            value, pos = _get_varint(data, pos)
            entry = FIELD_BY_NUMBER.get(num)
            if entry is not None and entry[1] == "varint":
                setattr(msg, entry[0], value)
        elif wt == _WT_LEN:
            length, pos = _get_varint(data, pos)
            if pos + length > n:
                raise ValueError("truncated length-delimited field")
            entry = FIELD_BY_NUMBER.get(num)
            if entry is not None and entry[1] == "bytes":
                setattr(msg, entry[0], bytes(data[pos : pos + length]))
            pos += length
        elif wt == 5:  # 32-bit, skip
            if pos + 4 > n:
                raise ValueError("truncated fixed32 field")
            pos += 4
        elif wt == 1:  # 64-bit, skip
            if pos + 8 > n:
                raise ValueError("truncated fixed64 field")
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
    return msg


def encode_frame(msg: FlowMessage) -> bytes:
    """Length-prefixed encoding (varint length + body) — the `proto.fixedlen`
    framing the reference enables for ClickHouse's Protobuf Kafka format
    (ref: mocker/mocker.go:95-102)."""
    body = encode_message(msg)
    out = bytearray()
    _put_varint(out, len(body))
    return bytes(out) + body


def encode_stream(msgs) -> bytes:
    """Concatenate length-prefixed frames for a sequence of messages."""
    out = bytearray()
    for m in msgs:
        out += encode_frame(m)
    return bytes(out)


def iter_raw_frames(data: bytes | memoryview):
    """Yield each length-prefixed frame's raw bytes (prefix included) without
    decoding — for splitting a frames stream onto a bus/partitions with one
    decode total downstream."""
    pos = 0
    n = len(data)
    view = memoryview(data)
    while pos < n:
        start = pos
        length, pos = _get_varint(view, pos)
        if pos + length > n:
            raise ValueError("truncated frame")
        pos += length
        yield bytes(view[start:pos])


def decode_frames(data: bytes | memoryview) -> list[FlowMessage]:
    """Parse a concatenation of length-prefixed FlowMessage frames."""
    msgs = []
    pos = 0
    n = len(data)
    view = memoryview(data)
    while pos < n:
        length, pos = _get_varint(view, pos)
        if pos + length > n:
            raise ValueError("truncated frame")
        msgs.append(decode_message(view[pos : pos + length]))
        pos += length
    return msgs
