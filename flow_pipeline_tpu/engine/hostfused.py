"""Host-grouped fused step: the CPU-backend twin of engine.fused.

Same model surface, same window lifecycle (it IS a FusedPipeline
subclass — update()'s slot/sub splitting and lifecycle advancement are
inherited untouched), different pre-aggregation substrate: batches are
grouped on the HOST with numpy (ops.hostgroup — ~20x cheaper than
XLA:CPU's single-threaded lax.sort on one core) and only the compact
group tables cross into the XLA step, which keeps what XLA is still
best at even on CPU: the CMS scatter updates, top-K table merges and
dense port scatters, in ONE dispatch per chunk.

Additional wins over the device-sorted path on CPU:

- flows_5m bypasses the device entirely: the host groupby is already
  exact in uint64, so rows fold straight into the window store
  (WindowAggregator.add_host_rows) — no 16-bit planes, no partial
  queue, no collision fallback machinery.
- Sketch families cascade: the finest key family (the 5-tuple top
  talkers) is grouped once from raw rows, and every family whose key
  set is a subset (src-IP, dst-IP) regroups the ~8-12k GROUP rows
  instead of 32k raw rows. The DDoS per-dst accumulate reads the dst
  family's table for free.
- Group tables are padded to a shared power-of-two bucket, so the XLA
  step sees a handful of static shapes and its CMS/top-K cost scales
  with actual batch cardinality, not the raw batch size.

Model selection lives in StreamWorker: host_assist="auto" picks this
pipeline iff the default backend is CPU ("on"/"off" force/forbid).
The TPU path is engine.fused, unchanged — this module is why the same
framework is honest on both: each backend gets the pre-aggregation its
memory hierarchy wants.

Equivalence vs the device-sorted pipeline (and transitively the
unfused per-model path) is proven in tests/test_hostfused.py, late
rows and window boundaries included.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import numpy as np

from ..ingest.shard import ShardPool, group_by_key_sharded, shared_pool
from ..models import heavy_hitter as hh
from ..models.ddos import _accumulate_grouped
from ..models.dense_top import dense_update
from ..models.spread import SpreadState, spread_key_width
from ..obs import REGISTRY, get_logger
from ..obs.tracing import StageTimer
from ..ops.hostgroup import native_group_available, select_lanes
from ..schema.batch import FlowBatch, lane_width
from .fused import FusedPipeline

log = get_logger("hostfused")

_DEGRADED_GAUGE = (
    "native_path_degraded",
    "1 when a requested native dataplane feature fell back to the slow "
    "path (label: feature) — benchmarks must check this is 0",
)


def report_native_degradation(feature: str, reason: str) -> None:
    """A requested native-dataplane feature falling back to numpy must be
    LOUD: a startup warning AND a scrapeable gauge. A log line alone let
    pre-r6 .so builds quietly serve numpy grouping under benchmarks that
    believed they measured the C kernel."""
    REGISTRY.gauge(*_DEGRADED_GAUGE).set(1, feature=feature)
    log.warning(
        "NATIVE PATH DEGRADED [%s]: %s — throughput from this process "
        "measures the fallback path; run `make native` (or rebuild the "
        "stale .so) for the fast path", feature, reason)


def mark_native_serving(feature: str) -> None:
    """Publish the healthy 0 explicitly so dashboards and bench capture
    can assert on the series instead of inferring from its absence."""
    REGISTRY.gauge(*_DEGRADED_GAUGE).set(0, feature=feature)


def _degradation_reason(symbol: str, since: str) -> str:
    from .. import native

    if not native.available():
        return "libflowdecode.so is not built or failed to load"
    return (f"loaded libflowdecode.so is stale (pre-{since}: "
            f"no {symbol} export)")


class PreparedChunk(NamedTuple):
    """Host pre-aggregation of one device-sized chunk — everything the
    apply half needs, with no model state touched yet. Group tables are
    computed UNCONDITIONALLY (the prepare stage cannot know whether the
    chunk's window is late until apply-time lifecycle advances); apply
    gates them with the do_hh/do_dd valid planes exactly like the serial
    path gates its device call."""
    wagg: list            # per wagg model: (keys, sums, counts) host rows
    hh_in: Optional[list]     # per hh family: (u [B,W], s [B,P+1], g)
    dense_in: Optional[tuple]  # (dcols padded, dvalid) or None
    ddos_in: Optional[tuple]   # (u [B,4], s [B], g) or None
    # fused dataplane (hostsketch/pipeline.py, -ingest.fused): per tree
    # (root lanes [N,W] u32, value planes [N,P] f32) — grouping, cascade
    # AND sketch updates all happen in ONE native pass at apply time, so
    # no hh group tables are materialized here. None = staged path.
    fused_in: Optional[list] = None
    # sketchwatch pre-extraction (obs/audit.py): per hh family
    # (name, (sampled rows, u64 addends) | None), computed on the
    # GROUP thread (pure hash+mask work) so the worker thread only pays
    # the uint64 fold. None = audit off, or an unsplit caller.
    audit_in: Optional[list] = None
    # flowspread (models/spread.py): per spread family
    # (pairs [G, kw+ew] u32 unique (key, element) rows,
    #  cand_keys [Gk, kw] u32, cand_counts [Gk] f32 per-key distinct-
    # pair counts — the table admission metric). Grouping to unique
    # pairs happens here on the group thread; the apply half only pays
    # the register scatter-max + table merge. None = no spread models.
    spread_in: Optional[list] = None


class PreparedBatch(NamedTuple):
    batch: FlowBatch      # original batch (offsets / archive_raw / metrics)
    parts: list           # [(slot, sub, n_rows, [PreparedChunk])]
    watermark: int

_U32_MAX = np.uint64(0xFFFFFFFF)


def _u32_lane(col: np.ndarray) -> np.ndarray:
    """One raw host column -> uint32 lane(s), saturating uint64 columns
    exactly like FlowBatch.device_columns (so host and device grouping
    see identical key/value words)."""
    if col.dtype == np.uint64:
        return np.minimum(col, _U32_MAX).astype(np.uint32)
    return col.astype(np.uint32, copy=False)


def _key_lanes_np(cols: dict, key_cols) -> np.ndarray:
    parts = []
    for name in key_cols:
        a = _u32_lane(cols[name])
        parts.append(a if a.ndim == 2 else a[:, None])
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)


def _fill_lanes(out: np.ndarray, off: int, lanes) -> int:
    """Write 1-D/2-D uint32 lane arrays into ``out`` columns starting at
    ``off``; returns the next free column. The ONE lane-layout fill loop
    (_key_lanes_into + _wagg_rows share it)."""
    for a in lanes:
        if a.ndim == 1:
            out[:, off] = a
            off += 1
        else:
            w = a.shape[1]
            out[:, off:off + w] = a
            off += w
    return off


def _key_lanes_into(cols: dict, key_cols) -> np.ndarray:
    """[N, W] uint32 key lanes written straight into ONE preallocated
    C-contiguous buffer — no per-lane ``[:, None]`` reshapes and no
    ``np.concatenate`` pass (ROADMAP 4a: the concat's temporaries were
    most of the residual host_group share on the fused leg, where lane
    extraction IS the prepare half). Same words as _key_lanes_np by
    construction; ``bench.py fused`` carries the paired A/B."""
    lanes = [_u32_lane(cols[name]) for name in key_cols]
    n = lanes[0].shape[0]
    total = sum(1 if a.ndim == 1 else a.shape[1] for a in lanes)
    out = np.empty((n, total), np.uint32)
    _fill_lanes(out, 0, lanes)
    return out


def _value_planes_np(cols: dict, value_cols,
                     scale_col: str | None = None) -> np.ndarray:
    """[N, P] float32 value planes with the device path's u32 saturation,
    multiplied by max(<scale_col>, 1) when sampling scaling is on (same
    f32 factor the device step applies)."""
    planes = np.stack([_u32_lane(cols[name]).astype(np.float32)
                       for name in value_cols], axis=1)
    if scale_col:
        r = np.maximum(_u32_lane(cols[scale_col]).astype(np.float32), 1.0)
        planes = planes * r[:, None]
    return planes


def _pow2_bucket(n: int, hi: int, lo: int = 1024) -> int:
    """Smallest power-of-two >= n in [lo, hi]; hi must be >= any possible
    n (callers pass the chunk size — a chunk of N rows cannot group into
    more than N rows)."""
    b = lo
    while b < n and b < hi:
        b <<= 1
    return b


@functools.lru_cache(maxsize=None)
def _cached_apply(hh_cfgs: tuple, dense_cfgs: tuple, ddos_cfgs: tuple):
    """One jitted state-update step over pre-grouped inputs.

    hh_in:   tuple of (uniq [B, W] u32, sums3 [B, P+1] f32, valid [B])
    dense_in: (cols dict of [Nd] int32, valid [Nd]) or None
    ddos_in: (uniq [B, 4] u32, sums [B] f32, valid [B]) or None

    Module-cached on the static config spec exactly like
    engine.fused._cached_step — rebuilt pipelines must share the
    compiled program.
    """

    def apply(states, hh_in, dense_in, ddos_in):
        hh_states, dense_tots, ddos_states = states
        new_hh = tuple(
            hh._apply_grouped(st, u, s, v, cfg)
            for st, (u, s, v), cfg in zip(hh_states, hh_in, hh_cfgs)
        )
        new_dense = dense_tots
        if dense_in is not None:
            dcols, dvalid = dense_in
            new_dense = tuple(
                dense_update(t, dcols, dvalid, config=c)
                for t, c in zip(dense_tots, dense_cfgs)
            )
        new_ddos = tuple(
            _accumulate_grouped(st, ddos_in[0], ddos_in[1], ddos_in[2], cfg)
            for st, cfg in zip(ddos_states, ddos_cfgs)
        ) if ddos_in is not None else ddos_states
        return new_hh, new_dense, new_ddos

    return jax.jit(apply, donate_argnums=(0,))


class HostGroupPipeline(FusedPipeline):
    """FusedPipeline with host (numpy) pre-aggregation — CPU backend."""

    @staticmethod
    def eligible(mode: str = "auto") -> bool:
        """Whether this pipeline should be picked over engine.fused.
        "auto" -> only when the default backend is CPU (the whole premise
        is that host memory IS device memory there)."""
        if mode == "on":
            return True
        if mode == "off":
            return False
        if mode != "auto":
            raise ValueError(
                f"host_assist must be auto|on|off, got {mode!r}")
        return jax.default_backend() == "cpu"

    def __init__(self, models: dict, shards: int = 0,
                 native_group: bool = False,
                 pool: Optional[ShardPool] = None,
                 audit: str = "off"):
        super().__init__(models)
        self.stages = StageTimer()
        # sketchwatch (-obs.audit, obs/audit.py): the sampled exact
        # shadow audit rides the host-grouped pipelines — observation
        # consumes the group tables (staged) or raw lanes (fused) this
        # pipeline already materializes, and window closes seal the
        # cohort via the wrapped models' audit_hook. Purely
        # observational: `make audit-parity` pins audit-on/off sink
        # rows bit-exact.
        if audit not in ("off", "sample", "full"):
            raise ValueError(
                f"audit must be off|sample|full, got {audit!r}")
        self.audit = None
        if audit != "off" and self._hh:
            from ..obs.audit import SketchAudit

            self.audit = SketchAudit(
                {name: (w.config, w.k) for name, w in self._hh},
                mode=audit)
            for name, w in self._hh:
                w.audit_hook = self._audit_close_hook(name)
        # flowspread shadow: exact distinct SETS per sampled key (the
        # set insert is idempotent, so the shadow shares the registers'
        # order-freedom). Same mode knob, same ~1/256 protocol sampler.
        self.spread_audit = None
        if audit != "off" and self._spread:
            from ..obs.audit import SpreadAudit

            self.spread_audit = SpreadAudit(
                {name: w.config for name, w in self._spread}, mode=audit)
            for name, w in self._spread:
                w.audit_hook = self._spread_close_hook(name)
        # Grouping backends (ingest runtime knobs): shards=1 disables the
        # sharded path entirely; 0 sizes it to the pool. native_group
        # requests the C hash-group kernel and quietly degrades to numpy
        # when the library is unbuilt — record which backend actually
        # serves so operators can tell from the log.
        self._native = native_group and native_group_available()
        if native_group and not self._native:
            report_native_degradation(
                "group", _degradation_reason("flow_hash_group", "r6"))
        elif native_group:
            mark_native_serving("group")
        self._shards = shards
        self._pool = None if shards == 1 else (pool or shared_pool())
        # flowspread fold knobs: the staged pipeline folds the register
        # scatter single-threaded on the worker thread with no stats
        # buffer; HostSketchPipeline._init_family_folds raises the thread
        # count to its engine's and attaches a flowtrace buffer (the
        # native kernel's per-depth ownership keeps ANY count bit-exact).
        self._spread_threads = 1
        self._spread_stats = None
        self._widths = {}
        # Sketch-family plan: group the maximal key families from raw
        # rows; regroup every strict-subset family (equal value planes)
        # from its parent's ~10x smaller group table.
        cfgs = [w.config for _, w in self._hh]
        for c in cfgs:
            for name in c.key_cols:
                self._widths[name] = lane_width(name)
        order = sorted(range(len(cfgs)),
                       key=lambda i: -len(cfgs[i].key_cols))
        self._fam_plan: list[tuple] = [()] * len(cfgs)
        planned: list[int] = []
        for i in order:
            parent = None
            for j in planned:
                if (set(cfgs[i].key_cols) < set(cfgs[j].key_cols)
                        and tuple(cfgs[i].value_cols)
                        == tuple(cfgs[j].value_cols)
                        and cfgs[i].scale_col == cfgs[j].scale_col):
                    if parent is None or len(cfgs[j].key_cols) < len(
                            cfgs[parent].key_cols):
                        parent = j
            if parent is None:
                self._fam_plan[i] = ("own",)
            else:
                sel = select_lanes(cfgs[parent].key_cols, self._widths,
                                   cfgs[i].key_cols)
                self._fam_plan[i] = ("cascade", parent, tuple(sel))
            planned.append(i)
        # DDoS per-dst sums: ride a family whose keys include dst_addr
        # and whose value planes carry the detector's value column.
        self._ddos_plan = None
        if self._ddos:
            dcfg = self._ddos[0][1].config
            for j, c in enumerate(cfgs):
                if ("dst_addr" in c.key_cols
                        and dcfg.value_col in c.value_cols
                        and c.scale_col == dcfg.scale_col):
                    self._ddos_plan = (
                        "cascade", j,
                        tuple(select_lanes(c.key_cols, {
                            **self._widths, "dst_addr": 4}, ("dst_addr",))),
                        c.value_cols.index(dcfg.value_col),
                    )
                    break
            if self._ddos_plan is None:
                self._ddos_plan = ("own",)
        self._apply = _cached_apply(
            tuple(w.config for _, w in self._hh),
            tuple(w.config for _, w in self._dense),
            tuple(d.config for _, d in self._ddos),
        )

    # ---- prepare half: pure host pre-aggregation ---------------------------
    #
    # prepare() touches NO model state, so the ingest executor runs it on
    # its group thread while the worker thread applies the previous
    # batch. update() = apply(prepare()) keeps the serial path the same
    # code — pipelined and serial modes cannot drift apart.

    def prepare(self, batch: FlowBatch) -> Optional[PreparedBatch]:
        if len(batch) == 0:
            return None
        parts, wm = self._split_parts(batch)
        out_parts = []
        with self.stages.stage("host_group"):
            for slot, sub, part in parts:
                chunks = []
                bs = self._bs
                for start in range(0, len(part), bs):
                    chunk = part.slice(start, start + bs)
                    chunks.append(self._prepare_chunk(
                        chunk.columns, len(chunk)))
                out_parts.append((slot, sub, len(part), chunks))
        return PreparedBatch(batch, out_parts, wm)

    def _prepare_chunk(self, cols: dict, n: int) -> PreparedChunk:
        # flows_5m: exact uint64 groupby straight into the window store —
        # no device partials on this path
        wagg = [self._wagg_rows(m, cols, n) for _, m in self._waggs]
        spread_in = self._prep_spread(cols) if self._spread else None
        if not (self._hh or self._dense or self._ddos):
            return PreparedChunk(wagg, None, None, None,
                                 spread_in=spread_in)
        fams = (self._group_families(cols)
                if (self._hh or self._ddos) else None)
        prep = PreparedChunk(wagg, *self._prep_device(cols, fams, n),
                             spread_in=spread_in)
        if self.audit is not None and prep.hh_in is not None:
            # audit pre-extraction rides the prepare half (group
            # thread) exactly like the tables it samples from
            prep = prep._replace(audit_in=[
                (name, self.audit.prepare_grouped(name, u, s, g))
                for (name, _), (u, s, g) in zip(self._hh, prep.hh_in)])
        return prep

    def _group(self, lanes, planes, exact):
        return group_by_key_sharded(lanes, planes, self._pool,
                                    self._shards, exact=exact,
                                    native=self._native)

    def _wagg_rows(self, m, cols: dict, n: int):
        lanes, planes = self._build_wagg_inputs(m.config, cols, n)
        return self._group_exact_planes(lanes, planes)

    def _prep_spread(self, cols: dict) -> list:
        """Per spread family: group the chunk to unique (key, element)
        pair rows — the registers' input; the max monoid makes the
        pre-grouping bit-identical to raw-row updates — then regroup
        the keys for the per-chunk distinct-pair admission metric.
        Backend-dependent group ORDER is irrelevant: the register fold
        is an order-free max and the table merge lex-groups its
        candidates, so sharded/native/numpy grouping all land the same
        state (the argument tests/test_spread.py pins down)."""
        out = []
        for name, w in self._spread:
            cfg = w.config
            kw = spread_key_width(cfg)
            pair_lanes = self._build_key_lanes(
                cols, (*cfg.key_cols, cfg.elem_col))
            pairs, _, _ = self._group(pair_lanes, [], exact=False)
            pairs = np.ascontiguousarray(pairs, dtype=np.uint32)
            cand_keys, _, pair_counts = self._group(
                np.ascontiguousarray(pairs[:, :kw]), [], exact=False)
            aud = (self.spread_audit.prepare_pairs(name, pairs)
                   if self.spread_audit is not None
                   and not self.spread_audit.paused else None)
            out.append((pairs,
                        np.ascontiguousarray(cand_keys, np.uint32),
                        pair_counts.astype(np.float32),
                        aud))
        return out

    # ---- lane building seams (r19 flowspeed) -------------------------------
    #
    # The three lane layouts the prepare half extracts from decoded
    # columns, behind override points so the hostsketch pipeline can
    # route them through the native ff_build_lanes / ff_build_planes
    # kernels. These numpy bodies are the bit-exact twins AND the
    # fallback when the library predates the lane builders — parity is
    # pinned by tests/test_hostfused.py TestLaneBuilders.

    def _build_key_lanes(self, cols: dict, key_cols) -> np.ndarray:
        return _key_lanes_into(cols, key_cols)

    def _build_value_planes(self, cols: dict, value_cols,
                            scale_col) -> np.ndarray:
        return np.ascontiguousarray(
            _value_planes_np(cols, value_cols, scale_col),
            dtype=np.float32)

    def _build_wagg_inputs(self, cfg, cols: dict, n: int):
        """(lanes [N, 1+W(+1)] u32, planes [N, P] u64-saturated) for one
        wagg model: slot first, key lanes, rate lane LAST, matching
        group_cols(cfg) — lanes filled straight into one preallocated
        buffer (the no-concat discipline of _key_lanes_into)."""
        t = np.minimum(cols["time_received"], _U32_MAX).astype(np.uint32)
        slot = t - t % np.uint32(cfg.window_seconds)
        key_lanes = [_u32_lane(cols[name]) for name in cfg.key_cols]
        total = 1 + sum(1 if a.ndim == 1 else a.shape[1]
                        for a in key_lanes) + (1 if cfg.scale_col else 0)
        lanes = np.empty((n, total), np.uint32)
        lanes[:, 0] = slot
        off = _fill_lanes(lanes, 1, key_lanes)
        if cfg.scale_col:
            lanes[:, off] = _u32_lane(cols[cfg.scale_col])
        planes = [np.minimum(cols[name], _U32_MAX) for name in cfg.value_cols]
        return lanes, np.stack(planes, axis=1)

    def _group_exact_planes(self, lanes: np.ndarray, planes: np.ndarray):
        """Exact groupby-sum of stacked [N, P] uint64 planes — the
        flows_5m substrate. Seam: the fused pipeline overrides this with
        the single-pass ff_group_sum kernel."""
        uniq, sums, counts = self._group(lanes, [planes], exact=True)
        return uniq, sums[0], counts

    def _group_families(self, cols: dict) -> list[tuple]:
        """Per-hh-family (uniq [G,W] u32, vsum [G,P] f64, cnt [G]) plus the
        DDoS per-dst tuple appended last when planned."""
        out: list = [None] * len(self._hh)
        for i, (plan, (_, w)) in enumerate(
                zip(self._fam_plan, self._hh)):
            if plan[0] != "own":
                continue
            cfg = w.config
            lanes = self._build_key_lanes(cols, cfg.key_cols)
            vals = self._build_value_planes(cols, cfg.value_cols,
                                            cfg.scale_col)
            uniq, sums, counts = self._group(lanes, [vals], exact=False)
            out[i] = (uniq, sums[0], counts)
        for i, plan in enumerate(self._fam_plan):
            if plan[0] != "cascade":
                continue
            _, parent, sel = plan
            p_uniq, p_vsum, p_cnt = out[parent]
            uniq, sums, _ = self._group(
                p_uniq[:, list(sel)], [p_vsum, p_cnt], exact=False)
            out[i] = (uniq, sums[0], sums[1].astype(np.int64))
        if self._ddos_plan is not None:
            dcfg = self._ddos[0][1].config
            if self._ddos_plan[0] == "cascade":
                _, parent, sel, plane = self._ddos_plan
                p_uniq, p_vsum, p_cnt = out[parent]
                uniq, sums, _ = self._group(
                    p_uniq[:, list(sel)], [p_vsum[:, plane]], exact=False)
                out.append((uniq, sums[0].astype(np.float32)))
            else:
                lanes = self._build_key_lanes(cols, ("dst_addr",))
                vals = self._build_value_planes(
                    cols, (dcfg.value_col,), dcfg.scale_col)[:, 0]
                uniq, sums, _ = self._group(lanes, [vals], exact=False)
                out.append((uniq, sums[0].astype(np.float32)))
        return out

    def _prep_device(self, cols: dict, fams, n: int):
        """Pad group tables / dense columns to their static shapes —
        the host half of the device step. Valid planes are NOT built
        here: they depend on apply-time lifecycle (do_hh / do_dd).

        Buckets are PER FAMILY (not the old shared max): a cascade family
        (src/dst IPs) typically groups 3-4x smaller than the 5-tuple
        talkers, and the CMS scatter + merge cost scales with padded
        rows — sharing the talkers' bucket made every family pay the
        largest family's price. Each family still draws from the same
        handful of power-of-two shapes, so the jit cache stays small."""
        hi = max(self._bs, 1024)
        hh_in = []
        for i, (_, w) in enumerate(self._hh):
            uniq, vsum, cnt = fams[i]
            g = uniq.shape[0]
            B = _pow2_bucket(g, hi=hi)
            W = uniq.shape[1]
            P = vsum.shape[1]
            u = np.zeros((B, W), np.uint32)
            s = np.zeros((B, P + 1), np.float32)
            u[:g] = uniq
            s[:g, :P] = vsum
            s[:g, P] = cnt
            hh_in.append((u, s, g))
        ddos_in = None
        if self._ddos_plan is not None:
            uniq, dsum = fams[-1]
            ddos_in = self._pad_ddos(uniq, dsum)
        return hh_in, self._prep_dense(cols, n), ddos_in

    def _prep_dense(self, cols: dict, n: int):
        """Dense-model columns padded to the static batch shape (shared
        by the staged and fused prepare halves)."""
        if not self._dense:
            return None
        need = set()
        for _, w in self._dense:
            need.add(w.config.key_col)
            need.update(w.config.value_cols)
            if w.config.scale_col:
                need.add(w.config.scale_col)
        bs = self._bs
        dcols = {}
        for name in need:
            src = _u32_lane(cols[name])
            a = np.zeros(bs, np.uint32)
            a[:n] = src
            dcols[name] = a.view(np.int32)
        dvalid = np.zeros(bs, bool)
        dvalid[:n] = True
        return (dcols, dvalid)

    def _pad_ddos(self, uniq: np.ndarray, dsum: np.ndarray):
        """Pad a per-dst group table to its power-of-two bucket for the
        jitted accumulate (shared by the staged prepare and the fused
        apply, which receives the table from the native pass)."""
        g = uniq.shape[0]
        B = _pow2_bucket(g, hi=max(self._bs, 1024))
        u = np.zeros((B, 4), np.uint32)
        s = np.zeros(B, np.float32)
        u[:g] = uniq
        s[:g] = dsum
        return (u, s, g)

    # ---- apply half: lifecycle + model state -------------------------------

    def apply(self, prep: Optional[PreparedBatch]) -> None:
        """Advance window lifecycles and fold one prepared batch into the
        models. Must run on the thread that owns model state (the worker
        thread, under its lock), in batch order."""
        if prep is None:
            return
        for slot, sub, n_rows, chunks in prep.parts:
            do_hh = self._advance_hh(slot, n_rows)
            do_dd = self._advance_ddos(sub, n_rows)
            for ch in chunks:
                for (_, m), rows in zip(self._waggs, ch.wagg):
                    m.add_host_rows(*rows)
                if not (do_hh or do_dd):
                    continue  # late part: device models take nothing
                if do_hh and ch.spread_in is not None:
                    self._fold_spread(ch)
                if ch.hh_in is None and ch.dense_in is None \
                        and ch.ddos_in is None and ch.fused_in is None:
                    continue
                self._timed_apply_chunk(ch, do_hh, do_dd)
                if do_hh and self.audit is not None:
                    # after the fold, mirroring the sketch's own gating:
                    # the shadow cohort covers exactly the rows the
                    # sketches took (late parts fold nowhere). Timed as
                    # its own stage: the audit's budget is measured
                    # in-run (share of wall), not inferred from paired
                    # A/B legs a 2-core box's frequency drift swamps
                    self._audit_chunk_timed(ch)
        for _, m in self._waggs:
            if prep.watermark > m.watermark:
                m.watermark = prep.watermark

    def update(self, batch: FlowBatch) -> None:
        self.apply(self.prepare(batch))

    def _fold_spread(self, ch: PreparedChunk) -> None:
        """Fold one chunk's prepared pair tables into the spread models
        (worker thread — mutates model state, like every apply).
        spread_apply_update routes the register scatter through the
        native hs_spread_update kernel when the library exports it, the
        numpy twin otherwise — either way bit-identical to
        SpreadModel.update over the same chunk, which is the parity
        anchor tests/test_spread.py pins."""
        from ..hostsketch.engine import (
            np_spread_table_merge,
            spread_apply_update,
        )

        with self.stages.stage("host_spread"):
            for (name, w), (pairs, cand_keys, cand_counts, aud) in zip(
                    self._spread, ch.spread_in):
                m = w.model
                kw = spread_key_width(w.config)
                spread_apply_update(m.state.regs, pairs[:, :kw],
                                    pairs[:, kw:],
                                    threads=self._spread_threads,
                                    stats=self._spread_stats)
                tk, tm = np_spread_table_merge(
                    m.state.table_keys, m.state.table_metric,
                    cand_keys, cand_counts)
                m.state = SpreadState(m.state.regs, tk, tm)
                if aud is not None:
                    self.spread_audit.fold_prepared(name, aud)

    # ---- sketchwatch hooks -------------------------------------------------

    def _audit_close_hook(self, name: str):
        """Per-family window-close hook handed to the wrapped model:
        seals the sampled cohort against the closing state (or ships it
        to the mesh member's capture)."""
        def hook(slot, model):
            # its own stage, separate from the per-chunk observation:
            # the close evaluation (CMS freeze + fill scan + report) is
            # a once-per-WINDOW lump, not a continuous hot-path tax —
            # budgeting them together would charge a 300s window's
            # close against whatever wall the bench stream compressed
            # that window into
            with self.stages.stage("sketch_audit_close"):
                self.audit.on_close(name, slot, model)
        return hook

    def _spread_close_hook(self, name: str):
        """Window-close seal for a spread family: decode the closing
        registers against the exact distinct sets accumulated for the
        sampled cohort and publish the error histogram."""
        def hook(slot, model):
            with self.stages.stage("sketch_audit_close"):
                self.spread_audit.on_close(name, slot, model)
        return hook

    def _audit_chunk_timed(self, ch: PreparedChunk) -> None:
        with self.stages.stage("sketch_audit"):
            self._audit_chunk(ch)

    def _audit_chunk(self, ch: PreparedChunk) -> None:
        """Feed one applied chunk to the shadow audit: fold the
        pre-extracted cohort rows when the prepare half supplied them,
        else extract here (serial/unsplit callers). The staged tables
        carry group-summed planes; the audit's uint64 fold makes the
        granularity irrelevant on the exact envelope."""
        if ch.audit_in is not None:
            for name, prepared in ch.audit_in:
                self.audit.fold_prepared(name, prepared)
            return
        if ch.hh_in is None:
            return
        for (name, _), (u, s, g) in zip(self._hh, ch.hh_in):
            self.audit.observe_grouped(name, u, s, g)

    def _timed_apply_chunk(self, ch: PreparedChunk, do_hh: bool,
                           do_dd: bool) -> None:
        """Stage attribution seam: here the whole chunk apply IS the
        jitted device step. The hostsketch pipeline overrides this to
        split its chunk between host_sketch (the native engine) and
        device_apply (what remains jitted), so the two backends' stage
        budgets stay comparable per stage."""
        with self.stages.stage("device_apply"):
            self._apply_chunk(ch, do_hh, do_dd)

    def _apply_chunk(self, ch: PreparedChunk, do_hh: bool,
                     do_dd: bool) -> None:
        hh_in = []
        for u, s, g in ch.hh_in:
            v = np.zeros(u.shape[0], bool)
            v[:g] = do_hh
            hh_in.append((u, s, v))
        dense_in = ch.dense_in if (self._dense and do_hh) else None
        ddos_in = None
        if ch.ddos_in is not None:
            u, s, g = ch.ddos_in
            v = np.zeros(u.shape[0], bool)
            v[:g] = do_dd
            ddos_in = (u, s, v)
        states = (
            tuple(w.model.state for _, w in self._hh),
            tuple(w.model.totals for _, w in self._dense),
            tuple(d.state for _, d in self._ddos),
        )
        new_hh, new_dense, new_ddos = self._apply(
            states, tuple(hh_in), dense_in, ddos_in)
        for (_, w), st in zip(self._hh, new_hh):
            w.model.state = st
        if dense_in is not None:
            for (_, w), tot in zip(self._dense, new_dense):
                w.model.totals = tot
        for (_, d), st in zip(self._ddos, new_ddos):
            d.state = st
