"""Host-grouped fused step: the CPU-backend twin of engine.fused.

Same model surface, same window lifecycle (it IS a FusedPipeline
subclass — update()'s slot/sub splitting and lifecycle advancement are
inherited untouched), different pre-aggregation substrate: batches are
grouped on the HOST with numpy (ops.hostgroup — ~20x cheaper than
XLA:CPU's single-threaded lax.sort on one core) and only the compact
group tables cross into the XLA step, which keeps what XLA is still
best at even on CPU: the CMS scatter updates, top-K table merges and
dense port scatters, in ONE dispatch per chunk.

Additional wins over the device-sorted path on CPU:

- flows_5m bypasses the device entirely: the host groupby is already
  exact in uint64, so rows fold straight into the window store
  (WindowAggregator.add_host_rows) — no 16-bit planes, no partial
  queue, no collision fallback machinery.
- Sketch families cascade: the finest key family (the 5-tuple top
  talkers) is grouped once from raw rows, and every family whose key
  set is a subset (src-IP, dst-IP) regroups the ~8-12k GROUP rows
  instead of 32k raw rows. The DDoS per-dst accumulate reads the dst
  family's table for free.
- Group tables are padded to a shared power-of-two bucket, so the XLA
  step sees a handful of static shapes and its CMS/top-K cost scales
  with actual batch cardinality, not the raw batch size.

Model selection lives in StreamWorker: host_assist="auto" picks this
pipeline iff the default backend is CPU ("on"/"off" force/forbid).
The TPU path is engine.fused, unchanged — this module is why the same
framework is honest on both: each backend gets the pre-aggregation its
memory hierarchy wants.

Equivalence vs the device-sorted pipeline (and transitively the
unfused per-model path) is proven in tests/test_hostfused.py, late
rows and window boundaries included.
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from ..models import heavy_hitter as hh
from ..models.ddos import _accumulate_grouped
from ..models.dense_top import dense_update
from ..obs import get_logger
from ..obs.tracing import StageTimer
from ..ops.hostgroup import group_by_key, select_lanes
from ..schema.batch import FlowBatch, lane_width
from .fused import FusedPipeline

log = get_logger("hostfused")

_U32_MAX = np.uint64(0xFFFFFFFF)


def _u32_lane(col: np.ndarray) -> np.ndarray:
    """One raw host column -> uint32 lane(s), saturating uint64 columns
    exactly like FlowBatch.device_columns (so host and device grouping
    see identical key/value words)."""
    if col.dtype == np.uint64:
        return np.minimum(col, _U32_MAX).astype(np.uint32)
    return col.astype(np.uint32, copy=False)


def _key_lanes_np(cols: dict, key_cols) -> np.ndarray:
    parts = []
    for name in key_cols:
        a = _u32_lane(cols[name])
        parts.append(a if a.ndim == 2 else a[:, None])
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)


def _value_planes_np(cols: dict, value_cols,
                     scale_col: str | None = None) -> np.ndarray:
    """[N, P] float32 value planes with the device path's u32 saturation,
    multiplied by max(<scale_col>, 1) when sampling scaling is on (same
    f32 factor the device step applies)."""
    planes = np.stack([_u32_lane(cols[name]).astype(np.float32)
                       for name in value_cols], axis=1)
    if scale_col:
        r = np.maximum(_u32_lane(cols[scale_col]).astype(np.float32), 1.0)
        planes = planes * r[:, None]
    return planes


def _pow2_bucket(n: int, hi: int, lo: int = 1024) -> int:
    """Smallest power-of-two >= n in [lo, hi]; hi must be >= any possible
    n (callers pass the chunk size — a chunk of N rows cannot group into
    more than N rows)."""
    b = lo
    while b < n and b < hi:
        b <<= 1
    return b


@functools.lru_cache(maxsize=None)
def _cached_apply(hh_cfgs: tuple, dense_cfgs: tuple, ddos_cfgs: tuple):
    """One jitted state-update step over pre-grouped inputs.

    hh_in:   tuple of (uniq [B, W] u32, sums3 [B, P+1] f32, valid [B])
    dense_in: (cols dict of [Nd] int32, valid [Nd]) or None
    ddos_in: (uniq [B, 4] u32, sums [B] f32, valid [B]) or None

    Module-cached on the static config spec exactly like
    engine.fused._cached_step — rebuilt pipelines must share the
    compiled program.
    """

    def apply(states, hh_in, dense_in, ddos_in):
        hh_states, dense_tots, ddos_states = states
        new_hh = tuple(
            hh._apply_grouped(st, u, s, v, cfg)
            for st, (u, s, v), cfg in zip(hh_states, hh_in, hh_cfgs)
        )
        new_dense = dense_tots
        if dense_in is not None:
            dcols, dvalid = dense_in
            new_dense = tuple(
                dense_update(t, dcols, dvalid, config=c)
                for t, c in zip(dense_tots, dense_cfgs)
            )
        new_ddos = tuple(
            _accumulate_grouped(st, ddos_in[0], ddos_in[1], ddos_in[2], cfg)
            for st, cfg in zip(ddos_states, ddos_cfgs)
        ) if ddos_in is not None else ddos_states
        return new_hh, new_dense, new_ddos

    return jax.jit(apply, donate_argnums=(0,))


class HostGroupPipeline(FusedPipeline):
    """FusedPipeline with host (numpy) pre-aggregation — CPU backend."""

    @staticmethod
    def eligible(mode: str = "auto") -> bool:
        """Whether this pipeline should be picked over engine.fused.
        "auto" -> only when the default backend is CPU (the whole premise
        is that host memory IS device memory there)."""
        if mode == "on":
            return True
        if mode == "off":
            return False
        if mode != "auto":
            raise ValueError(
                f"host_assist must be auto|on|off, got {mode!r}")
        return jax.default_backend() == "cpu"

    def __init__(self, models: dict):
        super().__init__(models)
        self.stages = StageTimer()
        self._widths = {}
        # Sketch-family plan: group the maximal key families from raw
        # rows; regroup every strict-subset family (equal value planes)
        # from its parent's ~10x smaller group table.
        cfgs = [w.config for _, w in self._hh]
        for c in cfgs:
            for name in c.key_cols:
                self._widths[name] = lane_width(name)
        order = sorted(range(len(cfgs)),
                       key=lambda i: -len(cfgs[i].key_cols))
        self._fam_plan: list[tuple] = [()] * len(cfgs)
        planned: list[int] = []
        for i in order:
            parent = None
            for j in planned:
                if (set(cfgs[i].key_cols) < set(cfgs[j].key_cols)
                        and tuple(cfgs[i].value_cols)
                        == tuple(cfgs[j].value_cols)
                        and cfgs[i].scale_col == cfgs[j].scale_col):
                    if parent is None or len(cfgs[j].key_cols) < len(
                            cfgs[parent].key_cols):
                        parent = j
            if parent is None:
                self._fam_plan[i] = ("own",)
            else:
                sel = select_lanes(cfgs[parent].key_cols, self._widths,
                                   cfgs[i].key_cols)
                self._fam_plan[i] = ("cascade", parent, tuple(sel))
            planned.append(i)
        # DDoS per-dst sums: ride a family whose keys include dst_addr
        # and whose value planes carry the detector's value column.
        self._ddos_plan = None
        if self._ddos:
            dcfg = self._ddos[0][1].config
            for j, c in enumerate(cfgs):
                if ("dst_addr" in c.key_cols
                        and dcfg.value_col in c.value_cols
                        and c.scale_col == dcfg.scale_col):
                    self._ddos_plan = (
                        "cascade", j,
                        tuple(select_lanes(c.key_cols, {
                            **self._widths, "dst_addr": 4}, ("dst_addr",))),
                        c.value_cols.index(dcfg.value_col),
                    )
                    break
            if self._ddos_plan is None:
                self._ddos_plan = ("own",)
        self._apply = _cached_apply(
            tuple(w.config for _, w in self._hh),
            tuple(w.config for _, w in self._dense),
            tuple(d.config for _, d in self._ddos),
        )

    # ---- per-chunk work ----------------------------------------------------

    def _run_chunks(self, part: FlowBatch, do_hh: bool, do_dd: bool) -> None:
        bs = self._bs
        for start in range(0, len(part), bs):
            chunk = part.slice(start, start + bs)
            cols = chunk.columns
            n = len(chunk)
            with self.stages.stage("host_group"):
                # flows_5m: exact uint64 groupby straight into the window
                # store — no device partials on this path
                for _, m in self._waggs:
                    self._wagg_rows(m, cols, n)
                fams = self._group_families(cols) \
                    if (do_hh or do_dd) and (self._hh or self._ddos) else None
            if not (do_hh or do_dd) or not (
                    self._hh or self._dense or self._ddos):
                continue
            with self.stages.stage("device_apply"):
                self._device_apply(chunk, cols, fams, do_hh, do_dd, n)

    def _wagg_rows(self, m, cols: dict, n: int) -> None:
        cfg = m.config
        t = np.minimum(cols["time_received"], _U32_MAX).astype(np.uint32)
        slot = t - t % np.uint32(cfg.window_seconds)
        lanes = [slot[:, None]]
        for name in cfg.key_cols:
            a = _u32_lane(cols[name])
            lanes.append(a if a.ndim == 2 else a[:, None])
        if cfg.scale_col:  # rate lane LAST, matching group_cols(cfg)
            lanes.append(_u32_lane(cols[cfg.scale_col])[:, None])
        lanes = np.concatenate(lanes, axis=1)
        planes = [np.minimum(cols[name], _U32_MAX) for name in cfg.value_cols]
        uniq, sums, counts = group_by_key(lanes, [np.stack(planes, axis=1)])
        m.add_host_rows(uniq, sums[0], counts)

    def _group_families(self, cols: dict) -> list[tuple]:
        """Per-hh-family (uniq [G,W] u32, vsum [G,P] f64, cnt [G]) plus the
        DDoS per-dst tuple appended last when planned."""
        out: list = [None] * len(self._hh)
        for i, (plan, (_, w)) in enumerate(
                zip(self._fam_plan, self._hh)):
            if plan[0] != "own":
                continue
            cfg = w.config
            lanes = _key_lanes_np(cols, cfg.key_cols)
            vals = _value_planes_np(cols, cfg.value_cols, cfg.scale_col)
            uniq, sums, counts = group_by_key(lanes, [vals], exact=False)
            out[i] = (uniq, sums[0], counts)
        for i, plan in enumerate(self._fam_plan):
            if plan[0] != "cascade":
                continue
            _, parent, sel = plan
            p_uniq, p_vsum, p_cnt = out[parent]
            uniq, sums, _ = group_by_key(
                p_uniq[:, list(sel)], [p_vsum, p_cnt], exact=False)
            out[i] = (uniq, sums[0], sums[1].astype(np.int64))
        if self._ddos_plan is not None:
            dcfg = self._ddos[0][1].config
            if self._ddos_plan[0] == "cascade":
                _, parent, sel, plane = self._ddos_plan
                p_uniq, p_vsum, p_cnt = out[parent]
                uniq, sums, _ = group_by_key(
                    p_uniq[:, list(sel)], [p_vsum[:, plane]], exact=False)
                out.append((uniq, sums[0].astype(np.float32)))
            else:
                lanes = _key_lanes_np(cols, ("dst_addr",))
                vals = _value_planes_np(cols, (dcfg.value_col,),
                                        dcfg.scale_col)[:, 0]
                uniq, sums, _ = group_by_key(lanes, [vals], exact=False)
                out.append((uniq, sums[0].astype(np.float32)))
        return out

    def _device_apply(self, chunk: FlowBatch, cols: dict, fams,
                      do_hh: bool, do_dd: bool, n: int) -> None:
        sizes = [1024]
        if self._hh:
            sizes += [f[0].shape[0] for f in fams[:len(self._hh)]]
        if self._ddos_plan is not None:
            sizes.append(fams[-1][0].shape[0])
        B = _pow2_bucket(max(sizes), hi=max(self._bs, 1024))
        hh_in = []
        for i, (_, w) in enumerate(self._hh):
            uniq, vsum, cnt = fams[i]
            g = uniq.shape[0]
            W = uniq.shape[1]
            P = vsum.shape[1]
            u = np.zeros((B, W), np.uint32)
            s = np.zeros((B, P + 1), np.float32)
            u[:g] = uniq
            s[:g, :P] = vsum
            s[:g, P] = cnt
            v = np.zeros(B, bool)
            v[:g] = do_hh
            hh_in.append((u, s, v))
        dense_in = None
        if self._dense and do_hh:
            need = set()
            for _, w in self._dense:
                need.add(w.config.key_col)
                need.update(w.config.value_cols)
                if w.config.scale_col:
                    need.add(w.config.scale_col)
            bs = self._bs
            dcols = {}
            for name in need:
                src = _u32_lane(cols[name])
                a = np.zeros(bs, np.uint32)
                a[:n] = src
                dcols[name] = a.view(np.int32)
            dvalid = np.zeros(bs, bool)
            dvalid[:n] = True
            dense_in = (dcols, dvalid)
        ddos_in = None
        if self._ddos_plan is not None:
            uniq, dsum = fams[-1]
            g = uniq.shape[0]
            u = np.zeros((B, 4), np.uint32)
            s = np.zeros(B, np.float32)
            u[:g] = uniq
            s[:g] = dsum
            v = np.zeros(B, bool)
            v[:g] = do_dd
            ddos_in = (u, s, v)
        states = (
            tuple(w.model.state for _, w in self._hh),
            tuple(w.model.totals for _, w in self._dense),
            tuple(d.state for _, d in self._ddos),
        )
        new_hh, new_dense, new_ddos = self._apply(
            states, tuple(hh_in), dense_in, ddos_in)
        for (_, w), st in zip(self._hh, new_hh):
            w.model.state = st
        if dense_in is not None:
            for (_, w), tot in zip(self._dense, new_dense):
                w.model.totals = tot
        for (_, d), st in zip(self._ddos, new_ddos):
            d.state = st
