"""Window-driving wrapper for the heavy-hitter model.

HeavyHitterModel aggregates an unbounded stream; this wrapper gives it the
same tumbling-window lifecycle as the exact aggregator: at watermark close
it extracts the window's top-K rows and resets the sketch — the streaming
equivalent of flows_5m's per-timeslot grouping, for key spaces too large to
aggregate exactly (the north-star 5-tuple configs, BASELINE.json).
"""

from __future__ import annotations

import numpy as np

from ..models.heavy_hitter import HeavyHitterConfig, HeavyHitterModel
from ..models.oracle import SECONDS_PER_SLOT
from ..schema.batch import FlowBatch


class LazyWindowTop:
    """Deferred top-K extraction for one closed window.

    Closing a sketch window costs a device sync (top-K ranking + CMS
    estimates pulled to host) that the HOT PATH does not need — only the
    sink does. The close captures the window's state (immutable jax
    arrays; reset() replaces rather than mutates, and the update step's
    buffer donation only ever consumes the NEW state), and resolve()
    materializes the rows wherever the flusher runs it.
    """

    __slots__ = ("_thunk", "timeslot")

    def __init__(self, thunk, timeslot: int):
        self._thunk = thunk
        self.timeslot = timeslot

    def resolve(self) -> dict:
        top = self._thunk()
        top["timeslot"] = np.full(
            len(top["valid"]), self.timeslot, dtype=np.uint64)
        return top


class WindowedHeavyHitter:
    """Tumbling-window top-K: update(batch) per batch; flush() yields rows
    for closed windows (one reset sketch per window)."""

    def __init__(self, config: HeavyHitterConfig = HeavyHitterConfig(),
                 window_seconds: int = SECONDS_PER_SLOT, k: int = 100,
                 model_cls=HeavyHitterModel, **model_kw):
        self.config = config
        self.window_seconds = window_seconds
        self.k = k
        self.model = model_cls(config, **model_kw)
        self.current_slot: int | None = None
        # flowmesh capture seam (mesh/member.py): when set, a window
        # close hands (slot, backing model) to the hook INSTEAD of
        # extracting rows locally — per-shard state is merged
        # network-wide at the coordinator and extracted ONCE from the
        # merged sketch. None (the default) keeps the single-worker
        # behavior byte-identical.
        self.capture = None
        # sketchwatch seam (obs/audit.py): when set, a window close
        # first hands (closing slot, backing model) to the audit so the
        # sampled exact shadow cohort is sealed against EXACTLY the
        # state being closed — before capture/extraction/reset. Fires
        # on every close path (slot roll, forced flush, mesh resync).
        self.audit_hook = None
        # Ingest-runtime knob (engine.worker sets it in pipelined mode):
        # close windows as LazyWindowTop handles so extraction runs on
        # the background flusher instead of the update path. Only honored
        # when the backing model can capture its state (top_lazy).
        self.lazy_extract = False
        self._pending: list = []  # dicts, or LazyWindowTop when lazy
        # Sketch windows cannot reopen (the sketch was reset at close), so
        # rows older than the current slot are DROPPED and counted — unlike
        # the exact aggregator, which emits late partials. Size
        # window_seconds/upstream batching so lateness cannot occur, or
        # monitor this counter.
        self.late_flows_dropped = 0

    def update(self, batch: FlowBatch) -> None:
        if len(batch) == 0:
            return
        # split rows by window slot so each sketch covers exactly one window
        slots = (
            batch.columns["time_received"].astype(np.int64)
            // self.window_seconds * self.window_seconds
        )
        for slot in np.unique(slots):
            idx = np.flatnonzero(slots == slot)
            part = FlowBatch(
                {k: v[idx] for k, v in batch.columns.items()}, batch.partition
            )
            slot = int(slot)
            if self.current_slot is None:
                self.current_slot = slot
            elif slot > self.current_slot:
                self._close()
                self.current_slot = slot
            elif slot < self.current_slot:
                # late rows for a closed (reset) window: drop, never
                # misattribute them to the current window's timeslot
                self.late_flows_dropped += len(part)
                continue
            self.model.update(part)

    def _close(self) -> None:
        if self.audit_hook is not None:
            self.audit_hook(self.current_slot, self.model)
        if self.capture is not None:
            # mesh member: ship the window's raw sketch state; no local
            # row extraction (the coordinator extracts from the merge)
            self.capture(self.current_slot, self.model)
            self.model.reset()
            return
        if self.lazy_extract and hasattr(self.model, "top_lazy"):
            self._pending.append(LazyWindowTop(
                self.model.top_lazy(self.k), self.current_slot))
        else:
            top = self.model.top(self.k)
            top["timeslot"] = np.full(
                len(top["valid"]), self.current_slot, dtype=np.uint64
            )
            self._pending.append(top)
        self.model.reset()

    def flush(self, force: bool = False) -> list:
        """Rows for closed windows (and the open one too, when force) —
        dicts, or unresolved LazyWindowTop handles under lazy_extract."""
        if force and self.current_slot is not None:
            self._close()
            self.current_slot = None
        out, self._pending = self._pending, []
        return out
