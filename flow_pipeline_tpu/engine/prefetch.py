"""Prefetching consumer: overlap host fetch+decode with the device step.

At the north-star rate the host path (bus fetch, wire decode,
columnarization) and the device path (jitted model updates) each take a
meaningful fraction of the batch budget; run serially they add up
(SURVEY.md §7 hard part (b): "double-buffered host->HBM feed"). This
wrapper runs the wrapped consumer on a dedicated thread, keeping a small
bounded queue of decoded batches ready, so the worker's device step for
batch i overlaps the host work for batch i+1 (JAX's async dispatch then
overlaps the device work itself with the NEXT poll).

Threading contract: the wrapped consumer is owned ENTIRELY by the
prefetch thread after start — kafka-python consumers are not thread-safe,
so commits are routed to that thread through a command queue and executed
between polls. ``flush_commits()`` blocks until queued commits have hit
the broker; the worker calls it after each snapshot so the at-least-once
protocol (state durable -> offsets committed) keeps its ordering.
"""

from __future__ import annotations

# flowlint: lock-checked
# (shared attributes declare their lock / single-writer story below;
# `make lint` verifies write sites — see docs/STATIC_ANALYSIS.md)

import queue
import threading
from typing import Optional

from ..guard import register_guard_metrics
from ..obs import get_logger

log = get_logger("prefetch")


class PrefetchConsumer:
    """Wraps a transport consumer with a fetch-ahead thread.

    depth is the max decoded batches held ready (2 = classic double
    buffering). The wrapper exposes the consumer surface the worker uses:
    poll / commit / committed / lag / positions.
    """

    def __init__(self, consumer, depth: int = 2, poll_max: int = 8192,
                 idle_sleep: float = 0.02):
        self.inner = consumer
        self.depth = depth
        # flowlint: unguarded -- worker writes, feed thread reads; stale sizes are tolerated by the documented poll() contract
        self.poll_max = poll_max
        self.idle_sleep = idle_sleep
        self._batches: queue.Queue = queue.Queue(maxsize=depth)
        self._commits: queue.Queue = queue.Queue()
        # pending-commit accounting: incremented on enqueue, decremented
        # after execution on the owner thread; a bare "queue empty" test
        # would race with a commit that is cleared-but-not-yet-enqueued
        self._pending = 0  # guarded-by: _cv
        # flowlint: unguarded -- the lock itself; bound once, never rebound
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._idle = threading.Event()  # last inner.poll returned nothing
        # freshness accounting for poll(): _started counts rounds begun,
        # _completed_start is the start-number of the last finished round
        # flowlint: unguarded -- feed thread is the sole writer; worker reads a monotonic int
        self._started = 0
        # flowlint: unguarded -- feed thread is the sole writer; worker reads a monotonic int
        self._completed_start = 0
        # first error from the feed thread; surfaced to the caller so a
        # poison message / dead broker crashes the worker (supervisor
        # restart semantics) instead of hanging or silently looping
        self._error: Optional[BaseException] = None  # guarded-by: _cv
        # flowlint: unguarded -- worker-thread lifecycle only (poll()/stop() run on the one owner thread)
        self._thread: Optional[threading.Thread] = None
        # flowguard occupancy: live bytes resident in the decoded-batch
        # queue (guard_buffer_bytes{stage="feed"}) — bounded at depth
        # batches by construction; this makes the occupancy observable
        self.m_bytes = register_guard_metrics()["buffer_bytes"]
        self._bytes = 0  # guarded-by: _cv

    def _track_bytes(self, delta: int) -> None:
        with self._cv:
            self._bytes += delta
            b = self._bytes
        self.m_bytes.set(b, stage="feed")

    # ---- consumer surface --------------------------------------------------

    def poll(self, max_messages: int = 8192):
        """Next prefetched batch, or None when the UNDERLYING consumer is
        idle. Blocks briefly while a fetch is in flight — returning None
        mid-fetch would make stop_when_idle callers quit a non-empty
        stream just because the thread hadn't finished its first poll.

        Contract drift from the wrapped consumer: ``max_messages`` applies
        to FUTURE feed rounds only — up to ``depth`` batches already
        fetched at the previous size are returned as-is. The worker passes
        a constant poll_max, so this is benign there; callers that vary
        the size mid-stream must tolerate a few stale-sized batches."""
        self.poll_max = max_messages  # picked up by the next feed round
        if self._thread is None:
            self._start()
        # Return None only after a poll round that STARTED after this call
        # came back empty: the sticky idle flag alone could be stale (a
        # producer may have published while the feed thread slept — or
        # while an in-flight round was already past its fetch), and a
        # premature None makes stop_when_idle callers abandon the tail.
        started_before = self._started
        while True:
            if self._error is not None:
                raise self._error
            try:
                batch = self._batches.get(timeout=self.idle_sleep)
                self._track_bytes(-batch.nbytes())
                return batch
            except queue.Empty:
                if not self._thread.is_alive():
                    # the thread may have died DURING our get() — re-check
                    # the error before calling it end-of-stream, or the
                    # crash-the-worker semantics silently become a clean
                    # exit for stop_when_idle callers
                    if self._error is not None:
                        raise self._error
                    return None
                if self._idle.is_set() and \
                        self._completed_start > started_before:
                    return None

    def commit(self, partition: int, next_offset: int) -> None:
        """Queue the commit for the owner thread (kafka-python consumers
        are not thread-safe). flush_commits() awaits execution."""
        if self._thread is None or not self._thread.is_alive():
            # no live thread owns the consumer (nothing polled yet, or the
            # feed died after surfacing its error): commit directly — an
            # enqueued commit would never drain and flush_commits would
            # stall for its full timeout
            self.inner.commit(partition, next_offset)
            return
        with self._cv:
            self._pending += 1
        self._commits.put((partition, next_offset))

    def flush_commits(self, timeout: float = 30.0) -> None:
        """Block until every queued commit has executed on the consumer."""
        if self._thread is None:
            return
        with self._cv:
            done = self._cv.wait_for(
                lambda: self._pending == 0 or self._error is not None,
                timeout,
            )
        if self._error is not None:
            # the real failure, not a misleading timeout: the exiting
            # thread's final drain still executes any queued commits
            raise self._error
        if not done:
            raise TimeoutError("prefetch commit queue did not drain")

    def __getattr__(self, name):
        # committed / lag / positions etc. delegate to the wrapped
        # consumer, and only exist if IT has them (callers feature-test
        # with hasattr). restore() adjusts .positions BEFORE the first
        # poll starts the thread; afterwards the thread owns them.
        return getattr(self.inner, name)

    # ---- lifecycle ---------------------------------------------------------

    def _start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="feed-prefetch", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        """Drain queued commits and stop the thread (batches already
        prefetched but unread are dropped — uncommitted, so they replay)."""
        if self._thread is None:
            return
        self.flush_commits(timeout)
        self._stop.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            # The thread is stuck in a blocking inner call (broker stall).
            # Refuse to relinquish ownership: _stop stays set so it exits
            # when the call returns, and commit()/poll() keep routing
            # through the queue instead of touching the non-thread-safe
            # consumer concurrently.
            raise TimeoutError("prefetch thread did not stop in time")
        self._thread = None
        self._stop.clear()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._drain_commits()
            if self._batches.full():
                # device side is behind; yield instead of spinning
                self._stop.wait(self.idle_sleep)
                continue
            self._started += 1
            round_no = self._started
            try:
                batch = self.inner.poll(self.poll_max)
            except Exception as e:  # noqa: BLE001 — hand to the caller:
                # retrying forever would turn a poison message or a dead
                # broker (which crashes the unwrapped worker for the
                # supervisor to restart) into a silent infinite loop
                log.exception("prefetch poll failed; surfacing to caller")
                with self._cv:
                    self._error = e
                    self._cv.notify_all()  # flush_commits waiters re-check
                break
            if batch is None or len(batch) == 0:
                self._idle.set()
                self._completed_start = round_no
                self._stop.wait(self.idle_sleep)
                continue
            self._idle.clear()
            self._completed_start = round_no
            self._batches.put(batch)
            self._track_bytes(batch.nbytes())
        self._drain_commits()

    def _drain_commits(self) -> None:
        while True:
            try:
                partition, next_offset = self._commits.get_nowait()
            except queue.Empty:
                return
            try:
                self.inner.commit(partition, next_offset)
            except Exception as e:  # noqa: BLE001 — flush_commits raises it:
                # reporting success for a commit that never reached the
                # broker would falsify "state durable -> offsets committed"
                log.exception("prefetch commit failed; surfacing to caller")
                with self._cv:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cv:
                    self._pending -= 1
                    self._cv.notify_all()
