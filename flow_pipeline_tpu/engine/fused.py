"""Fused per-batch device step: one dispatch, shared pre-aggregation.

The unfused worker updates every model serially and each sketch/exact
model independently re-sorts the same batch — five multi-key sorts and
~eight dispatches per batch at the default model set. The reference's
ClickHouse rollup chain makes ONE pass over the raw rows per ingest and
fans the materialized views out from it (ref: compose/clickhouse/
create.sh:92-110). This module is the TPU-first equivalent:

- Each heavy-hitter key family gets a HASH-grouped pre-agg
  (ops.segment.hash_groupby_float): the sort runs over the 64-bit key
  hash (2 lanes) instead of the raw 4-11 key lanes, which beats the
  previous shared 10-lane master sort even though families no longer
  share a sort — lax.sort cost scales with operand count, and three
  2-lane sorts are cheaper than one 10-lane sort plus a 4-lane dst
  sort.
- The dst-keyed hash sort is still shared between the top-dst-IP
  sketch and the DDoS per-dst accumulate (they want the same per-dst
  groups under different row masks).
- The flows_5m exact groupby, the dense port scatters, and all sketch
  table merges run in the SAME jitted step, so the worker makes one
  device dispatch per chunk and every column crosses the host boundary
  once.

Window lifecycle (closing sketches at slot roll, DDoS sub-windows, late
-row drops) stays host-side and byte-identical to the unfused models':
the batch is split at (slot, sub-window) boundaries and each homogeneous
group advances the wrapped models' own lifecycle hooks before the fused
device call. tests/test_fused.py proves output equivalence against the
unfused path, late rows included.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models import heavy_hitter as hh
from ..models.ddos import DDoSDetector, _accumulate_grouped
from ..models.dense_top import DenseTopKModel, dense_update
from ..models.heavy_hitter import HeavyHitterModel
from ..models.spread import SpreadModel
from ..models.window_agg import WindowAggregator
from ..models.window_agg import _cached_update as _cached_wagg_update
from ..obs import get_logger
from ..schema.batch import FlowBatch, lane_width
from ..ops.segment import (
    _hash_grouped,
    hash_groupby_float,
    hash_lanes,
    hash_sort,
    presorted_segments,
)
from .windowed import WindowedHeavyHitter

log = get_logger("fused")

# numpy (not jnp): a module-level jnp constant would initialize the JAX
# backend at import time — importing the engine must never claim a chip
_SENTINEL = np.uint32(0xFFFFFFFF)


def _hh_plan(cfg) -> tuple:
    """How a heavy-hitter config's pre-agg is computed inside the fused
    step: ("B",) = the shared dst-keyed hash sort (dual-masked with the
    DDoS accumulate); ("own",) = its own hash_groupby_float (still inside
    the fused dispatch, just not shared)."""
    if tuple(cfg.value_cols) == ("bytes", "packets") and \
            cfg.key_cols == ("dst_addr",):
        return ("B",)
    return ("own",)


@functools.lru_cache(maxsize=None)
def _cached_step(hh_specs, dense_cfgs, ddos_cfgs, wagg_cfgs):
    """Build + jit the fused device step for one static model spec.

    Module-level cache: pipelines are rebuilt freely (bench samples,
    supervisor restarts), and the fused graph is the most expensive
    compile in the framework — it must be shared the same way the
    unfused models' module-level jits are. All spec elements are frozen
    config dataclasses / string tuples, so the key is hashable.
    """
    from ..models.window_agg import group_cols as _wagg_group_cols

    wagg_fns = tuple(_cached_wagg_update(c.window_seconds,
                                         _wagg_group_cols(c),
                                         c.value_cols) for c in wagg_cfgs)
    # The shared B path scales its payload planes by the FIRST B config's
    # rate; a second dst-keyed family with a different scale_col would
    # silently get the wrong sampling correction — demote it to its own
    # groupby (mirrors the chain-absorb scale_col equality check below).
    b_scale = next((cfg.scale_col for plan, cfg in hh_specs
                    if plan[0] == "B"), None)
    hh_specs = tuple(
        (("own",) if plan[0] == "B" and cfg.scale_col != b_scale else plan,
         cfg)
        for plan, cfg in hh_specs)
    hh_b = any(plan[0] == "B" for plan, _ in hh_specs)
    need_b = hh_b or bool(ddos_cfgs)
    hh_vals = ("bytes", "packets")  # the dst-shared payload planes

    # Nested-family chains: an "own" family whose key tuple is a PREFIX
    # of another's (src-address under the 5-tuple top-talkers) rides the
    # same sort — lanes are [h64(prefix), h64(full)], so rows group by
    # the prefix at sort-lane width 2 and by the full key at width 4.
    # Two extra lanes on one sort beat a whole second 2-lane sort.
    own_ix = [i for i, (plan, cfg) in enumerate(hh_specs)
              if plan[0] == "own" and tuple(cfg.value_cols) == hh_vals]
    own_ix.sort(key=lambda i: -len(hh_specs[i][1].key_cols))
    chains, absorbed = [], set()
    for i in own_ix:
        if i in absorbed:
            continue
        members = [i]
        pk = hh_specs[i][1].key_cols
        for j in own_ix:
            if j in absorbed or j == i:
                continue
            ck = hh_specs[j][1].key_cols
            if (len(ck) < len(pk) and pk[:len(ck)] == ck
                    and hh_specs[j][1].scale_col
                    == hh_specs[i][1].scale_col):
                members.append(j)
                absorbed.add(j)
        if len(members) > 1:
            members.sort(key=lambda m: len(hh_specs[m][1].key_cols))
            chains.append(tuple(members))
            absorbed.add(i)

    def to_f32(col):
        # int32 bit-patterns of uint32 counters: reinterpret unsigned
        # before the float cast so saturated values stay positive
        return col.astype(jnp.uint32).astype(jnp.float32)

    def rate_of(cols, cfg):
        # serving-side sampling factor (see HeavyHitterConfig.scale_col);
        # rate 0 ("unknown") scales by 1
        if not getattr(cfg, "scale_col", None):
            return None
        return jnp.maximum(to_f32(cols[cfg.scale_col]), 1.0)

    def step(states, cols, valid, valid_hh, valid_dd):
        hh_states, dense_tots, ddos_states = states

        chain_results: dict[int, tuple] = {}
        for members in chains:
            parent_cfg = hh_specs[members[-1]][1]
            full_lanes = hh._key_lanes(cols, parent_cfg.key_cols)
            n = full_lanes.shape[0]
            sort_lanes = []
            for m in members:
                h1, h2 = hash_lanes(hh._key_lanes(
                    cols, hh_specs[m][1].key_cols))
                sort_lanes.append(jnp.where(valid_hh, h1, _SENTINEL))
                sort_lanes.append(jnp.where(valid_hh, h2, _SENTINEL))
            out = lax.sort(sort_lanes + [lax.iota(jnp.int32, n)],
                           num_keys=2 * len(members))
            perm = out[-1]
            sh = jnp.stack(out[:-1], axis=1)
            sk = jnp.where(valid_hh[:, None], full_lanes.astype(jnp.uint32),
                           _SENTINEL)[perm]
            sv = jnp.stack([to_f32(cols[c]) for c in hh_vals], axis=1)
            r = rate_of(cols, parent_cfg)  # members share scale_col
            if r is not None:
                sv = sv * r[:, None]
            sv = jnp.where(valid_hh[:, None], sv, 0.0)[perm]
            sc = valid_hh[perm].astype(jnp.int32)
            for level, m in enumerate(members):
                width = sum(
                    lane_width(c) for c in hh_specs[m][1].key_cols)
                uniq, sums, counts, _ = _hash_grouped(
                    sh[:, :2 * (level + 1)], sk[:, :width], sv, sc, False)
                chain_results[m] = (uniq, sums, counts)

        if need_b:
            # One dst-keyed hash sort serves the top-dst-IP sketch AND the
            # DDoS per-dst accumulate under their own row masks: masks
            # apply to the GATHERED rows, so the dual-mask planes cost
            # gathers, not extra sort lanes (ops.segment.hash_sort).
            dst = cols["dst_addr"].astype(jnp.uint32)
            vb = valid_hh if hh_b else jnp.zeros_like(valid_hh)
            vd = (valid_dd if ddos_cfgs
                  else jnp.zeros_like(valid_hh))
            va = vb | vd
            n = dst.shape[0]
            sh_b, perm = hash_sort(dst, va)
            sk_b = jnp.where(va[:, None], dst, _SENTINEL)[perm]
            vbp, vdp = vb[perm], vd[perm]
            planes, cnts = [], []
            if hh_b:
                b_cfg = next(cfg for plan, cfg in hh_specs
                             if plan[0] == "B")
                rb = rate_of(cols, b_cfg)
                for c in hh_vals:
                    p = to_f32(cols[c])
                    if rb is not None:
                        p = p * rb
                    planes.append(jnp.where(vbp, p[perm], 0.0))
                cnts.append(vbp.astype(jnp.int32))
            for dcfg in ddos_cfgs[:1]:  # detectors share cadence+col set
                p = to_f32(cols[dcfg.value_col])
                rd = rate_of(cols, dcfg)
                if rd is not None:
                    p = p * rd
                planes.append(jnp.where(vdp, p[perm], 0.0))
                cnts.append(vdp.astype(jnp.int32))
            sv_b = jnp.stack(planes, axis=1)
            sc_b = jnp.stack(cnts, axis=1)  # [N, nc]
            seg = presorted_segments(sh_b)
            sums_b = jax.ops.segment_sum(sv_b, seg, num_segments=n)
            cnt_b = jax.ops.segment_sum(sc_b, seg, num_segments=n)
            # min, not max: rows masked for NEITHER consumer keep their
            # sentinel keys and may share a hash segment with real rows
            # only on a ~2^-64 hash collision — min lets the real key win
            uniq_b = jax.ops.segment_min(sk_b, seg, num_segments=n)

            def consume_b(plane_ix, cnt_ix, nplanes):
                counts = cnt_b[:, cnt_ix]
                real = counts > 0
                s = jnp.where(real[:, None],
                              sums_b[:, plane_ix:plane_ix + nplanes], 0.0)
                u = jnp.where(real[:, None], uniq_b, _SENTINEL)
                return u, s, counts

        new_hh = []
        for i, ((plan, cfg), st) in enumerate(zip(hh_specs, hh_states)):
            if plan[0] == "B":
                uniq, sums, counts = consume_b(0, 0, 2)
            elif i in chain_results:
                uniq, sums, counts = chain_results[i]
            else:
                lanes = hh._key_lanes(cols, cfg.key_cols)
                vals = jnp.stack(
                    [to_f32(cols[c]) for c in cfg.value_cols], axis=1)
                r = rate_of(cols, cfg)
                if r is not None:
                    vals = vals * r[:, None]
                uniq, sums, counts = hash_groupby_float(
                    lanes, vals, valid_hh)
            sums3 = jnp.concatenate(
                [sums, counts.astype(jnp.float32)[:, None]], axis=1)
            new_hh.append(
                hh._apply_grouped(st, uniq, sums3, counts > 0, cfg))

        new_dense = tuple(
            dense_update(t, cols, valid_hh, config=c)
            for t, c in zip(dense_tots, dense_cfgs)
        )

        new_ddos = []
        for dcfg, dst_state in zip(ddos_cfgs, ddos_states):
            plane_ix = 2 if hh_b else 0
            cnt_ix = 1 if hh_b else 0
            u, s, counts = consume_b(plane_ix, cnt_ix, 1)
            new_ddos.append(_accumulate_grouped(
                dst_state, u, s[:, 0], counts > 0, dcfg))

        wagg_parts = tuple(fn(cols, valid) for fn in wagg_fns)
        return (tuple(new_hh), new_dense, tuple(new_ddos)), wagg_parts

    return jax.jit(step, donate_argnums=(0,))


class FusedPipeline:
    """Drives a worker's whole model dict through one jitted step/batch."""

    @staticmethod
    def supported(models: dict[str, Any]) -> bool:
        """True iff every model is a plain single-chip kind this pipeline
        knows how to fuse (sharded/mesh variants keep the per-model path:
        their states live as mesh-sharded arrays with their own update
        programs) and the windowed models agree on cadence/chunking."""
        whh_windows, subs, batch_sizes = set(), set(), set()
        for m in models.values():
            if type(m) is WindowAggregator:
                batch_sizes.add(m.config.batch_size)
            elif type(m) is WindowedHeavyHitter and type(m.model) in (
                    HeavyHitterModel, DenseTopKModel, SpreadModel):
                whh_windows.add(m.window_seconds)
                batch_sizes.add(m.config.batch_size)
            elif type(m) is DDoSDetector:
                subs.add(m.config.sub_window_seconds)
                batch_sizes.add(m.config.batch_size)
            else:
                return False
        n_ddos = sum(type(m) is DDoSDetector for m in models.values())
        return (len(whh_windows) <= 1 and len(subs) <= 1 and n_ddos <= 1
                and len(batch_sizes) == 1)

    def __init__(self, models: dict[str, Any]):
        if not self.supported(models):
            raise ValueError("model set not fusable (see supported())")
        self._waggs: list[tuple[str, WindowAggregator]] = []
        self._hh: list[tuple[str, WindowedHeavyHitter]] = []
        self._dense: list[tuple[str, WindowedHeavyHitter]] = []
        self._ddos: list[tuple[str, DDoSDetector]] = []
        # spread wrappers ride the SAME window lifecycle (_advance_hh
        # closes every _whh member in lockstep) but not the jitted step:
        # their state is host numpy by design and their grouping key
        # (key + counted element) cannot share the hh pre-agg, so each
        # chunk updates them host-side — the max monoid makes that
        # bit-identical to any other chunking/ordering.
        self._spread: list[tuple[str, WindowedHeavyHitter]] = []
        self._whh: list[WindowedHeavyHitter] = []  # hh/dense/spread wrappers
        for name, m in models.items():
            if type(m) is WindowAggregator:
                self._waggs.append((name, m))
            elif type(m) is DDoSDetector:
                self._ddos.append((name, m))
            elif type(m.model) is HeavyHitterModel:
                self._hh.append((name, m))
                self._whh.append(m)
            elif type(m.model) is SpreadModel:
                self._spread.append((name, m))
                self._whh.append(m)
            else:
                self._dense.append((name, m))
                self._whh.append(m)
        first = next(iter(models.values()))
        self._bs = first.config.batch_size
        self._window_seconds = (self._whh[0].window_seconds
                                if self._whh else None)
        self._sub_seconds = (self._ddos[0][1].config.sub_window_seconds
                             if self._ddos else None)
        self._hh_specs = tuple(
            (_hh_plan(w.config), w.config) for _, w in self._hh)
        self._cols = self._column_union()
        # The compiled step is cached on the static spec, NOT per instance:
        # every bench sample / supervisor restart builds a fresh pipeline,
        # and a per-instance jit would recompile the whole fused graph
        # each time (the unfused models' jits are module-cached too).
        self._step = _cached_step(
            self._hh_specs,
            tuple(w.config for _, w in self._dense),
            tuple(d.config for _, d in self._ddos),
            tuple(m.config for _, m in self._waggs),
        )

    # ---- device step ------------------------------------------------------

    def _column_union(self) -> tuple[str, ...]:
        cols: list[str] = []

        def add(*names):
            for n in names:
                if n not in cols:
                    cols.append(n)

        def scale_of(cfg):
            return (cfg.scale_col,) if getattr(cfg, "scale_col", None) \
                else ()

        for _, m in self._waggs:
            add("time_received", *m.config.key_cols, *m.config.value_cols,
                *scale_of(m.config))
        for _, w in self._hh:
            add(*w.config.key_cols, *w.config.value_cols,
                *scale_of(w.config))
        for _, w in self._dense:
            add(w.config.key_col, *w.config.value_cols,
                *scale_of(w.config))
        for _, w in self._spread:
            add(*w.config.key_cols, w.config.elem_col)
        for _, d in self._ddos:
            add("dst_addr", d.config.value_col, *scale_of(d.config))
        return tuple(cols)

    # ---- host lifecycle ---------------------------------------------------

    def _split_parts(self, batch: FlowBatch):
        """Split a batch at (window slot, DDoS sub-window) boundaries into
        homogeneous parts, in (slot, sub) order. Returns (parts, wm) with
        parts = [(slot, sub, FlowBatch)] and wm the batch watermark —
        pure host work, shared by update() and the ingest runtime's
        prepare stage (which runs it off the worker thread)."""
        n = len(batch)
        t = batch.columns["time_received"].astype(np.int64)
        slots = ((t // self._window_seconds) * self._window_seconds
                 if self._whh else np.zeros(n, np.int64))
        subs = ((t // self._sub_seconds) * self._sub_seconds
                if self._ddos else np.zeros(n, np.int64))
        # One (slot, sub) pair per batch is the overwhelmingly common case
        # (sub-windows are tens of seconds, batches are milliseconds of
        # traffic) — detect it with scalar min/max passes instead of a
        # row-tuple unique (np.unique(axis=0) void-sorts the whole batch,
        # ~19ms per 32k rows; this path is ~0.1ms). Boundary batches take
        # the tuple unique, which orders correctly for ANY int64 pair —
        # scalar-encoding tricks can wrap on corrupt extreme timestamps
        # and would process real rows under an adopted garbage slot.
        if slots.min() == slots.max() and subs.min() == subs.max():
            groups = [(int(slots[0]), int(subs[0]), None)]
        else:
            pairs = np.stack([slots, subs], axis=1)
            uniq_pairs, inverse = np.unique(pairs, axis=0,
                                            return_inverse=True)
            inverse = inverse.reshape(-1)  # numpy 2.0 quirk under axis=
            groups = [
                (int(slot), int(sub), np.flatnonzero(inverse == gi))
                for gi, (slot, sub) in enumerate(uniq_pairs)
            ]
        parts = []
        for slot, sub, idx in groups:
            if idx is None:
                part = batch
            else:
                part = FlowBatch(
                    {k: v[idx] for k, v in batch.columns.items()},
                    batch.partition,
                )
            parts.append((slot, sub, part))
        return parts, int(t.max())

    def update(self, batch: FlowBatch) -> None:
        if len(batch) == 0:
            return
        parts, wm = self._split_parts(batch)
        for slot, sub, part in parts:
            do_hh = self._advance_hh(slot, len(part))
            do_dd = self._advance_ddos(sub, len(part))
            self._run_chunks(part, do_hh, do_dd)
        for _, m in self._waggs:
            if wm > m.watermark:
                m.watermark = wm

    def _advance_hh(self, slot: int, n_rows: int) -> bool:
        """Lockstep WindowedHeavyHitter lifecycle (same transitions as its
        own update(): first slot adopts, newer slot closes + rolls, older
        slot drops late rows). Returns False when the group is late."""
        if not self._whh:
            return False
        cur = self._whh[0].current_slot
        if cur is None:
            for w in self._whh:
                w.current_slot = slot
            return True
        if slot > cur:
            for w in self._whh:
                w._close()
                w.current_slot = slot
            return True
        if slot < cur:
            for w in self._whh:
                w.late_flows_dropped += n_rows
            return False
        return True

    def _advance_ddos(self, sub: int, n_rows: int) -> bool:
        """Lockstep DDoSDetector sub-window lifecycle (close scores the
        OLD sub-window before current_sub advances, as in its update())."""
        if not self._ddos:
            return False
        cur = self._ddos[0][1].current_sub
        if cur is None:
            for _, d in self._ddos:
                d.current_sub = sub
            return True
        if sub > cur:
            for _, d in self._ddos:
                d.close_sub_window()
                d.current_sub = sub
            return True
        if sub < cur:
            for _, d in self._ddos:
                d.late_flows_dropped += n_rows
            return False
        return True

    def _run_chunks(self, part: FlowBatch, do_hh: bool, do_dd: bool) -> None:
        bs = self._bs
        for start in range(0, len(part), bs):
            chunk = part.slice(start, start + bs)
            if do_hh:
                # host-side spread fold per chunk (see __init__): the
                # chunk is <= one model batch, so model.update makes
                # exactly one grouped pass over it
                for _, w in self._spread:
                    w.model.update(chunk)
            padded, mask = chunk.pad_to(bs)
            host_cols = padded.device_columns(self._cols)
            cols = {k: jnp.asarray(v) for k, v in host_cols.items()}
            valid = jnp.asarray(mask)
            zeros = (jnp.zeros_like(valid)
                     if not (do_hh and do_dd) else None)
            states = (
                tuple(w.model.state for _, w in self._hh),
                tuple(w.model.totals for _, w in self._dense),
                tuple(d.state for _, d in self._ddos),
            )
            new_states, wagg_parts = self._step(
                states, cols, valid,
                valid if do_hh else zeros,
                valid if do_dd else zeros,
            )
            new_hh, new_dense, new_ddos = new_states
            for (_, w), st in zip(self._hh, new_hh):
                w.model.state = st
            for (_, w), tot in zip(self._dense, new_dense):
                w.model.totals = tot
            for (_, d), st in zip(self._ddos, new_ddos):
                d.state = st
            for (_, m), out in zip(self._waggs, wagg_parts):
                # exact fallback for the ~2^-64 hash-collision case: the
                # chunk re-runs its own lexicographic groupby at drain
                # time (flows_5m stays bit-exact). Closes over the HOST
                # columns so pending fallbacks don't pin device buffers
                # (see WindowAggregator._exact_fallback).
                m.add_partial(out, fallback=m._exact_fallback(
                    host_cols, mask))
