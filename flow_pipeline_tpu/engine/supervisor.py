"""Failure detection + elastic recovery for bare-metal deployments.

The reference's recovery story is container-level: ``restart: always`` on
every long-running compose service plus consumer-group rebalance
(SURVEY.md §5 failure detection). Inside containers that still applies; for
bare-metal/systemd-less runs this supervisor provides the same semantics in
process: run a worker factory, restart on crash with exponential backoff,
give up after ``max_restarts`` within ``window_seconds`` (a crash loop is a
bug, not a transient).

The worker's checkpoint/offset machinery makes restarts safe: a fresh
worker restores the snapshot and resumes from committed offsets, so crashes
cost at most the unsnapshotted tail, never double counting.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable

from ..obs import REGISTRY, get_logger

log = get_logger("supervisor")


@dataclass(frozen=True)
class SupervisorConfig:
    max_restarts: int = 5
    window_seconds: float = 300.0
    backoff_initial: float = 0.5
    backoff_max: float = 30.0


class Supervisor:
    """run() calls ``factory()`` to build a worker and invokes
    ``worker.run(**run_kwargs)``; on exception it rebuilds (factory should
    wire restore()) and retries with backoff."""

    def __init__(self, factory: Callable, config: SupervisorConfig = SupervisorConfig(),
                 **run_kwargs):
        self.factory = factory
        self.config = config
        self.run_kwargs = run_kwargs
        self.restarts = 0
        self.m_restarts = REGISTRY.counter("worker_restarts_total",
                                           "supervisor worker restarts")

    def run(self) -> None:
        crash_times: list[float] = []
        backoff = self.config.backoff_initial
        while True:
            worker = self.factory()
            try:
                worker.run(**self.run_kwargs)
                return  # clean exit
            except KeyboardInterrupt:
                worker.finalize()
                raise
            except Exception as e:  # noqa: BLE001 — the supervisor's job
                now = time.monotonic()
                recent = [
                    t for t in crash_times
                    if now - t < self.config.window_seconds
                ]
                if not recent:  # healthy era since the last crash burst
                    backoff = self.config.backoff_initial
                crash_times = recent + [now]
                self.restarts += 1
                self.m_restarts.inc()
                if len(crash_times) > self.config.max_restarts:
                    log.error("crash loop (%d crashes in %.0fs); giving up",
                              len(crash_times), self.config.window_seconds)
                    raise
                log.exception("worker crashed (%s); restarting in %.1fs",
                              e, backoff)
                time.sleep(backoff)
                backoff = min(backoff * 2, self.config.backoff_max)
