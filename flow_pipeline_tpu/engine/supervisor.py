"""Failure detection + elastic recovery for bare-metal deployments.

The reference's recovery story is container-level: ``restart: always`` on
every long-running compose service plus consumer-group rebalance
(SURVEY.md §5 failure detection). Inside containers that still applies; for
bare-metal/systemd-less runs this supervisor provides the same semantics in
process: run a worker factory, restart on crash with exponential backoff,
give up after ``max_restarts`` within ``window_seconds`` (a crash loop is a
bug, not a transient).

The worker's checkpoint/offset machinery makes restarts safe: a fresh
worker restores the snapshot and resumes from committed offsets, so crashes
cost at most the unsnapshotted tail, never double counting.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable

from ..obs import REGISTRY, get_logger

log = get_logger("supervisor")


@dataclass(frozen=True)
class SupervisorConfig:
    max_restarts: int = 5
    window_seconds: float = 300.0
    backoff_initial: float = 0.5
    backoff_max: float = 30.0


class Supervisor:
    """run() calls ``factory()`` to build a worker and invokes
    ``worker.run(**run_kwargs)``; on exception it rebuilds (factory should
    wire restore()) and retries with backoff.

    A crash INSIDE ``factory()`` — a corrupt checkpoint restore, a sink
    that cannot connect at build time — counts as a worker crash and
    rides the same backoff/give-up ladder: before r17 it propagated
    straight out, turning a transient restore failure into a permanent
    supervisor death (tests/test_supervisor.py pins the fix).

    ``time_fn``/``sleep_fn`` are injectable so the backoff-window logic
    (reset after a healthy era, give-up inside a crash burst) is testable
    without wall-clock sleeps."""

    def __init__(self, factory: Callable, config: SupervisorConfig = SupervisorConfig(),
                 time_fn: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 **run_kwargs):
        self.factory = factory
        self.config = config
        self.run_kwargs = run_kwargs
        self.restarts = 0
        self._time = time_fn
        self._sleep = sleep_fn
        self.m_restarts = REGISTRY.counter("worker_restarts_total",
                                           "supervisor worker restarts")

    def run(self) -> None:
        crash_times: list[float] = []
        backoff = self.config.backoff_initial
        while True:
            worker = None
            try:
                worker = self.factory()
                worker.run(**self.run_kwargs)
                return  # clean exit
            except KeyboardInterrupt:
                if worker is not None:
                    worker.finalize()
                raise
            except Exception as e:  # noqa: BLE001 — the supervisor's job
                now = self._time()
                recent = [
                    t for t in crash_times
                    if now - t < self.config.window_seconds
                ]
                if not recent:  # healthy era since the last crash burst
                    backoff = self.config.backoff_initial
                crash_times = recent + [now]
                self.restarts += 1
                self.m_restarts.inc()
                if len(crash_times) > self.config.max_restarts:
                    log.error("crash loop (%d crashes in %.0fs); giving up",
                              len(crash_times), self.config.window_seconds)
                    raise
                log.exception("worker crashed (%s); restarting in %.1fs",
                              e, backoff)
                self._sleep(backoff)
                backoff = min(backoff * 2, self.config.backoff_max)
