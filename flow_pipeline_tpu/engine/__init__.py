"""Streaming engine: the "processor" stage.

The reference's architecture reserves a processor slot between Kafka and
the database — "a _processor_ that would enrich the data by consuming from
Kafka and re-injecting the data ... or directly into the database"
(ref: README.md:44-47). This package is that service, TPU-backed:

    consumer.poll -> columnar decode -> model.update (device sketches)
      -> window close -> rows -> sinks -> snapshot -> offset commit

Delivery contract: offsets commit only after the covering flush/snapshot
(at-least-once; the reference inserter loses up to flush.count-1 rows by
marking first, ref: inserter/inserter.go:188). Snapshot/restore covers the
open-window sketch state so a restarted worker resumes without double
counting (SURVEY.md §5 checkpoint/resume).
"""

from .worker import StreamWorker, WorkerConfig
from .windowed import WindowedHeavyHitter
from .checkpoint import save_checkpoint, load_checkpoint
from .fused import FusedPipeline
from .prefetch import PrefetchConsumer
from .supervisor import Supervisor, SupervisorConfig

__all__ = [
    "StreamWorker",
    "WorkerConfig",
    "FusedPipeline",
    "PrefetchConsumer",
    "WindowedHeavyHitter",
    "save_checkpoint",
    "load_checkpoint",
    "Supervisor",
    "SupervisorConfig",
]
