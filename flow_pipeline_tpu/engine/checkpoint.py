"""Sketch-state snapshot / restore.

Kafka offsets are the reference's only checkpoint ("it fetches from the
current offset", ref: README.md:115); a sketch worker additionally needs
the open-window device state so a restart resumes without double counting
(SURVEY.md §5). A checkpoint is a directory with:

- ``arrays.npz``   every device/host array leaf (numpy, compressed)
- ``meta.json``    consumer positions, window dicts, scalars, tree layout

Writes are atomic (tmp dir + rename) so a crash mid-write leaves the
previous checkpoint intact. Only numpy/json are used — no pickle, so a
checkpoint directory is safe to share between trust domains.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any

import numpy as np


def _encode(obj: Any, arrays: dict[str, np.ndarray], path: str) -> Any:
    """Recursively split a state object into JSON-able structure + arrays."""
    if isinstance(obj, dict):
        return {
            "__kind__": "dict",
            "items": [
                [_encode(k, arrays, f"{path}.k{i}"),
                 _encode(v, arrays, f"{path}.v{i}")]
                for i, (k, v) in enumerate(obj.items())
            ],
        }
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # NamedTuple
        return {
            "__kind__": "namedtuple",
            "name": type(obj).__name__,
            "fields": {
                f: _encode(getattr(obj, f), arrays, f"{path}.{f}")
                for f in obj._fields
            },
        }
    if isinstance(obj, (list, tuple)):
        return {
            "__kind__": "list" if isinstance(obj, list) else "tuple",
            "items": [_encode(v, arrays, f"{path}.{i}") for i, v in enumerate(obj)],
        }
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    # array-like (jax or numpy): materialize to host
    arr = np.asarray(obj)
    arrays[path] = arr
    return {"__kind__": "array", "ref": path}


def _decode(spec: Any, arrays) -> Any:
    if isinstance(spec, dict) and "__kind__" in spec:
        kind = spec["__kind__"]
        if kind == "dict":
            return {
                _freeze(_decode(k, arrays)): _decode(v, arrays)
                for k, v in spec["items"]
            }
        if kind == "namedtuple":
            return {f: _decode(v, arrays) for f, v in spec["fields"].items()}
        if kind in ("list", "tuple"):
            items = [_decode(v, arrays) for v in spec["items"]]
            return items if kind == "list" else tuple(items)
        if kind == "array":
            return arrays[spec["ref"]]
        raise ValueError(f"unknown kind {kind}")
    return spec


def _freeze(key):
    return tuple(key) if isinstance(key, list) else key


def save_checkpoint(path: str, state: Any) -> None:
    """Atomically write ``state`` (nested dicts/lists/NamedTuples/arrays)."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    meta = _encode(state, arrays, "r")
    tmp = tempfile.mkdtemp(prefix=".ckpt-", dir=parent)
    try:
        np.savez_compressed(os.path.join(tmp, "arrays.npz"), **arrays)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.isdir(path):
            old = path + ".old"
            # a crash between the renames below can leave a stale .old;
            # clear it or every future snapshot fails with ENOTEMPTY
            if os.path.isdir(old):
                shutil.rmtree(old)
            os.rename(path, old)
            os.rename(tmp, path)
            shutil.rmtree(old)
        else:
            os.rename(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def checkpoint_exists(path: str) -> bool:
    return os.path.isdir(path) or os.path.isdir(path + ".old")


def load_checkpoint(path: str) -> Any:
    """Load a checkpoint. NamedTuples come back as field dicts — callers
    rebuild their concrete state types (see StreamWorker.restore).

    Falls back to ``<path>.old`` when the primary is missing: a crash
    between save_checkpoint's two renames leaves only the previous
    checkpoint under .old, which is still a consistent snapshot."""
    if not os.path.isdir(path) and os.path.isdir(path + ".old"):
        path = path + ".old"
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    arrays = np.load(os.path.join(path, "arrays.npz"))
    return _decode(meta, arrays)
