"""Sketch-state snapshot / restore.

Kafka offsets are the reference's only checkpoint ("it fetches from the
current offset", ref: README.md:115); a sketch worker additionally needs
the open-window device state so a restart resumes without double counting
(SURVEY.md §5). A checkpoint is a directory with:

- ``arrays.npz``   every device/host array leaf (numpy, compressed)
- ``meta.json``    consumer positions, window dicts, scalars, tree layout

Writes follow the full durable-publish protocol via ``utils/fsutil``
(this was the one durable surface with ZERO fsyncs before flowtorn):
each payload is written with write→fsync→replace→dir-fsync inside a
staging directory, the staging directory is atomically renamed over
the target, and the containing directory is fsynced — so a crash at
ANY point leaves the complete old checkpoint (possibly under ``.old``)
or the complete new one, never a torn or silently-empty mix. The
crash-point model checker (``make crash-parity``) enumerates every
window of the save and pins exactly that. Only numpy/json are used —
no pickle, so a checkpoint directory is safe to share between trust
domains.
"""

from __future__ import annotations

# flowlint: durable-checked

import io
import json
import os
import shutil
import tempfile
from typing import Any

import numpy as np

from ..utils import fsutil


def _encode(obj: Any, arrays: dict[str, np.ndarray], path: str) -> Any:
    """Recursively split a state object into JSON-able structure + arrays."""
    if isinstance(obj, dict):
        return {
            "__kind__": "dict",
            "items": [
                [_encode(k, arrays, f"{path}.k{i}"),
                 _encode(v, arrays, f"{path}.v{i}")]
                for i, (k, v) in enumerate(obj.items())
            ],
        }
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # NamedTuple
        return {
            "__kind__": "namedtuple",
            "name": type(obj).__name__,
            "fields": {
                f: _encode(getattr(obj, f), arrays, f"{path}.{f}")
                for f in obj._fields
            },
        }
    if isinstance(obj, (list, tuple)):
        return {
            "__kind__": "list" if isinstance(obj, list) else "tuple",
            "items": [_encode(v, arrays, f"{path}.{i}") for i, v in enumerate(obj)],
        }
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    # array-like (jax or numpy): materialize to host
    arr = np.asarray(obj)
    arrays[path] = arr
    return {"__kind__": "array", "ref": path}


def _decode(spec: Any, arrays) -> Any:
    if isinstance(spec, dict) and "__kind__" in spec:
        kind = spec["__kind__"]
        if kind == "dict":
            return {
                _freeze(_decode(k, arrays)): _decode(v, arrays)
                for k, v in spec["items"]
            }
        if kind == "namedtuple":
            return {f: _decode(v, arrays) for f, v in spec["fields"].items()}
        if kind in ("list", "tuple"):
            items = [_decode(v, arrays) for v in spec["items"]]
            return items if kind == "list" else tuple(items)
        if kind == "array":
            return arrays[spec["ref"]]
        raise ValueError(f"unknown kind {kind}")
    return spec


def _freeze(key):
    return tuple(key) if isinstance(key, list) else key


def save_checkpoint(path: str, state: Any) -> None:
    """Atomically and DURABLY write ``state`` (nested dicts/lists/
    NamedTuples/arrays). The payloads are staged (and individually
    fsynced) in a sibling temp directory, the directory is renamed over
    the target, and the parent directory entry is fsynced — only then
    is the superseded ``.old`` tree deleted, so every crash window
    leaves a complete old or complete new checkpoint on disk."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    meta = _encode(state, arrays, "r")
    tmp = tempfile.mkdtemp(prefix=".ckpt-", dir=parent)
    try:
        # serialize in memory, publish through the one durable-write
        # idiom (write tmp -> fsync -> replace -> dir fsync): numpy's
        # own savez path never fsyncs
        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        fsutil.write_bytes_durable(os.path.join(tmp, "arrays.npz"),
                                   buf.getvalue())
        fsutil.write_bytes_durable(os.path.join(tmp, "meta.json"),
                                   json.dumps(meta).encode("utf-8"))
        if os.path.isdir(path):
            old = path + ".old"
            # a crash between the renames below can leave a stale .old;
            # clear it or every future snapshot fails with ENOTEMPTY
            if os.path.isdir(old):
                fsutil.rmtree(old)
            fsutil.rename(path, old)
            fsutil.rename(tmp, path)
            fsutil.rmtree(old)
        else:
            fsutil.rename(tmp, path)
            # a crash between the two renames of a PREVIOUS save leaves
            # the predecessor under .old with no primary; now that a
            # complete new checkpoint is published (rename above), the
            # stale .old is superseded — clear it AFTER publishing so
            # no crash window is ever left with neither tree
            if os.path.isdir(path + ".old"):
                fsutil.rmtree(path + ".old")
        # directory-entry barrier: the renames above (and the .old
        # cleanup) are durable only once the parent directory is —
        # without this a power loss after the ack could silently revert
        # an acked checkpoint to its predecessor
        fsutil.fsync_dir(parent)
    except BaseException:
        # flowlint: disable=durability-protocol -- best-effort cleanup of the unpublished staging dir on a failed save; no ack references it, resurrection after a crash is harmless garbage
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def checkpoint_exists(path: str) -> bool:
    return os.path.isdir(path) or os.path.isdir(path + ".old")


def load_checkpoint(path: str) -> Any:
    """Load a checkpoint. NamedTuples come back as field dicts — callers
    rebuild their concrete state types (see StreamWorker.restore).

    Falls back to ``<path>.old`` when the primary is missing: a crash
    between save_checkpoint's two renames leaves only the previous
    checkpoint under .old, which is still a consistent snapshot."""
    if not os.path.isdir(path) and os.path.isdir(path + ".old"):
        path = path + ".old"
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    arrays = np.load(os.path.join(path, "arrays.npz"))
    return _decode(meta, arrays)
