"""Live query API: serve aggregates straight off the worker's models.

The reference answers "top talkers right now?" by scanning raw rows in the
database at query time (ref: compose/grafana/dashboards/viz.json queries,
SURVEY.md §3.5) — O(rows). Here the device already holds ranked sketch
state, so the worker can answer in O(K) without touching storage, including
for the WINDOW STILL OPEN (storage only sees closed windows):

    GET /healthz            liveness + progress counters
    GET /topk?model=X&k=N   current open-window top-K from the sketch
    GET /windows?model=X    open exact-window slots + row counts
    GET /alerts?limit=N     recent DDoS alerts

Handlers acquire the worker's lock (held across each run_once step), so
queries see consistent model state and never race a concurrent flush.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..models.ddos import DDoSDetector
from ..models.window_agg import WindowAggregator
from ..obs import get_logger
from ..obs.server import reply_json
from ..sink.base import rows_to_records
from .windowed import WindowedHeavyHitter

log = get_logger("query")


class QueryServer:
    """HTTP query endpoint over a StreamWorker's models.

    ``mesh`` (a mesh.MeshCoordinator) makes /topk mesh-aware: instead of
    reading one worker's sketch, the coordinator fans the query to every
    live member's state provider and answers from the network-wide
    MERGED open-window view — the same monoid fold the window-close
    merge runs, so the answer equals a single worker seeing the whole
    stream (tests/test_mesh.py pins the equality).

    ``serve`` (a serve.SnapshotStore) lets /topk answer from the
    flowserve snapshot WITHOUT the worker lock whenever the snapshot is
    fresh — covers the exact consumed point (``flows_seen`` matches), so
    the answer is bit-identical to the locked read
    (tests/test_serve.py pins the parity); anything staler falls back to
    the locked path."""

    def __init__(self, worker, port: int = 8082, host: str = "127.0.0.1",
                 mesh=None, serve=None):
        self.worker = worker
        self.mesh = mesh
        self.serve = serve
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                url = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(url.query).items()}
                try:
                    handler = {
                        "/healthz": outer._healthz,
                        "/topk": outer._topk,
                        "/windows": outer._windows,
                        "/alerts": outer._alerts,
                    }.get(url.path)
                    if handler is None:
                        reply_json(self, {"error":
                                          f"unknown path {url.path}"}, 404)
                        return
                    if url.path == "/topk" and outer.mesh is None \
                            and outer.worker is not None:
                        # flowserve fast path FIRST, outside the lock: a
                        # fresh snapshot answers without stalling (or
                        # being stalled by) the dataplane
                        result = outer._topk_from_snapshot(q)
                        if result is not None:
                            reply_json(self, result, default=str)
                            return
                    if outer.mesh is not None and url.path in (
                            "/topk", "/healthz"):
                        # mesh fan-out acquires MEMBER locks; it must
                        # not run under a co-resident worker's lock
                        result = handler(q)
                    elif outer.worker is None:
                        reply_json(self, {"error":
                                          "no worker behind this path"},
                                   400)
                        return
                    else:
                        with outer.worker.lock:  # consistent view
                            result = handler(q)
                    reply_json(self, result, default=str)
                except (KeyError, ValueError) as e:
                    # malformed query params (/topk?k=abc) and unknown
                    # models answer 400, never a handler traceback
                    reply_json(self, {"error": str(e)}, 400)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="query-http", daemon=True
        )

    # ---- endpoints --------------------------------------------------------

    def _healthz(self, q) -> dict:
        if self.worker is None:
            st = self.mesh.status()
            return {"ok": True, "mesh_epoch": st["epoch"],
                    "mesh_members": len(st["members"]),
                    "models": [s.name for s in self.mesh.specs]}
        return {
            "ok": True,
            "flows_seen": self.worker.flows_seen,
            "batches_seen": self.worker.batches_seen,
            "models": list(self.worker.models),
        }

    def _model(self, q, want_type):
        name = q.get("model")
        if name:
            model = self.worker.models.get(name)
            if model is None:
                raise KeyError(f"no model named {name!r}")
            return name, model
        for name, model in self.worker.models.items():
            if isinstance(model, want_type):
                return name, model
        raise KeyError(f"no model of kind {want_type.__name__} configured")

    def _topk_from_snapshot(self, q):
        """Lock-free /topk off the flowserve snapshot, or None when the
        snapshot cannot answer VERBATIM what the locked path would:
        it must cover the exact consumed point (``flows_seen`` — reading
        the worker's counter is one atomic attribute load), know the
        requested model, and hold at least k extracted rows. The
        returned dict is shaped exactly like the locked ``_topk`` (the
        parity test compares them field-for-field)."""
        if self.serve is None:
            return None
        snap = self.serve.current
        if snap is None or snap.source != "worker" or \
                snap.flows_seen != self.worker.flows_seen:
            return None
        name = q.get("model")
        if name:
            fam = snap.families.get(name)
        else:
            fam = next(iter(snap.families.values()), None)
        k = int(q.get("k", 10))
        if fam is None or k < 0 or k > fam.depth:
            # the locked path serves (or errors) instead — a negative k
            # would slice from the END here but not there, and the fast
            # path must answer VERBATIM or not at all
            return None
        rows = {col: arr[:k] for col, arr in fam.rows.items()}
        return {
            "model": fam.name,
            "window_start": fam.window_start,
            "rows": rows_to_records(rows),
        }

    def _topk(self, q) -> dict:
        if self.mesh is not None:
            # the coordinator merges every live member's open-window
            # state (mesh.MeshCoordinator.query_topk) — O(K) per member
            return self.mesh.query_topk(
                q.get("model"), int(q["k"]) if "k" in q else None)
        name, model = self._model(q, WindowedHeavyHitter)
        if not isinstance(model, WindowedHeavyHitter):
            raise ValueError(f"model {name!r} has no top-K surface")
        # host sketch backend: model state is engine-resident between
        # syncs; pull it current before reading (we hold worker.lock)
        self.worker.sync_sketch_states()
        k = int(q.get("k", 10))
        top = model.model.top(k)
        return {
            "model": name,
            "window_start": model.current_slot,
            "rows": rows_to_records(top),
        }

    def _windows(self, q) -> dict:
        name, model = self._model(q, WindowAggregator)
        if not isinstance(model, WindowAggregator):
            raise ValueError(f"model {name!r} is not a window aggregator")
        model._drain()
        return {
            "model": name,
            "watermark": model.watermark,
            "open_windows": [
                {"timeslot": slot, "groups": len(store)}
                for slot, store in sorted(model.windows.items())
            ],
        }

    def _alerts(self, q) -> dict:
        limit = int(q.get("limit", 50))
        out = []
        for name, model in self.worker.models.items():
            if isinstance(model, DDoSDetector):
                # `recent` is retained for queries; `alerts` drains to sinks
                out.extend(
                    {**a, "model": name} for a in list(model.recent)[-limit:]
                )
        return {"alerts": rows_to_records(out)}

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> "QueryServer":
        self._thread.start()
        log.info("query api on http://%s:%d", self.host, self.port)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
