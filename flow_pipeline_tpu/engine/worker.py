"""StreamWorker: the TPU processor service loop.

Wires consumer -> models -> sinks with at-least-once offset commits and
periodic snapshots. One worker owns one consumer (one partition subset) and
any number of aggregation models; scale-out is more workers on more
partitions — the sarama consumer-group model (ref: inserter/inserter.go:
238-256) — and/or a device mesh inside one worker (parallel/).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional, Sequence

from ..families import registry
from ..guard import GuardConfig, GuardController
from ..models.ddos import DDoSDetector
from ..models.heavy_hitter import HHState
from ..models.window_agg import WindowAggregator
from ..obs import REGISTRY, get_logger
from ..obs.trace import TRACER
from ..obs.tracing import StageTimer

# Buckets for the window-end -> sink-commit latency histogram: seconds,
# spanning "flushed within the batch" (~1s) to "stuck for an hour".
COMMIT_LATENCY_BUCKETS = (
    1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1_200.0, 1_800.0,
    3_600.0,
)
from .checkpoint import load_checkpoint, save_checkpoint
from .prefetch import PrefetchConsumer
from .windowed import WindowedHeavyHitter

log = get_logger("worker")


class _ShedPrep:
    """Stand-in prepared object when flowguard admission sheds an ENTIRE
    batch on the group thread: carries the (now empty) batch through the
    executor so its offset range still reaches the commit path — shed
    rows were consumed and accounted, never lost to replay."""

    __slots__ = ("batch",)

    def __init__(self, batch):
        self.batch = batch


@dataclass(frozen=True)
class WorkerConfig:
    poll_max: int = 8192
    snapshot_every: int = 50  # batches between snapshots (0 = never)
    checkpoint_path: Optional[str] = None
    idle_sleep: float = 0.05
    # Double-buffered feed (SURVEY §7): >0 wraps the consumer in a
    # PrefetchConsumer holding this many decoded batches ready, so host
    # fetch+decode for batch i+1 overlaps the device step for batch i.
    prefetch: int = 2
    # Fuse the per-model device updates into one jitted step per batch
    # with shared pre-aggregation (engine.fused) when the model set
    # supports it; falls back to the serial per-model path otherwise
    # (e.g. mesh-sharded models). Outputs are equivalence-tested.
    fused: bool = True
    # Host-grouped pre-aggregation (engine.hostfused): "auto" uses it
    # when the default backend is CPU (numpy's introsort beats XLA:CPU's
    # lax.sort ~20x on one core, so grouping host-side and shipping only
    # compact group tables to the XLA step is the idiomatic CPU layout);
    # "on"/"off" force/forbid. On TPU "auto" keeps the device-sorted
    # fused step.
    host_assist: str = "auto"
    # Sketch-step backend (flow_pipeline_tpu.hostsketch): "device" keeps
    # the jitted CMS/top-K apply (engine.hostfused/_cached_apply — the
    # TPU dataplane and the pre-r8 CPU path); "host" executes it in the
    # native threaded uint64 engine behind the same apply seam —
    # bit-exact on the integer envelope (tests/test_hostsketch.py) and
    # the big remaining CPU lever (device_apply was ~66% of e2e wall,
    # BENCH_r06). Requires the host-grouped pipeline (CPU backend or
    # host_assist="on"); falls back to device with a warning otherwise.
    sketch_backend: str = "device"
    # Ingest dataplane (flow_pipeline_tpu.ingest): "pipelined" runs the
    # host pre-aggregation on a group thread (overlapping the device
    # step), window extraction + sink writes on a background flusher, and
    # sharded grouping on a thread pool — engaged when the host-grouped
    # pipeline is active (it has the prepare/apply split); "serial" keeps
    # the single-threaded path (the pre-r6 behavior, the A/B baseline).
    ingest_mode: str = "pipelined"
    ingest_shards: int = 0       # grouping shards: 0 auto, 1 disables
    ingest_depth: int = 2        # prepared batches held ready
    ingest_flush_queue: int = 8  # queued background flush jobs (bound)
    # Worker threads inside the native dataplane kernels (the fused
    # pass, the staged sketch engine, lane building, the wagg fold) —
    # every kernel is deterministic at ANY count, so this is purely a
    # throughput knob. 0 keeps the hostsketch engine's conservative
    # auto count (half the cores, capped at 4): the kernels are
    # memory-bound and extra threads thrash small hosts' shared cache.
    ingest_threads: int = 0
    ingest_native_group: bool = False  # C hash-group kernel (numpy fallback)
    # Single-pass fused native dataplane (native/flowfused.cc): "auto"
    # runs group->cascade->sketch in one C pass whenever the host sketch
    # backend is active and the library exports it (falling back to the
    # staged path LOUDLY — gauge + warning — when the .so is stale);
    # "on" demands it (raises when it cannot serve); "off" keeps the
    # staged prepare/apply split, the bit-exact parity reference.
    ingest_fused: str = "auto"
    # Full-fidelity raw archiving (the reference's flows_raw path,
    # ref: compose/clickhouse/create.sh:36-62): every consumed batch is
    # handed to sinks exposing archive_raw(batch). Off by default — the
    # pre-aggregated tables are the serving path; raw rows are for
    # drill-down/audit and cost one row per flow.
    archive_raw: bool = False
    # sketchwatch (-obs.audit, obs/audit.py): the sampled exact shadow
    # audit measuring how wrong the sketches are. "sample" keeps exact
    # uint64 counts for a deterministic ~1/256 key cohort and publishes
    # relative-error/recall/saturation metrics at every window close;
    # "full" audits every key (tests, the error-vs-fill sweep); "off"
    # disables. Needs the host-grouped pipeline (CPU backend or
    # -processor.hostassist on) — elsewhere it quietly stays off.
    obs_audit: str = "sample"
    # flowguard (-guard.lag, guard/): watermark-lag budget in seconds
    # before the degradation ladder engages. 0 (the default) disarms
    # the controller entirely — every exact-parity path runs untouched.
    guard_lag: float = 0.0
    # Ladder ceiling: level 1 drops optional work, levels 2..max are
    # hash-sampled admission at keep rate 1/2^(level-1).
    guard_max_level: int = 6
    # The role this worker's flow_build_info identity gauge publishes
    # under. A mesh member's INNER worker must identify as "member" —
    # publishing a second role="worker" series next to the member's
    # would give one process two identities (MeshMember rewrites this).
    build_role: str = "worker"


class StreamWorker:
    """Drives models from a consumer; emits rows to sinks.

    models: {"name": model} — models expose update(batch) and one of
      flush(force)->rows-dict (WindowAggregator), flush(force)->list of
      row-dicts (WindowedHeavyHitter), or close_sub_window/alerts
      (DDoSDetector).
    sinks: objects with write(table: str, rows) -> None.
    """

    def __init__(self, consumer, models: dict[str, Any],
                 sinks: Sequence[Any] = (), config: WorkerConfig = WorkerConfig()):
        if config.prefetch and consumer is not None and not isinstance(
                consumer, PrefetchConsumer):
            consumer = PrefetchConsumer(consumer, depth=config.prefetch,
                                        poll_max=config.poll_max)
        self.consumer = consumer
        self.models = models
        self.sinks = list(sinks)
        self.config = config
        if config.ingest_mode not in ("pipelined", "serial"):
            raise ValueError(
                f"ingest_mode must be pipelined|serial, "
                f"got {config.ingest_mode!r}")
        if config.sketch_backend not in ("device", "host"):
            raise ValueError(
                f"sketch_backend must be device|host, "
                f"got {config.sketch_backend!r}")
        if config.ingest_fused not in ("auto", "on", "off"):
            raise ValueError(
                f"ingest_fused must be auto|on|off, "
                f"got {config.ingest_fused!r}")
        if config.ingest_threads < 0:
            raise ValueError(
                f"ingest_threads must be >= 0 (0 = auto), "
                f"got {config.ingest_threads}")
        if config.ingest_fused == "on" and config.sketch_backend != "host":
            raise ValueError(
                "ingest_fused='on' requires sketch_backend='host' — the "
                "fused pass updates the host sketch engine in place")
        if config.obs_audit not in ("off", "sample", "full"):
            raise ValueError(
                f"obs_audit must be off|sample|full, "
                f"got {config.obs_audit!r}")
        if config.guard_lag < 0:
            raise ValueError(
                f"guard_lag must be >= 0 (0 = disarmed), "
                f"got {config.guard_lag}")
        # flowguard: constructed unconditionally (its metric families
        # must exist — as zeros — on every worker for the honesty
        # tests), armed only when a lag budget is declared
        self.guard = GuardController(GuardConfig(
            lag_budget=config.guard_lag,
            max_level=config.guard_max_level))
        # invertible hh families (-hh.sketch=invertible) have no jitted
        # table step: they are served by the host sketch pipeline
        # (staged or fused) or, failing that, the per-model numpy path
        hh_sketch = self._hh_sketch_mode(models)
        self.fused = None
        if config.fused and models:
            from .fused import FusedPipeline
            from .hostfused import HostGroupPipeline

            if FusedPipeline.supported(models):
                host_grouped = HostGroupPipeline.eligible(config.host_assist)
                if config.sketch_backend == "host" and host_grouped:
                    from ..hostsketch import HostSketchPipeline

                    self.fused = HostSketchPipeline(
                        models, shards=config.ingest_shards,
                        native_group=config.ingest_native_group,
                        fused=config.ingest_fused,
                        audit=config.obs_audit,
                        threads=config.ingest_threads)
                elif config.sketch_backend == "host":
                    # the host engine consumes the host-grouped prepare
                    # tables; without them there is nothing to feed it
                    log.warning(
                        "sketch.backend=host needs the host-grouped "
                        "pipeline (CPU backend or -processor.hostassist "
                        "on); keeping the device sketch step")
                    self.fused = FusedPipeline(models)
                elif host_grouped:
                    self.fused = HostGroupPipeline(
                        models, shards=config.ingest_shards,
                        native_group=config.ingest_native_group,
                        audit=config.obs_audit)
                else:
                    self.fused = FusedPipeline(models)
            else:
                log.info("model set not fusable; using per-model updates")
        if hh_sketch in ("invertible", "mixed") and self.fused is not None:
            from ..hostsketch import HostSketchPipeline

            if not isinstance(self.fused, HostSketchPipeline):
                # the jitted table step cannot fold invertible state;
                # only the host sketch engine (and the per-model numpy
                # fallback) can — degrade loudly rather than corrupt
                log.warning(
                    "hh.sketch=invertible needs the host sketch "
                    "pipeline (-sketch.backend=host + CPU backend or "
                    "-processor.hostassist on); falling back to the "
                    "per-model numpy path for this worker")
                self.fused = None
        if config.ingest_fused == "on":
            # "on" is a hard requirement everywhere, not just inside the
            # pipeline constructor: any selection-level fallback above
            # (non-fusable models, host grouping ineligible, fused=False)
            # would otherwise silently run the staged/device path under a
            # flag that documents "errors when it cannot serve"
            from ..hostsketch import HostSketchPipeline

            if not isinstance(self.fused, HostSketchPipeline):
                raise RuntimeError(
                    "ingest_fused='on' but the host sketch pipeline was "
                    "not selected — it needs a fusable model set and "
                    "host-grouped pre-aggregation (CPU backend or "
                    "-processor.hostassist on)")
        # Pipelined ingest runtime: a group thread prepares batch N+1
        # while this thread applies batch N, and a background flusher
        # takes window extraction + sink writes off the hot path. Only
        # the host-grouped pipeline has the prepare/apply split; other
        # paths (device-sorted fused, per-model, mesh-sharded) keep the
        # serial loop — their overlap comes from jax async dispatch.
        self.executor = None
        self.flusher = None
        if config.ingest_mode == "pipelined" and consumer is not None:
            from .hostfused import HostGroupPipeline
            from ..ingest import AsyncFlusher, PipelinedExecutor

            if isinstance(self.fused, HostGroupPipeline) and not isinstance(
                    consumer, PrefetchConsumer):
                # prefetch=0 leaves the raw consumer unwrapped; moving its
                # poll() onto the group thread while commit() stays here
                # would hit a non-thread-safe Kafka client from two
                # threads. The PrefetchConsumer wrap is what serializes
                # all client access on its feed thread — without it, keep
                # the serial loop.
                log.info("ingest pipelined mode needs the prefetch wrap "
                         "(feed.prefetch > 0); using the serial path")
            elif isinstance(self.fused, HostGroupPipeline):
                # the guard admission runs INSIDE the prepare wrapper on
                # the group thread: shed rows never reach grouping, so
                # degradation sheds the pre-aggregation cost too
                self.executor = PipelinedExecutor(
                    consumer, self._prepare_admitted,
                    poll_max=config.poll_max, depth=config.ingest_depth)
                self.flusher = AsyncFlusher(
                    max_queue=config.ingest_flush_queue)
                for m in models.values():
                    if isinstance(m, WindowedHeavyHitter) and \
                            hasattr(m.model, "top_lazy"):
                        m.lazy_extract = True
        self.batches_seen = 0
        self.flows_seen = 0
        # offsets covered by state (committable after next snapshot/flush)
        self._covered: dict[int, int] = {}
        self._emitted_since_snapshot = False
        # Guards model/window state against concurrent readers (the live
        # query API); the worker holds it across each run_once step.
        self.lock = threading.Lock()
        # flowserve hook (serve.WorkerServePublisher.attach): when set,
        # every _process step lets it publish an immutable snapshot for
        # the lock-free read path. Wired once before run() starts.
        # flowlint: unguarded -- bound once at wiring (before the loop), then read on the worker thread only
        self.serve = None
        self.m_flows = REGISTRY.counter("flows_processed_total",
                                        "flows decoded and aggregated")
        self.m_batches = REGISTRY.counter("batches_processed_total",
                                          "batches pulled off the bus")
        self.m_rows = REGISTRY.counter("insert_count",
                                       "rows flushed to sinks")
        self.m_lag = REGISTRY.gauge("consumer_lag", "bus messages behind")
        self.m_raw = REGISTRY.counter("raw_rows_archived",
                                      "rows archived to flows_raw")
        self.m_late = REGISTRY.gauge(
            "late_flows_dropped",
            "rows dropped because their sketch window had closed",
        )
        self.m_proc = REGISTRY.summary("flow_processing_time_us",
                                       "per-batch processing time")
        # End-to-end watermark: the newest flow-export timestamp (window
        # end) whose rows are COMMITTED to the sinks, plus the
        # window-end -> sink-commit latency distribution. Registered
        # eagerly (not on first flush) so /metrics always carries the
        # families the dashboards chart.
        self.m_commit_wm = REGISTRY.gauge(
            "flow_commit_watermark_seconds",
            "newest flow-export timestamp (window end, epoch s) whose "
            "rows are committed to the sinks")
        self.m_commit_lat = REGISTRY.histogram(
            "flow_sink_commit_latency_seconds",
            "window end (flow export time) -> sink commit latency",
            buckets=COMMIT_LATENCY_BUCKETS)
        # host_fused phase counters (flowtrace): fed by the fused native
        # dataplane from the kernels' stats out-struct; the name/help
        # specs live in hostsketch.pipeline (the publisher) and are
        # registered here so the family exists — and scrapes as zeros —
        # on every worker, fused or not.
        from ..hostsketch.pipeline import (GROUPS_COUNTER, PHASE_COUNTERS,
                                           ROWS_COUNTER)

        REGISTRY.counter(*PHASE_COUNTERS["host_fused"])
        REGISTRY.counter(*ROWS_COUNTER)
        REGISTRY.counter(*GROUPS_COUNTER)
        # the degradation gauge likewise: the NativePathDegraded alert
        # must resolve against every worker's /metrics, not only those
        # whose pipeline selection happened to touch a native feature
        from .hostfused import _DEGRADED_GAUGE

        REGISTRY.gauge(*_DEGRADED_GAUGE)
        # sketchwatch families likewise registered eagerly (as zeros) on
        # every worker — the dashboard/alert honesty tests resolve the
        # sketch-health surface against this registration
        from ..obs.audit import register_audit_metrics

        register_audit_metrics()
        if config.obs_audit != "off" and \
                getattr(self.fused, "audit", None) is None and models:
            has_hh = any(
                isinstance(m, WindowedHeavyHitter)
                and getattr(m.model, "snapshot_kind", None)
                == "windowed_hh" for m in models.values())
            if not has_hh:
                # nothing sketch-backed to audit (dense/exact models
                # only) — flipping pipeline knobs would not change that
                log.info("obs.audit=%s: no sketch-backed families in "
                         "the model set; nothing to audit",
                         config.obs_audit)
            else:
                # the audit consumes the host-grouped pipelines'
                # tables; the device-sorted/per-model paths have
                # nothing to feed it
                log.info("obs.audit=%s needs the host-grouped pipeline "
                         "(CPU backend or -processor.hostassist on); "
                         "sketch accuracy audit is off for this worker",
                         config.obs_audit)
        # runtime identity: what this worker ACTUALLY runs (native
        # capability set, trace mode, sketch backend) — dashboards and
        # bench artifacts join against it instead of trusting flags
        from ..obs.buildinfo import publish_build_info

        publish_build_info(config.build_role,
                           sketch_backend=config.sketch_backend,
                           hh_sketch=hh_sketch)
        # flowlint: unguarded -- written by whichever single thread runs _write_rows (worker inline, or the one flusher thread)
        self._commit_watermark = 0.0
        # flowlint: unguarded -- worker thread only (set per _process step, read when queueing flush jobs)
        self._trace_chunk = -1
        # per-stage breakdown (the reference charts the same
        # flow_summary_*_time_us family for its collector stages)
        self.stages = StageTimer()
        if config.archive_raw:
            # fail fast on schema drift instead of crash-looping on 400s
            for sink in self.sinks:
                check = getattr(sink, "check_raw_schema", None)
                if check is not None:
                    check()

    @staticmethod
    def _hh_sketch_mode(models: dict) -> str:
        """The heavy-hitter sketch family this worker actually runs —
        the flow_build_info ``hh_sketch`` label ("none" when the model
        set has no sketch-backed hh family)."""
        modes = {
            getattr(m.model.config, "hh_sketch", "table")
            for m in models.values()
            if isinstance(m, WindowedHeavyHitter)
            and getattr(m.model, "snapshot_kind", None) == "windowed_hh"}
        if not modes:
            return "none"
        if modes == {"table"}:
            return "table"
        # any invertible family needs the host sketch pipeline (the
        # fallback check below keys off this); a table+invertible mix
        # (-hh.sketch=auto's cascade flip) is labeled honestly
        return "invertible" if modes == {"invertible"} else "mixed"

    # ---- main loop --------------------------------------------------------

    def run_once(self) -> bool:
        """Poll one batch through the pipeline. Returns False when idle."""
        if self.executor is not None:
            prep = self.executor.next()  # grouped off-thread (ingest)
            if prep is None:
                if self.guard.armed:
                    # idle = caught up: feed lag 0 so the ladder can
                    # step back up without needing fresh traffic
                    self.guard.observe(0.0)
                return False
            with self.lock:
                return self._process(prep.batch, prep)
        batch = self.consumer.poll(self.config.poll_max)
        if batch is None or len(batch) == 0:
            if self.guard.armed:
                self.guard.observe(0.0)
            return False
        with self.lock:
            return self._process(batch)

    def _prepare_admitted(self, batch):
        """Group-thread prepare with flowguard admission in FRONT of the
        grouping pass, so shed rows never pay pre-aggregation. A stale
        ``level`` read here sheds one batch at the previous level — the
        per-row scale factor keeps even that exact."""
        if self.guard.sample_shift > 0:
            batch, _ = self.guard.admit(batch)
            if len(batch) == 0:
                return _ShedPrep(batch)
        return self.fused.prepare(batch)

    def _process(self, batch, prep=None) -> bool:
        t0 = time.perf_counter()
        t0_wall = time.time()
        self._trace_chunk = getattr(batch, "chunk_id", -1)
        guard = self.guard
        if guard.armed:
            # watermark lag = age of the backlog head (bus produce time
            # -> this pickup); unstamped transports (Kafka) report 0.0
            # and the ladder simply never engages for them
            pa = getattr(batch, "produced_at", 0.0)
            guard.observe(t0_wall - pa if pa > 0.0 else 0.0)
            if prep is None and guard.sample_shift > 0:
                # serial path (no group thread): admit here instead
                batch, _ = guard.admit(batch)
            # level >= 1 drops optional work FIRST: every registered
            # family's audit cohort stops refreshing and the trace ring
            # stops recording before any data does
            for _kind, attr in registry.audit_attrs():
                shadow = getattr(self.fused, attr, None)
                if shadow is not None:
                    shadow.paused = guard.drop_optional
            TRACER.paused = guard.drop_optional
        if self.config.archive_raw:
            archived = False
            for sink in self.sinks:
                fn = getattr(sink, "archive_raw", None)
                if fn is not None:
                    self.m_raw.inc(fn(batch))
                    archived = True
            # Raw rows have no merge semantics to absorb replayed batches
            # (unlike the aggregate partials), so force the snapshot/commit
            # right after archiving: the duplicate exposure shrinks to a
            # crash inside the archive -> snapshot gap — the same
            # irreducible at-least-once window as sink flushes (_process
            # below), not snapshot_every batches' worth of raw rows.
            self._emitted_since_snapshot |= archived
        with self.stages.stage("processing"):
            if len(batch) == 0:
                pass  # fully shed upstream; offsets still commit below
            elif prep is not None:
                self.fused.apply(prep)  # prepare ran on the group thread
            elif self.fused is not None:
                self.fused.update(batch)
            else:
                for model in self.models.values():
                    model.update(batch)
            for name, model in self.models.items():
                dropped = getattr(model, "late_flows_dropped", None)
                if dropped:
                    self.m_late.set(dropped, model=name)
        self.batches_seen += 1
        self.flows_seen += len(batch)
        self.m_flows.inc(len(batch))
        self.m_batches.inc()
        self.m_proc.observe((time.perf_counter() - t0) * 1e6)
        TRACER.record("apply", t0_wall, time.time(),
                      chunk=self._trace_chunk, rows=len(batch))
        if batch.last_offset >= 0:
            prev = self._covered.get(batch.partition, 0)
            self._covered[batch.partition] = max(prev, batch.last_offset + 1)
        self.flush_closed()
        # Snapshot immediately after any flush that emitted rows: a replay
        # from an older snapshot would rebuild and re-emit those windows
        # (duplicate partials inflate merging sinks). With this coupling the
        # duplicate exposure shrinks to a crash inside the sink-write ->
        # snapshot gap — the irreducible at-least-once window without
        # transactional sinks.
        if self._emitted_since_snapshot or (
            self.config.snapshot_every
            and self.batches_seen % self.config.snapshot_every == 0
        ):
            self.snapshot_and_commit()
        if self.serve is not None:
            # flowserve publish decision (window close / refresh due):
            # runs HERE, under the lock the read path never takes —
            # extraction cost is paid per publish, never per query
            self.serve.on_batch(self)
        return True

    def run(self, max_batches: Optional[int] = None,
            stop_when_idle: bool = False) -> None:
        try:
            done = 0
            while max_batches is None or done < max_batches:
                if self.run_once():
                    done += 1
                elif stop_when_idle:
                    break
                else:
                    time.sleep(self.config.idle_sleep)
            self.finalize()
        except BaseException:
            # flight-recorder dump on the way down: the last ring's worth
            # of per-chunk spans is exactly the causality a post-mortem
            # needs, and it is gone once the supervisor restarts us
            path = TRACER.dump_on_error("worker")
            if path:
                log.error("worker error: flowtrace flight recorder "
                          "dumped to %s", path)
            raise
        finally:
            # A crash mid-loop (e.g. a sink raising in _emit) must not
            # leak the feed/group/flush threads: the group thread owns
            # the wrapped consumer, and with a real broker a zombie would
            # keep the partitions assigned while a supervisor-built
            # replacement starves. Best effort — never mask the original
            # exception.
            if self.executor is not None:
                try:
                    self.executor.stop()
                except Exception:  # noqa: BLE001
                    log.exception("ingest executor stop failed during unwind")
            if self.flusher is not None:
                try:
                    self.flusher.stop()
                except Exception:  # noqa: BLE001
                    log.exception("ingest flusher stop failed during unwind")
            if isinstance(self.consumer, PrefetchConsumer):
                try:
                    self.consumer.stop()
                except Exception:  # noqa: BLE001
                    log.exception("prefetch stop failed during unwind")

    # ---- flushing ---------------------------------------------------------

    def flush_closed(self, force: bool = False) -> None:
        """Emit rows for closed (or all, when force) windows to the sinks."""
        t0 = time.perf_counter()
        emitted = self._flush_closed(force)
        # Observe only flushes that DID something: this runs every batch
        # but windows close hundreds of batches apart, so timing the
        # no-ops would bury real flush latency below every exported
        # quantile of the 1024-sample summary window. (The return value,
        # not the shared snapshot flag: raw archiving sets that flag
        # before the flush and would mask every mid-stream observation.)
        # Under the async flusher the jobs time THEMSELVES into the same
        # summary (_write_rows); timing the submit would double-count.
        if emitted and self.flusher is None:
            self.stages.observe("flushing", (time.perf_counter() - t0) * 1e6)

    def sync_sketch_states(self) -> None:
        """Export host-backend sketch state into the models before a read
        (checkpoint, forced flush, live top-K query). No-op on the device
        backend, where model state is always current. Callers must hold
        self.lock (the worker loop does; query_api acquires it)."""
        sync = getattr(self.fused, "sync_states", None)
        if sync is not None:
            sync()

    def _flush_closed(self, force: bool) -> bool:
        if force:
            # force closes the OPEN window straight off model state;
            # mid-stream (force=False) closes go through the pipeline's
            # _advance_hh, which syncs itself
            self.sync_sketch_states()
        emitted = False
        for name, model in self.models.items():
            if isinstance(model, WindowAggregator):
                win = model.config.window_seconds
                if self.flusher is not None:
                    # detach the closed stores under the lock (cheap dict
                    # pops); row building + sink writes run on the flusher
                    stores = model.pop_closed(force)
                    if stores:
                        from ..models.window_agg import rows_from_stores

                        cfg = model.config
                        self._emit(name, lambda c=cfg, s=stores:
                                   rows_from_stores(c, s),
                                   export_ts=max(s for s, _ in stores)
                                   + win)
                        emitted = True
                else:
                    rows = model.flush(force)
                    if len(rows["timeslot"]):
                        self._emit(f"{name}", rows, len(rows["timeslot"]),
                                   export_ts=int(rows["timeslot"].max())
                                   + win)
                        emitted = True
            elif isinstance(model, WindowedHeavyHitter):
                for top in model.flush(force):
                    # dict, or an unresolved LazyWindowTop (lazy_extract):
                    # _emit materializes it wherever the write runs
                    self._emit(f"{name}", top,
                               export_ts=self._top_export_ts(model, top))
                    emitted = True
            elif isinstance(model, DDoSDetector):
                if force:
                    model.close_sub_window()
                if model.alerts:
                    alerts, model.alerts = model.alerts, []
                    self._emit(f"{name}", alerts, len(alerts))
                    emitted = True
        return emitted

    @staticmethod
    def _top_export_ts(model, top):
        """Window-end export timestamp for one flushed top-K window —
        dict rows carry a timeslot column, lazy handles the slot attr."""
        slot = getattr(top, "timeslot", None)
        if slot is None and isinstance(top, dict) and len(top["timeslot"]):
            slot = int(top["timeslot"][0])
        if slot is None:
            return None
        return int(slot) + model.window_seconds

    @staticmethod
    def _materialize(rows):
        """Rows as handed to _emit -> concrete columnar rows/list."""
        if callable(rows):
            return rows()
        if hasattr(rows, "resolve"):
            return rows.resolve()
        return rows

    @staticmethod
    def _row_count(rows) -> int:
        if isinstance(rows, dict):
            if "timeslot" in rows and "valid" not in rows:
                return len(rows["timeslot"])
            return int(rows["valid"].sum())
        return len(rows)

    def _emit(self, table: str, rows, n: Optional[int] = None,
              export_ts: Optional[float] = None) -> None:
        """Write rows (or a deferred producer of rows) to the sinks —
        inline, or via the background flusher when the ingest runtime is
        on. A flusher failure surfaces on the next submit/drain and fails
        that step BEFORE its offsets commit (at-least-once). export_ts
        (window end, epoch s) feeds the commit-latency watermark; the
        triggering chunk's id is captured here so flush spans stay tied
        to the chunk that closed the window, across the thread hop."""
        self._emitted_since_snapshot = True
        chunk = self._trace_chunk
        if self.flusher is not None:
            self.flusher.submit(
                lambda: self._write_rows(table, rows, n, export_ts, chunk))
            return
        self._write_rows(table, rows, n, export_ts, chunk)

    def _write_rows(self, table: str, rows, n: Optional[int],
                    export_ts: Optional[float] = None,
                    chunk: int = -1) -> None:
        t0 = time.perf_counter()
        t0_wall = time.time()
        rows = self._materialize(rows)
        n = self._row_count(rows) if n is None else n
        for sink in self.sinks:
            sink.write(table, rows)
        if self.flusher is not None:
            self.stages.observe("flushing", (time.perf_counter() - t0) * 1e6)
        now = time.time()
        TRACER.record("flush", t0_wall, now, chunk=chunk, table=table,
                      rows=n)
        if export_ts is not None:
            # flow-export-timestamp -> sink-commit latency: how stale the
            # serving tables are relative to the traffic they describe.
            # A forced flush (shutdown) pops the still-OPEN window, whose
            # end lies in the future — clamp to now so the latency can't
            # go negative and the watermark never claims coverage beyond
            # wall clock (late rows for that window would be new partials)
            export_ts = min(export_ts, now)
            self.m_commit_lat.observe(now - export_ts, table=table)
            if export_ts > self._commit_watermark:
                self._commit_watermark = export_ts
                self.m_commit_wm.set(export_ts)
        self.m_rows.inc(n)
        log.info("flushed table=%s rows=%d", table, n)

    def finalize(self) -> None:
        """Drain everything (end of stream / shutdown)."""
        with self.lock:
            self.flush_closed(force=True)
            self.snapshot_and_commit()
            if self.serve is not None:
                # end-of-stream view: the final forced flush closed every
                # window; readers keep getting answers after the loop ends
                self.serve.publish(self)
        if hasattr(self.consumer, "lag"):
            self.m_lag.set(self.consumer.lag())
        if self.executor is not None:
            self.executor.stop()
        if self.flusher is not None:
            self.flusher.stop()
        if isinstance(self.consumer, PrefetchConsumer):
            self.consumer.stop()

    # ---- checkpoint / offsets --------------------------------------------

    def snapshot_and_commit(self) -> None:
        """Snapshot open state, then commit covered offsets. Order matters:
        state must be durable before the bus forgets the input."""
        if self.flusher is not None:
            # the snapshot no longer contains windows handed to the
            # flusher; their rows must be IN the sinks before the state
            # and offsets that forget them become durable — a flush
            # failure raises here and the step dies uncommitted (replay)
            self.flusher.drain()
        if self.config.checkpoint_path:
            save_checkpoint(self.config.checkpoint_path, self._state())
        self._emitted_since_snapshot = False
        for partition, next_off in sorted(self._covered.items()):
            self.consumer.commit(partition, next_off)
        if isinstance(self.consumer, PrefetchConsumer):
            # commits execute on the feed thread; wait so the protocol's
            # ordering (state durable -> offsets committed) stays true
            self.consumer.flush_commits()
        if hasattr(self.consumer, "lag"):
            self.m_lag.set(self.consumer.lag())

    def _state(self) -> dict:
        # host-backend sketch state lives in the engine between syncs;
        # the snapshot must cover everything the committed offsets cover
        self.sync_sketch_states()
        models_state: dict[str, Any] = {}
        for name, model in self.models.items():
            fam = _model_family(model)
            if fam is not None:
                # backing models declare their checkpoint tag explicitly
                # (duck-typing on attribute names mis-dispatches the day
                # a model grows an attribute another kind uses); the
                # family registry owns the per-kind save hook
                models_state[name] = registry.hook(
                    fam, "checkpoint_save")(model)
            elif isinstance(model, DDoSDetector):
                # detector, not a mergeable family (NON_FAMILY_KINDS)
                models_state[name] = {
                    "kind": "ddos",
                    "state": model.state,
                    "current_sub": model.current_sub,
                    "folds": model.folds,
                }
        return {
            "covered": {str(k): v for k, v in self._covered.items()},
            "models": models_state,
            "batches_seen": self.batches_seen,
            "flows_seen": self.flows_seen,
        }

    def restore(self, path: Optional[str] = None) -> bool:
        """Rehydrate from the checkpoint; returns False if none exists.

        Per-kind state rehydration is the family registry's
        checkpoint_restore hook, dispatched on the checkpoint's own kind
        tag; unknown tags are skipped silently (exactly the pre-registry
        fall-through), kind/model mismatches skip loudly inside the
        hooks."""
        import jax.numpy as jnp

        from .checkpoint import checkpoint_exists

        path = path or self.config.checkpoint_path
        if not path or not checkpoint_exists(path):
            return False
        snap = load_checkpoint(path)
        self._covered = {int(k): v for k, v in snap["covered"].items()}
        self.batches_seen = snap["batches_seen"]
        self.flows_seen = snap["flows_seen"]
        for name, ms in snap["models"].items():
            model = self.models.get(name)
            if model is None:
                # e.g. a checkpoint written with -model.ports on, restarted
                # with it off: skip rather than crash-loop on a KeyError;
                # that model's state simply starts over if re-enabled later
                log.warning("checkpoint has state for unconfigured model "
                            "%r; skipping", name)
                continue
            fam = registry.family_for_checkpoint(ms["kind"])
            if fam is not None:
                registry.hook(fam, "checkpoint_restore")(model, ms, name)
            elif ms["kind"] == "ddos":
                st = ms["state"]
                from ..models.ddos import DDoSState

                model.state = DDoSState(
                    **{k: jnp.asarray(v) for k, v in st.items()}
                )
                model.current_sub = ms["current_sub"]
                model.folds = ms["folds"]
        # resume reading from the covered offsets, not the poll position
        for p, off in self._covered.items():
            if hasattr(self.consumer, "positions"):
                self.consumer.positions[p] = off
        return True


# ---- per-family checkpoint hooks (families/registry.py) -------------------
#
# save_*(model) -> the model's checkpoint state dict (including its
# "kind" tag); restore_*(model, ms, name) rehydrates one model from a
# decoded checkpoint entry, skipping LOUDLY on any shape/kind mismatch
# (that window's state starts over — never restore the wrong layout).


def _model_family(model):
    """Registered family owning one live model object, else None (DDoS
    detectors and unknown backings checkpoint outside the registry)."""
    if isinstance(model, WindowAggregator):
        return registry.family("wagg")
    if isinstance(model, WindowedHeavyHitter):
        return registry.family_for_snapshot(model.model.snapshot_kind)
    return None


def _kind_matches(model, ms: dict, name: str) -> bool:
    """The checkpoint's kind tag must match the live model's backing.
    e.g. a checkpoint from a build whose port models were sketch-backed
    restored into a dense-backed one: restoring the wrong state shape
    would silently lose the open window (and corrupt future snapshots);
    skip loudly instead — that window's sketch starts over."""
    want = getattr(getattr(model, "model", None), "snapshot_kind", None)
    if want != ms["kind"]:
        log.warning(
            "checkpoint kind %r does not match model %r backing (%r); "
            "skipping its state", ms["kind"], name, want)
        return False
    return True


def save_wagg_state(model) -> dict:
    model._drain()  # fold pending device partials first: the snapshot
    # must cover everything the committed offsets cover
    return {
        "kind": "window_agg",
        "windows": model.windows,
        "watermark": model.watermark,
    }


def restore_wagg_state(model, ms: dict, name: str) -> None:
    windows = {
        int(slot): {k: v for k, v in store.items()}
        for slot, store in ms["windows"].items()
    }
    want = model.store_key_lanes
    bad = next((k for store in windows.values()
                for k in store if len(k) != want), None)
    if bad is not None:
        # a checkpoint from a different grouping layout (e.g.
        # pre-sampling builds without the rate lane): restoring
        # it would mis-split key tuples at flush and emit
        # garbage keys — skip loudly; open windows start over
        log.warning(
            "checkpoint window keys have %d lanes, model "
            "%r expects %d; skipping its window state",
            len(bad), name, want)
    else:
        model.windows = windows
    model.watermark = ms["watermark"]


def save_hh_state(model) -> dict:
    return {
        "kind": "windowed_hh",
        "hh": model.model.state,
        "current_slot": model.current_slot,
    }


def restore_hh_state(model, ms: dict, name: str) -> None:
    if not _kind_matches(model, ms, name):
        return
    hh = ms["hh"]  # NamedTuple decoded as field dict
    inv_cfg = getattr(model.model.config, "hh_sketch",
                      "table") == "invertible"
    if ("keysum" in hh) != inv_cfg:
        # a table-family checkpoint restored into an
        # invertible-config model (or vice versa): the
        # state layouts do not convert — skip loudly,
        # that window's sketch starts over (the same
        # discipline as the kind-mismatch skip above)
        log.warning(
            "checkpoint hh state for model %r is %s "
            "but the model runs hh_sketch=%s; skipping "
            "its state", name,
            "invertible" if "keysum" in hh else "table",
            model.model.config.hh_sketch)
        return
    if inv_cfg:
        import numpy as np

        from ..models.heavy_hitter import InvState

        # numpy, NOT jnp: without x64 a jnp.asarray
        # would silently downcast the exact u64 planes
        model.model.state = InvState(
            cms=np.asarray(hh["cms"], dtype=np.uint64),
            keysum=np.asarray(hh["keysum"], dtype=np.uint64),
            keycheck=np.asarray(hh["keycheck"], dtype=np.uint64),
        )
    else:
        import jax.numpy as jnp

        model.model.state = HHState(
            cms=jnp.asarray(hh["cms"]),
            table_keys=jnp.asarray(hh["table_keys"]),
            table_vals=jnp.asarray(hh["table_vals"]),
        )
    model.current_slot = ms["current_slot"]


def save_spread_state(model) -> dict:
    return {
        "kind": "windowed_spread",
        "spread": model.model.state,
        "current_slot": model.current_slot,
    }


def restore_spread_state(model, ms: dict, name: str) -> None:
    if not _kind_matches(model, ms, name):
        return
    import numpy as np

    from ..models.spread import SpreadState

    # numpy, NOT jnp: spread state is host-resident by
    # design (u8 registers + u32 table keys — the exact
    # max monoid IS the canonical form)
    sp = ms["spread"]  # NamedTuple decoded as field dict
    model.model.state = SpreadState(
        regs=np.asarray(sp["regs"], dtype=np.uint8),
        table_keys=np.asarray(sp["table_keys"], dtype=np.uint32),
        table_metric=np.asarray(sp["table_metric"], dtype=np.float32),
    )
    model.current_slot = ms["current_slot"]


def save_dense_state(model) -> dict:
    return {
        "kind": "windowed_dense",
        "totals": model.model.totals,
        "current_slot": model.current_slot,
    }


def restore_dense_state(model, ms: dict, name: str) -> None:
    if not _kind_matches(model, ms, name):
        return
    import jax.numpy as jnp

    model.model.totals = jnp.asarray(ms["totals"])
    model.current_slot = ms["current_slot"]
