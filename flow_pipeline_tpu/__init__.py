"""flow_pipeline_tpu — a TPU-native flow-analytics framework.

A brand-new framework with the capabilities of cloudflare/flow-pipeline
(flow generation/collection -> Kafka transport -> ingest -> windowed
aggregation -> dashboards), re-designed TPU-first: the aggregation tier is a
device-resident streaming-sketch engine (count-min, space-saving top-K,
EWMA/quantile anomaly detection) written in JAX/Pallas, sharded over a
`jax.sharding.Mesh` with ICI collectives merging per-chip sketch state.

Module map (mirrors the reference's layer map, SURVEY.md §1):

- ``schema``     wire format + columnar batches     (ref: pb-ext/)
- ``gen``        synthetic flow generation          (ref: mocker/)
- ``transport``  partitioned bus w/ offsets         (ref: Kafka topic `flows`)
- ``models``     aggregation models: exact oracle,
                 count-min HH, space-saving, DDoS   (ref: ClickHouse flows_5m)
- ``ops``        TPU kernels: hashing, sketch
                 updates, segment reductions        (ref: none — the TPU substitution)
- ``engine``     streaming engine, windows, flush   (ref: inserter/ + Kafka engine)
- ``parallel``   mesh, shard_map, sketch allreduce  (ref: 2-partition consumer group)
- ``sink``       Postgres/ClickHouse row writers    (ref: compose/{postgres,clickhouse})
- ``obs``        metrics, logging, /metrics         (ref: Prometheus + logrus)
- ``utils``      dotted-flag config, misc           (ref: Go `flag`)
"""

__version__ = "0.1.0"
