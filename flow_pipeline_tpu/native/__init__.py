"""Native (C++) host-path acceleration.

The host hot path — bulk protobuf decode into columnar batches — dominates at
≥1M flows/sec (the reference's analogue is ClickHouse's C++ Kafka/Protobuf
engine, ref: compose/clickhouse/create.sh:5-34). ``libflowdecode.so`` decodes a
length-prefixed FlowMessage stream straight into struct-of-arrays buffers;
this module loads it via ctypes and falls back to pure Python when unbuilt.

Build with ``make native`` once ``native/`` (flowdecode.cc + Makefile) lands;
until then ``available()`` is False and the pure-Python codec is used.
"""

from __future__ import annotations

import ctypes
import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_HERE = os.path.dirname(os.path.abspath(__file__))
_SEARCH = [
    os.path.join(_HERE, "libflowdecode.so"),
    os.path.join(_HERE, "..", "..", "native", "libflowdecode.so"),
]

# Loader override for instrumented builds (`make -C native san` / `tsan`
# produce libflowdecode_{san,tsan}.so): FLOWDECODE_LIB points the ctypes
# loader at an explicit .so. The override is STRICT — if the named
# library cannot be loaded we raise instead of quietly falling back to
# the regular build, because the only reason to set it is a sanitizer
# run (tools/flowlint/native_stress.py) and a silent fallback would fake
# a clean pass with uninstrumented code.
_LIB_ENV = "FLOWDECODE_LIB"


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    override = os.environ.get(_LIB_ENV)
    if override:
        # raise WITHOUT latching _TRIED: a failed strict override must
        # stay loud on every call — latching would let a caller that
        # swallowed the first error fall through to "no native library"
        # and silently run uninstrumented code with the override set
        if not os.path.exists(override):
            raise RuntimeError(
                f"{_LIB_ENV}={override} does not exist (build it with "
                "`make -C native san` / `tsan`)")
        try:
            lib = ctypes.CDLL(override)
        except OSError as e:
            raise RuntimeError(
                f"{_LIB_ENV}={override} failed to load: {e} (sanitizer "
                "builds need their runtime preloaded — see "
                "tools/flowlint/native_stress.py)") from e
        _LIB = _bind(lib)
        _TRIED = True
        return _LIB
    _TRIED = True
    for path in _SEARCH:
        if os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                continue
            _LIB = _bind(lib)
            break
    return _LIB


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Attach the C ABI signatures (shared by the default search path and
    the FLOWDECODE_LIB override)."""
    lib.flow_decode_stream.restype = ctypes.c_longlong
    lib.flow_decode_stream.argtypes = [
        ctypes.c_char_p,
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_void_p),  # column buffer pointers
        ctypes.c_longlong,  # capacity (rows)
    ]
    lib.flow_count_frames.restype = ctypes.c_longlong
    lib.flow_count_frames.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    lib.flow_encode_stream.restype = ctypes.c_longlong
    lib.flow_encode_stream.argtypes = [
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_longlong,
        ctypes.c_char_p,
        ctypes.c_longlong,
    ]
    if hasattr(lib, "flow_hash_group"):  # pre-r6 .so lacks it
        lib.flow_hash_group.restype = ctypes.c_longlong
        lib.flow_hash_group.argtypes = [
            ctypes.c_void_p,  # [n, w] uint32 lanes
            ctypes.c_longlong,
            ctypes.c_longlong,
            ctypes.c_void_p,  # [n] int32 perm out
            ctypes.c_void_p,  # [n] int32 starts out
            ctypes.POINTER(ctypes.c_int32),  # collided out
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
    if hasattr(lib, "flow_hash_group_mt"):  # pre-r19 .so lacks it
        lib.flow_hash_group_mt.restype = ctypes.c_longlong
        lib.flow_hash_group_mt.argtypes = [
            ctypes.c_void_p,  # [n, w] uint32 lanes
            ctypes.c_longlong,
            ctypes.c_longlong,
            ctypes.c_void_p,  # [n] int32 perm out
            ctypes.c_void_p,  # [n] int32 starts out
            ctypes.POINTER(ctypes.c_int32),  # collided out
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
    if hasattr(lib, "hs_cms_update"):  # pre-r8 .so lacks the sketch engine
        lib.hs_cms_update.restype = ctypes.c_longlong
        lib.hs_cms_update.argtypes = [
            ctypes.c_void_p,  # [P, D, W] uint64 sketch (in place)
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, kw] uint32 keys
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, P] float32 addends
            ctypes.c_void_p,  # [n] uint8 valid (NULL = all)
            ctypes.c_int,     # conservative
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
        lib.hs_cms_query.restype = ctypes.c_longlong
        lib.hs_cms_query.argtypes = [
            ctypes.c_void_p,  # [P, D, W] uint64 sketch
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, kw] uint32 keys
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, P] float32 out
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
        lib.hs_hh_prefilter.restype = ctypes.c_longlong
        lib.hs_hh_prefilter.argtypes = [
            ctypes.c_void_p,  # [cap, kw] uint32 table keys
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, kw] uint32 candidate keys
            ctypes.c_void_p,  # [n, P] float32 sums (plane 0 ranks)
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [2*cap] int32 selection out
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
        lib.hs_topk_merge.restype = ctypes.c_longlong
        lib.hs_topk_merge.argtypes = [
            ctypes.c_void_p,  # [cap, kw] uint32 table keys (in place)
            ctypes.c_void_p,  # [cap, P] float32 table vals (in place)
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, kw] uint32 candidate keys
            ctypes.c_void_p,  # [n, P] float32 batch sums
            ctypes.c_void_p,  # [n, P] float32 CMS estimates
            ctypes.c_void_p,  # [n] uint8 valid (NULL = all)
            ctypes.c_longlong,
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
    if hasattr(lib, "hs_inv_update"):  # pre-r16 .so lacks the invertible
        lib.hs_inv_update.restype = ctypes.c_longlong
        lib.hs_inv_update.argtypes = [
            ctypes.c_void_p,  # [P, D, W] uint64 count/value planes (in place)
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [D, W, kw] uint64 keysum planes (in place)
            ctypes.c_void_p,  # [D, W] uint64 checksum plane (in place)
            ctypes.c_void_p,  # [n, kw] uint32 keys
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, P] float32 addends (count plane last)
            ctypes.c_void_p,  # [n] uint8 valid (NULL = all)
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
        lib.hs_inv_decode.restype = ctypes.c_longlong
        lib.hs_inv_decode.argtypes = [
            ctypes.c_void_p,  # [P, D, W] uint64 count/value planes
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [D, W, kw] uint64 keysum planes
            ctypes.c_void_p,  # [D, W] uint64 checksum plane
            ctypes.c_longlong,
            ctypes.c_void_p,  # [D*W, kw] uint32 decoded keys out
            ctypes.c_void_p,  # [D*W, P] uint64 decoded sums out
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
    if hasattr(lib, "hs_spread_update"):  # pre-r21 .so lacks flowspread
        lib.hs_spread_update.restype = ctypes.c_longlong
        lib.hs_spread_update.argtypes = [
            ctypes.c_void_p,  # [D, W, m] uint8 register planes (in place)
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, kw] uint32 key lanes
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, ew] uint32 element lanes
            ctypes.c_longlong,
            ctypes.c_void_p,  # [n] uint8 valid (NULL = all)
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
    if hasattr(lib, "ff_group_sum"):  # pre-r10 .so lacks the fused plane
        lib.ff_group_sum.restype = ctypes.c_longlong
        lib.ff_group_sum.argtypes = [
            ctypes.c_void_p,  # [n, w] uint32 lanes
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, p] uint64 value planes
            ctypes.c_longlong,
            ctypes.c_void_p,  # [n, w] uint32 uniq out
            ctypes.c_void_p,  # [n, p] uint64 sums out
            ctypes.c_void_p,  # [n] int64 counts out
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
    if hasattr(lib, "ff_group_sum_mt"):  # pre-r19 .so lacks it
        lib.ff_group_sum_mt.restype = ctypes.c_longlong
        lib.ff_group_sum_mt.argtypes = [
            ctypes.c_void_p,  # [n, w] uint32 lanes
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, p] uint64 value planes
            ctypes.c_longlong,
            ctypes.c_void_p,  # [n, w] uint32 uniq out
            ctypes.c_void_p,  # [n, p] uint64 sums out
            ctypes.c_void_p,  # [n] int64 counts out
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
    if hasattr(lib, "ff_build_lanes"):  # pre-r19 .so lacks lane building
        lib.ff_build_lanes.restype = ctypes.c_longlong
        lib.ff_build_lanes.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),  # [ncols] column buffers
            ctypes.c_void_p,  # [ncols] uint8 is64
            ctypes.c_void_p,  # [ncols] int64 widths (1 or 4)
            ctypes.c_void_p,  # [ncols] uint32 slot mods (NULL = none)
            ctypes.c_longlong, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, wtotal] uint32 lanes out
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
        lib.ff_build_planes.restype = ctypes.c_longlong
        lib.ff_build_planes.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),  # [p] scalar column buffers
            ctypes.c_void_p,  # [p] uint8 is64
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # scale column (NULL = none; f32 mode only)
            ctypes.c_int,     # scale_is64
            ctypes.c_void_p,  # [n, p] float32 out (XOR with out_u64)
            ctypes.c_void_p,  # [n, p] uint64 out (the wagg layout)
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
        ]
    if hasattr(lib, "ff_fused_update"):
        lib.ff_fused_update.restype = ctypes.c_longlong
        lib.ff_fused_update.argtypes = [
            ctypes.c_void_p,  # [n, w] uint32 root lanes
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, p] float32 value planes
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [nf] int64 parent
            ctypes.c_void_p,  # [sel_off[nf]] int64 child lane selections
            ctypes.c_void_p,  # [nf+1] int64 sel offsets
            ctypes.c_void_p,  # [nf] int64 depth
            ctypes.c_void_p,  # [nf] int64 width
            ctypes.c_void_p,  # [nf] int64 capacity
            ctypes.c_void_p,  # [nf] uint8 conservative
            ctypes.c_void_p,  # [nf] uint8 prefilter
            ctypes.c_void_p,  # [nf] uint8 admission==plain
            ctypes.POINTER(ctypes.c_void_p),  # [nf] cms buffers
            ctypes.POINTER(ctypes.c_void_p),  # [nf] table key buffers
            ctypes.POINTER(ctypes.c_void_p),  # [nf] table val buffers
            ctypes.c_int,     # do_sketch
            ctypes.c_longlong,  # ddos parent family (-1 = none)
            ctypes.c_void_p,  # [ddos_sel_w] int64 ddos lane selection
            ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_void_p,  # [n, ddos_sel_w] uint32 ddos keys out
            ctypes.c_void_p,  # [n] float32 ddos sums out
            ctypes.c_int,     # threads
            ctypes.c_void_p,  # [FF_STATS_LEN] int64 stats (NULL = off)
            # r16 invertible trailer (safe past a pre-r16 .so: extra
            # cdecl args are ignored, and invertible trees are gated on
            # the hs_inv_update export which only r16+ builds carry)
            ctypes.c_void_p,  # [nf] uint8 invertible flags (NULL = none)
            ctypes.POINTER(ctypes.c_void_p),  # [nf] keysum buffers
            ctypes.POINTER(ctypes.c_void_p),  # [nf] keycheck buffers
        ]
    return lib


def available() -> bool:
    return _load() is not None


# ---- flowtrace phase counters ----------------------------------------------
#
# Every groupby/sketch kernel takes an optional trailing int64 stats
# buffer it ACCUMULATES per-phase wall nanoseconds and row/group counts
# into — the in-kernel attribution that the single-pass fused dataplane
# erased from the Python-side stage timers. Slot layout mirrors the
# FF_STAT_* enum in native/ffstat.h (the C side is authoritative;
# tests/test_flowtrace.py pins the two in sync via behavior).
FF_STATS_LEN = 16
FF_STAT_SLOTS = {
    "radix": 0,      # LSD radix passes incl. the row-hash pass (ns)
    "refine": 1,     # run refinement + group boundary scan (ns)
    "regroup": 2,    # cascade regroup: gather + group + fold (ns)
    "cms": 3,        # hs_cms_update (ns)
    "prefilter": 4,  # hs_hh_prefilter (ns)
    "topk": 5,       # hs_cms_query (admission est) + hs_topk_merge (ns)
    "fold": 6,       # root group-table accumulation (ns)
    "inv": 10,       # hs_inv_update / hs_inv_decode (the invertible
                     # family's whole sketch fold — no admission phases)
    "lanes": 11,     # ff_build_lanes / ff_build_planes: native lane
                     # building off the decoded columns (r19 flowspeed)
    "spread": 12,    # hs_spread_update (the flowspread distinct-count
                     # family's register fold — r21)
}
FF_STAT_PHASES = tuple(FF_STAT_SLOTS)  # ns-valued phase slots, in order
FF_STAT_ROWS = 7
FF_STAT_GROUPS = 8
FF_STAT_RADIX_PASSES = 9


def new_stats() -> np.ndarray:
    """A zeroed stats buffer kernels accumulate into (reusable across
    calls — callers zero or diff it themselves)."""
    return np.zeros(FF_STATS_LEN, np.int64)


def _stats_ptr(stats):
    """Validated ctypes arg for an optional stats buffer."""
    if stats is None:
        return None
    assert stats.dtype == np.int64 and stats.flags["C_CONTIGUOUS"] \
        and stats.shape == (FF_STATS_LEN,)
    return _c_arr(stats)


# Feature -> witness symbol: the capability surface operators and the
# degradation report key off. Each entry marks an .so generation (r1
# decode, r6 group, r8 sketch, r10 fused) — a stale build silently
# lacking the newer symbols is exactly what missing_features() exists
# to make loud (gauge + startup warning, engine/hostfused.py).
_FEATURE_SYMBOLS = {
    "decode": "flow_decode_stream",
    "group": "flow_hash_group",
    "sketch": "hs_cms_update",
    "fused": "ff_fused_update",
    "invsketch": "hs_inv_update",
    # r19 flowspeed: native lane building off the decoded columns +
    # the threaded groupby (one .so generation — witness either)
    "lanes": "ff_build_lanes",
    # r21 flowspread: the distinct-count register fold
    "spread": "hs_spread_update",
}


def capabilities() -> dict:
    """Per-feature availability of the loaded library ({} keys always
    present; all False when no library loads at all)."""
    lib = _load()
    return {feat: bool(lib is not None and hasattr(lib, sym))
            for feat, sym in _FEATURE_SYMBOLS.items()}


def missing_features() -> list[str]:
    """Features the loaded (or absent) library cannot serve — what a
    startup banner should name before any fallback quietly engages."""
    return [feat for feat, ok in capabilities().items() if not ok]


def reload() -> bool:
    """Re-attempt loading (e.g. after a caller built the library); returns
    availability. Used by bench.py's fresh-box auto-build."""
    global _TRIED
    _TRIED = False
    return available()


# Column order shared with native/flowdecode.cc — scalar uint32 columns in
# schema order, then the three [N,4] address columns.
def _column_order():
    from ..schema.batch import COLUMNS, ADDR_COLUMNS

    return list(COLUMNS), list(ADDR_COLUMNS)


def decode_stream(data: bytes, capacity_hint: int = 0):
    """Decode length-prefixed FlowMessage frames into a FlowBatch using the
    native library. Raises RuntimeError if the library is not built."""
    from ..schema.batch import FlowBatch

    lib = _load()
    if lib is None:
        raise RuntimeError("libflowdecode.so not built; run `make native`")
    # Exact row count via a cheap native scan of the length prefixes (a frame
    # can be as small as 1 byte — an all-default message).
    cap = capacity_hint or max(1, int(lib.flow_count_frames(data, len(data))))
    batch = FlowBatch.empty(cap)
    scalar_names, addr_names = _column_order()
    ptrs = (ctypes.c_void_p * (len(scalar_names) + len(addr_names)))()
    for i, name in enumerate(scalar_names + addr_names):
        arr = batch.columns[name]
        assert arr.flags["C_CONTIGUOUS"]
        ptrs[i] = arr.ctypes.data_as(ctypes.c_void_p).value
    n = lib.flow_decode_stream(data, len(data), ptrs, cap)
    if n < 0:
        raise ValueError(f"native decode failed at frame {-n - 1}")
    return batch.slice(0, int(n))


def group_available() -> bool:
    """Whether the loaded library exports the hash-group kernel (an .so
    built before r6 decodes fine but cannot group)."""
    lib = _load()
    return lib is not None and hasattr(lib, "flow_hash_group")


def hash_group(lanes: np.ndarray, stats: Optional[np.ndarray] = None,
               threads: int = 1):
    """Native hash-grouping of [N, W] uint32 key lanes.

    Computes the same 64-bit row hash as ops.hostgroup.hash_u64, radix-
    sorts it, and verifies lane equality within each hash group in one
    C pass. Returns (perm [N] int32, starts [G] int32, collided bool) —
    identical contract (and identical group order) to the numpy path, so
    callers can switch per batch. ``threads`` > 1 routes through the
    r19 flow_hash_group_mt kernel (per-key-range partitioning,
    per-partition stable sort) whose output is BIT-IDENTICAL to the
    serial kernel at any thread count; a pre-r19 library quietly serves
    the serial path. Raises RuntimeError when the library is missing or
    too old (callers gate on group_available())."""
    lib = _load()
    if lib is None or not hasattr(lib, "flow_hash_group"):
        raise RuntimeError("libflowdecode.so missing flow_hash_group; "
                           "run `make native`")
    lanes = np.ascontiguousarray(lanes, dtype=np.uint32)
    n, w = lanes.shape
    perm = np.empty(n, np.int32)
    starts = np.empty(max(n, 1), np.int32)
    collided = ctypes.c_int32(0)
    if threads > 1 and hasattr(lib, "flow_hash_group_mt"):
        g = lib.flow_hash_group_mt(
            lanes.ctypes.data_as(ctypes.c_void_p), n, w,
            perm.ctypes.data_as(ctypes.c_void_p),
            starts.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(collided), int(threads),
            _stats_ptr(stats),
        )
    else:
        g = lib.flow_hash_group(
            lanes.ctypes.data_as(ctypes.c_void_p), n, w,
            perm.ctypes.data_as(ctypes.c_void_p),
            starts.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(collided),
            _stats_ptr(stats),
        )
    if g < 0:
        raise ValueError("flow_hash_group failed (batch too large?)")
    return perm, starts[:g], bool(collided.value)


def sketch_available() -> bool:
    """Whether the loaded library exports the hostsketch engine (an .so
    built before r8 decodes and groups fine but cannot sketch)."""
    lib = _load()
    return lib is not None and hasattr(lib, "hs_cms_update")


def _c_arr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


def hs_cms_update(cms: np.ndarray, keys: np.ndarray, vals: np.ndarray,
                  valid, conservative: bool, threads: int = 1,
                  stats: Optional[np.ndarray] = None) -> None:
    """Native uint64 CMS update (plain or conservative) in place.

    cms [P, D, W] uint64 C-contiguous; keys [n, kw] uint32; vals [n, P]
    float32; valid [n] bool or None. Deterministic for any thread count
    (see native/hostsketch.cc). Raises on degenerate shapes."""
    lib = _load()
    if lib is None or not hasattr(lib, "hs_cms_update"):
        raise RuntimeError("libflowdecode.so missing hostsketch engine; "
                           "run `make native`")
    assert cms.dtype == np.uint64 and cms.flags["C_CONTIGUOUS"]
    p, d, w = cms.shape
    keys = np.ascontiguousarray(keys, dtype=np.uint32)
    vals = np.ascontiguousarray(vals, dtype=np.float32)
    n, kw = keys.shape
    vptr = None
    if valid is not None:
        valid = np.ascontiguousarray(valid, dtype=np.uint8)
        vptr = _c_arr(valid)
    rc = lib.hs_cms_update(_c_arr(cms), p, d, w, _c_arr(keys), n, kw,
                           _c_arr(vals), vptr, int(bool(conservative)),
                           int(threads), _stats_ptr(stats))
    if rc != 0:
        raise ValueError(f"hs_cms_update failed (rc={rc}): degenerate "
                         f"shape planes={p} depth={d} width={w}")


def hs_cms_query(cms: np.ndarray, keys: np.ndarray, threads: int = 1,
                 stats: Optional[np.ndarray] = None) -> np.ndarray:
    """Native CMS point query: [n, P] float32 min-over-depth estimates."""
    lib = _load()
    if lib is None or not hasattr(lib, "hs_cms_query"):
        raise RuntimeError("libflowdecode.so missing hostsketch engine; "
                           "run `make native`")
    assert cms.dtype == np.uint64 and cms.flags["C_CONTIGUOUS"]
    p, d, w = cms.shape
    keys = np.ascontiguousarray(keys, dtype=np.uint32)
    n, kw = keys.shape
    out = np.empty((n, p), np.float32)
    rc = lib.hs_cms_query(_c_arr(cms), p, d, w, _c_arr(keys), n, kw,
                          _c_arr(out), int(threads), _stats_ptr(stats))
    if rc != 0:
        raise ValueError(f"hs_cms_query failed (rc={rc})")
    return out


def hs_hh_prefilter(table_keys: np.ndarray, cand_keys: np.ndarray,
                    cand_sums: np.ndarray, threads: int = 1,
                    stats: Optional[np.ndarray] = None) -> np.ndarray:
    """Native table-aware candidate prefilter: selected row indices in
    (metric desc, index asc) order — lax.top_k's tie-break. Returns
    [min(n, 2*cap)] int32."""
    lib = _load()
    if lib is None or not hasattr(lib, "hs_hh_prefilter"):
        raise RuntimeError("libflowdecode.so missing hostsketch engine; "
                           "run `make native`")
    table_keys = np.ascontiguousarray(table_keys, dtype=np.uint32)
    cand_keys = np.ascontiguousarray(cand_keys, dtype=np.uint32)
    cand_sums = np.ascontiguousarray(cand_sums, dtype=np.float32)
    cap, kw = table_keys.shape
    n, planes = cand_sums.shape
    sel = np.empty(2 * cap, np.int32)
    m = lib.hs_hh_prefilter(_c_arr(table_keys), cap, kw, _c_arr(cand_keys),
                            _c_arr(cand_sums), n, planes, _c_arr(sel),
                            int(threads), _stats_ptr(stats))
    if m < 0:
        raise ValueError(f"hs_hh_prefilter failed (rc={m})")
    return sel[:m]


def hs_topk_merge(table_keys: np.ndarray, table_vals: np.ndarray,
                  cand_keys: np.ndarray, cand_sums: np.ndarray,
                  cand_est: np.ndarray, valid,
                  stats: Optional[np.ndarray] = None) -> int:
    """Native space-saving admission merge, in place on the table buffers
    (ops.topk.topk_merge_est semantics — pass cand_est=cand_sums for the
    'plain' batch-sum merge). Returns the number of real rows."""
    lib = _load()
    if lib is None or not hasattr(lib, "hs_topk_merge"):
        raise RuntimeError("libflowdecode.so missing hostsketch engine; "
                           "run `make native`")
    assert table_keys.dtype == np.uint32 and \
        table_keys.flags["C_CONTIGUOUS"]
    assert table_vals.dtype == np.float32 and \
        table_vals.flags["C_CONTIGUOUS"]
    cap, kw = table_keys.shape
    planes = table_vals.shape[1]
    cand_keys = np.ascontiguousarray(cand_keys, dtype=np.uint32)
    cand_sums = np.ascontiguousarray(cand_sums, dtype=np.float32)
    cand_est = np.ascontiguousarray(cand_est, dtype=np.float32)
    n = cand_keys.shape[0]
    vptr = None
    if valid is not None:
        valid = np.ascontiguousarray(valid, dtype=np.uint8)
        vptr = _c_arr(valid)
    rc = lib.hs_topk_merge(_c_arr(table_keys), _c_arr(table_vals),
                           cap, kw, planes, _c_arr(cand_keys),
                           _c_arr(cand_sums), _c_arr(cand_est), vptr, n,
                           _stats_ptr(stats))
    if rc < 0:
        raise ValueError(f"hs_topk_merge failed (rc={rc}): degenerate "
                         f"shape cap={cap} kw={kw} planes={planes}")
    return int(rc)


def inv_available() -> bool:
    """Whether the loaded library exports the invertible sketch kernels
    (an .so built before r16 serves the table family fine but cannot
    run -hh.sketch=invertible natively)."""
    lib = _load()
    return lib is not None and hasattr(lib, "hs_inv_update")


def hs_inv_update(cms: np.ndarray, keysum: np.ndarray,
                  keycheck: np.ndarray, keys: np.ndarray,
                  vals: np.ndarray, valid, threads: int = 1,
                  stats: Optional[np.ndarray] = None) -> None:
    """Native invertible-sketch update in place — one pure per-bucket
    fold (u64 count/value planes + key-recovery planes), no admission
    machinery. cms [P, D, W] u64; keysum [D, W, kw] u64; keycheck
    [D, W] u64; keys [n, kw] u32; vals [n, P] f32 (count plane LAST).
    Deterministic for any thread count (plain wrap adds are order-free;
    see native/hostsketch.cc). Raises on degenerate shapes."""
    lib = _load()
    if lib is None or not hasattr(lib, "hs_inv_update"):
        raise RuntimeError("libflowdecode.so missing the invertible "
                           "sketch kernels; run `make native`")
    assert cms.dtype == np.uint64 and cms.flags["C_CONTIGUOUS"]
    assert keysum.dtype == np.uint64 and keysum.flags["C_CONTIGUOUS"]
    assert keycheck.dtype == np.uint64 and keycheck.flags["C_CONTIGUOUS"]
    p, d, w = cms.shape
    keys = np.ascontiguousarray(keys, dtype=np.uint32)
    vals = np.ascontiguousarray(vals, dtype=np.float32)
    n, kw = keys.shape
    assert keysum.shape == (d, w, kw) and keycheck.shape == (d, w)
    vptr = None
    if valid is not None:
        valid = np.ascontiguousarray(valid, dtype=np.uint8)
        vptr = _c_arr(valid)
    rc = lib.hs_inv_update(_c_arr(cms), p, d, w, _c_arr(keysum),
                           _c_arr(keycheck), _c_arr(keys), n, kw,
                           _c_arr(vals), vptr, int(threads),
                           _stats_ptr(stats))
    if rc != 0:
        raise ValueError(f"hs_inv_update failed (rc={rc}): degenerate "
                         f"shape planes={p} depth={d} width={w} kw={kw}")


def hs_inv_decode(cms: np.ndarray, keysum: np.ndarray,
                  keycheck: np.ndarray,
                  stats: Optional[np.ndarray] = None):
    """Native heavy-key recovery from an invertible sketch (IBLT-style
    peel over pure buckets; inputs read-only). Returns (keys [K, kw]
    u32, vals [K, P] u64) in the kernel's peel order — callers
    canonicalize (hostsketch.engine lex-sorts before ranking)."""
    lib = _load()
    if lib is None or not hasattr(lib, "hs_inv_decode"):
        raise RuntimeError("libflowdecode.so missing the invertible "
                           "sketch kernels; run `make native`")
    assert cms.dtype == np.uint64 and cms.flags["C_CONTIGUOUS"]
    assert keysum.dtype == np.uint64 and keysum.flags["C_CONTIGUOUS"]
    assert keycheck.dtype == np.uint64 and keycheck.flags["C_CONTIGUOUS"]
    p, d, w = cms.shape
    kw = keysum.shape[2]
    assert keysum.shape == (d, w, kw) and keycheck.shape == (d, w)
    keys_out = np.empty((d * w, kw), np.uint32)
    vals_out = np.empty((d * w, p), np.uint64)
    n = lib.hs_inv_decode(_c_arr(cms), p, d, w, _c_arr(keysum),
                          _c_arr(keycheck), kw, _c_arr(keys_out),
                          _c_arr(vals_out), _stats_ptr(stats))
    if n < 0:
        raise ValueError(f"hs_inv_decode failed (rc={n})")
    n = int(n)
    return keys_out[:n], vals_out[:n]


def spread_available() -> bool:
    """Whether the loaded library exports the flowspread register fold
    (an .so built before r21 serves every other family fine but cannot
    run -spread.* natively — the numpy twin serves, bit-identically)."""
    lib = _load()
    return lib is not None and hasattr(lib, "hs_spread_update")


def hs_spread_update(regs: np.ndarray, keys: np.ndarray,
                     elems: np.ndarray, threads: int = 1,
                     stats: Optional[np.ndarray] = None,
                     valid=None) -> None:
    """Native distinct-count register update in place — the threaded
    twin of hostsketch.engine.np_spread_update (u8 scatter-max over
    per-depth-owned register blocks; deterministic at any thread count
    since max is order-free — see native/hostsketch.cc). regs [D, W, m]
    u8 C-contiguous; keys [n, kw] u32; elems [n, ew] u32. Raises on
    degenerate shapes."""
    lib = _load()
    if lib is None or not hasattr(lib, "hs_spread_update"):
        raise RuntimeError("libflowdecode.so missing the flowspread "
                           "kernel; run `make native`")
    assert regs.dtype == np.uint8 and regs.flags["C_CONTIGUOUS"]
    d, w, m = regs.shape
    keys = np.ascontiguousarray(keys, dtype=np.uint32)
    elems = np.ascontiguousarray(elems, dtype=np.uint32)
    n, kw = keys.shape
    ew = elems.shape[1]
    assert elems.shape[0] == n
    vptr = None
    if valid is not None:
        valid = np.ascontiguousarray(valid, dtype=np.uint8)
        vptr = _c_arr(valid)
    rc = lib.hs_spread_update(_c_arr(regs), d, w, m, _c_arr(keys), n, kw,
                              _c_arr(elems), ew, vptr, int(threads),
                              _stats_ptr(stats))
    if rc != 0:
        raise ValueError(f"hs_spread_update failed (rc={rc}): degenerate "
                         f"shape depth={d} width={w} m={m} kw={kw} ew={ew}")


def fused_available() -> bool:
    """Whether the loaded library exports the fused dataplane (an .so
    built before r10 decodes, groups and sketches fine but cannot run
    the single-pass group->cascade->sketch update)."""
    lib = _load()
    return lib is not None and hasattr(lib, "ff_fused_update")


def group_sum(lanes: np.ndarray, vals: np.ndarray,
              stats: Optional[np.ndarray] = None, threads: int = 1):
    """Single-pass exact groupby-sum (ff_group_sum): the native twin of
    ops.hostgroup.group_by_key(exact=True) over integer planes.

    lanes [n, w] uint32; vals [n, p] uint64. Returns (uniq [G, w] u32,
    sums [G, p] u64, counts [G] i64), or None on a 64-bit hash collision
    between distinct key rows — the caller re-groups lexicographically,
    the same contract the numpy path honors. ``threads`` > 1 rides the
    r19 ff_group_sum_mt kernel (threaded grouping + per-group-range u64
    fold — exact integer sums, bit-identical at any thread count); a
    pre-r19 library quietly serves the serial kernel."""
    lib = _load()
    if lib is None or not hasattr(lib, "ff_group_sum"):
        raise RuntimeError("libflowdecode.so missing the fused dataplane; "
                           "run `make native`")
    lanes = np.ascontiguousarray(lanes, dtype=np.uint32)
    vals = np.ascontiguousarray(vals, dtype=np.uint64)
    n, w = lanes.shape
    p = vals.shape[1]
    if vals.shape[0] != n:
        # C iterates vals by lane row count — a shorter vals would read
        # out of bounds, and no rc can report it after the fact
        raise ValueError(f"lanes rows ({n}) != vals rows "
                         f"({vals.shape[0]})")
    uniq = np.empty((n, w), np.uint32)
    sums = np.empty((n, p), np.uint64)
    counts = np.empty(max(n, 1), np.int64)
    if threads > 1 and hasattr(lib, "ff_group_sum_mt"):
        g = lib.ff_group_sum_mt(_c_arr(lanes), n, w, _c_arr(vals), p,
                                _c_arr(uniq), _c_arr(sums),
                                _c_arr(counts), int(threads),
                                _stats_ptr(stats))
    else:
        g = lib.ff_group_sum(_c_arr(lanes), n, w, _c_arr(vals), p,
                             _c_arr(uniq), _c_arr(sums), _c_arr(counts),
                             _stats_ptr(stats))
    if g == -2:
        return None  # 64-bit collision: caller takes the exact fallback
    if g < 0:
        raise ValueError(f"ff_group_sum failed (rc={g})")
    g = int(g)
    return uniq[:g], sums[:g], counts[:g]


# ---- native lane building off the decoded columns (r19 flowspeed) ----------


def lanes_available() -> bool:
    """Whether the loaded library exports the lane-building kernels (an
    .so built before r19 runs the fused dataplane fine but builds its
    lanes in numpy — engine/hostfused.py's bit-exact twins)."""
    lib = _load()
    return lib is not None and hasattr(lib, "ff_build_lanes")


def _lane_cols(columns):
    """(ptr array, is64, widths, contiguous keepalives) for a list of
    decoded columns — [n] u32 / [n] u64 scalars or [n, 4] u32 words."""
    keep = []
    ptrs = (ctypes.c_void_p * len(columns))()
    is64 = np.zeros(len(columns), np.uint8)
    widths = np.empty(len(columns), np.int64)
    for i, col in enumerate(columns):
        a = np.ascontiguousarray(col)
        if a.ndim == 2:
            if a.shape[1] != 4 or a.dtype != np.uint32:
                raise ValueError(
                    f"column {i}: 2-D lanes must be [n, 4] uint32, got "
                    f"{a.shape} {a.dtype}")
            widths[i] = 4
        elif a.dtype == np.uint64:
            is64[i] = 1
            widths[i] = 1
        else:
            a = np.ascontiguousarray(a, dtype=np.uint32)
            widths[i] = 1
        keep.append(a)
        ptrs[i] = a.ctypes.data_as(ctypes.c_void_p).value
    # must hold even under python -O: the C kernels read cols[c][r] for
    # every r < n taken from column 0 — a shorter column would be read
    # past its end (heap overread), not caught
    for i, a in enumerate(keep[1:], start=1):
        if a.shape[0] != keep[0].shape[0]:
            raise ValueError(
                f"column {i}: {a.shape[0]} rows, column 0 has "
                f"{keep[0].shape[0]} — all columns must share n")
    return ptrs, is64, widths, keep


def build_lanes(columns, mods=None, threads: int = 1,
                stats: Optional[np.ndarray] = None) -> np.ndarray:
    """[n, W] uint32 key lanes built natively off decoded columns — the
    C twin of engine/hostfused.py _key_lanes_into (u64 saturation, [n,4]
    address words copied through, optional per-column slot transform
    ``v - v % mods[i]`` for the wagg slot lane). Raises RuntimeError on
    a pre-r19 library (callers gate on lanes_available())."""
    lib = _load()
    if lib is None or not hasattr(lib, "ff_build_lanes"):
        raise RuntimeError("libflowdecode.so missing the lane-building "
                           "kernels; run `make native`")
    ptrs, is64, widths, keep = _lane_cols(columns)
    n = keep[0].shape[0]
    wtotal = int(widths.sum())
    out = np.empty((n, wtotal), np.uint32)
    mods_arr = None
    if mods is not None:
        mods_arr = np.ascontiguousarray(mods, dtype=np.uint32)
        if mods_arr.shape != (len(columns),):
            # must hold even under python -O: a short mods array would
            # send ff_build_lanes reading past its end
            raise ValueError(
                f"mods must have one entry per column "
                f"({len(columns)}), got shape {mods_arr.shape}")
    rc = lib.ff_build_lanes(
        ptrs, _c_arr(is64), _c_arr(widths),
        _c_arr(mods_arr) if mods_arr is not None else None,
        len(keep), n, wtotal, _c_arr(out), int(threads),
        _stats_ptr(stats))
    del keep
    if rc != 0:
        raise ValueError(f"ff_build_lanes failed (rc={rc})")
    return out


def build_planes_f32(columns, scale=None, threads: int = 1,
                     stats: Optional[np.ndarray] = None) -> np.ndarray:
    """[n, P] float32 value planes built natively — the C twin of
    _value_planes_np (u32 saturation, u32->f32 cast, one f32 multiply
    by max(scale, 1) per cell)."""
    lib = _load()
    if lib is None or not hasattr(lib, "ff_build_planes"):
        raise RuntimeError("libflowdecode.so missing the lane-building "
                           "kernels; run `make native`")
    ptrs, is64, widths, keep = _lane_cols(columns)
    if (widths != 1).any():
        raise ValueError("value planes take scalar columns only")
    n = keep[0].shape[0]
    out = np.empty((n, len(keep)), np.float32)
    sptr = None
    s64 = 0
    if scale is not None:
        s = np.ascontiguousarray(scale)
        if s.dtype == np.uint64:
            s64 = 1
        else:
            s = np.ascontiguousarray(s, dtype=np.uint32)
        if s.shape[0] != n:
            # same overread class as the mods/column checks above
            raise ValueError(
                f"scale has {s.shape[0]} rows, columns have {n}")
        keep.append(s)
        sptr = _c_arr(s)
    rc = lib.ff_build_planes(ptrs, _c_arr(is64), len(is64), n, sptr,
                             s64, _c_arr(out), None, int(threads),
                             _stats_ptr(stats))
    del keep
    if rc != 0:
        raise ValueError(f"ff_build_planes failed (rc={rc})")
    return out


def build_planes_u64(columns, threads: int = 1,
                     stats: Optional[np.ndarray] = None) -> np.ndarray:
    """[n, P] uint64 value planes saturated at U32_MAX — the C twin of
    _wagg_rows' ``np.minimum(col, U32_MAX)`` plane stack (the exact
    flows_5m substrate)."""
    lib = _load()
    if lib is None or not hasattr(lib, "ff_build_planes"):
        raise RuntimeError("libflowdecode.so missing the lane-building "
                           "kernels; run `make native`")
    ptrs, is64, widths, keep = _lane_cols(columns)
    if (widths != 1).any():
        raise ValueError("value planes take scalar columns only")
    n = keep[0].shape[0]
    out = np.empty((n, len(keep)), np.uint64)
    rc = lib.ff_build_planes(ptrs, _c_arr(is64), len(is64), n, None, 0,
                             None, _c_arr(out), int(threads),
                             _stats_ptr(stats))
    del keep
    if rc != 0:
        raise ValueError(f"ff_build_planes failed (rc={rc})")
    return out


@dataclass(frozen=True)
class FusedPlan:
    """Static per-tree parameter block for fused_update — built once per
    pipeline from engine/hostfused.py's _fam_plan (hostsketch/pipeline),
    reused every chunk. Family 0 is the tree's root ("own") family;
    parents precede children."""

    parent: np.ndarray            # [nf] int64; -1 = root
    sel: np.ndarray               # [sel_off[nf]] int64 child lane picks
    sel_off: np.ndarray           # [nf+1] int64
    depth: np.ndarray             # [nf] int64
    width: np.ndarray             # [nf] int64
    cap: np.ndarray               # [nf] int64
    conservative: np.ndarray      # [nf] uint8
    prefilter: np.ndarray         # [nf] uint8
    admission_plain: np.ndarray   # [nf] uint8
    ddos_parent: int = -1         # family index, -1 = no ddos side table
    ddos_sel: Optional[np.ndarray] = None  # [ddos_sel_w] int64
    ddos_plane: int = -1
    # [nf] uint8 — families running -hh.sketch=invertible (their states
    # are HostInvState; the admission path is never entered for them).
    # None = all-table, the pre-r16 plan shape.
    invertible: Optional[np.ndarray] = None


def fused_update(lanes: np.ndarray, vals: np.ndarray, plan: FusedPlan,
                 states, do_sketch: bool, do_ddos: bool = True,
                 threads: int = 1,
                 stats: Optional[np.ndarray] = None):
    """One fused group->cascade->sketch pass over a chunk's root-family
    lanes (ff_fused_update): every family's CMS/prefilter/top-K state in
    ``states`` (HostHHState per family, plan order) is updated IN PLACE;
    the only surfaced output is the DDoS per-dst side table.

    lanes [n, w] uint32; vals [n, p] float32 (pre-scaled value planes —
    the count plane is appended natively). ``do_sketch=False`` runs the
    grouping only (late parts that still need the ddos table); states
    may then be None. ``do_ddos=False`` skips the plan's per-dst cascade
    (native regroup + output buffers) when the caller would discard the
    table — a late ddos sub-window. Returns (ddos_uniq [G, dw] u32,
    ddos_sums [G] f32) or None when no ddos table was produced."""
    lib = _load()
    if lib is None or not hasattr(lib, "ff_fused_update"):
        raise RuntimeError("libflowdecode.so missing the fused dataplane; "
                           "run `make native`")
    lanes = np.ascontiguousarray(lanes, dtype=np.uint32)
    vals = np.ascontiguousarray(vals, dtype=np.float32)
    n, w = lanes.shape
    p = vals.shape[1]
    if vals.shape[0] != n:
        # the fused pass folds vals rows into in-place sketch state by
        # lane row index — reject the mismatch before any state is
        # touched (same contract as the oob lane-selection check)
        raise ValueError(f"lanes rows ({n}) != vals rows "
                         f"({vals.shape[0]})")
    parent = np.ascontiguousarray(plan.parent, dtype=np.int64)
    sel = np.ascontiguousarray(plan.sel, dtype=np.int64)
    sel_off = np.ascontiguousarray(plan.sel_off, dtype=np.int64)
    depth = np.ascontiguousarray(plan.depth, dtype=np.int64)
    width = np.ascontiguousarray(plan.width, dtype=np.int64)
    cap = np.ascontiguousarray(plan.cap, dtype=np.int64)
    conserv = np.ascontiguousarray(plan.conservative, dtype=np.uint8)
    prefilter = np.ascontiguousarray(plan.prefilter, dtype=np.uint8)
    plain = np.ascontiguousarray(plan.admission_plain, dtype=np.uint8)
    nf = parent.shape[0]
    cms_ptrs = (ctypes.c_void_p * nf)()
    tkey_ptrs = (ctypes.c_void_p * nf)()
    tval_ptrs = (ctypes.c_void_p * nf)()
    inv_ks_ptrs = (ctypes.c_void_p * nf)()
    inv_kc_ptrs = (ctypes.c_void_p * nf)()
    inv_flags = None
    if plan.invertible is not None:
        inv_flags = np.ascontiguousarray(plan.invertible, dtype=np.uint8)
        if inv_flags.any() and not inv_available():
            # the loaded .so predates hs_inv_update — its ff_fused_update
            # also predates the invertible trailer and would silently
            # run the table path on inv state buffers
            raise RuntimeError("libflowdecode.so missing the invertible "
                              "sketch kernels; run `make native`")
    if do_sketch:
        for i, st in enumerate(states):
            assert st.cms.dtype == np.uint64 and st.cms.flags["C_CONTIGUOUS"]
            cms_ptrs[i] = st.cms.ctypes.data_as(ctypes.c_void_p).value
            if inv_flags is not None and inv_flags[i]:
                assert st.keysum.dtype == np.uint64 and \
                    st.keysum.flags["C_CONTIGUOUS"]
                assert st.keycheck.dtype == np.uint64 and \
                    st.keycheck.flags["C_CONTIGUOUS"]
                inv_ks_ptrs[i] = st.keysum.ctypes.data_as(
                    ctypes.c_void_p).value
                inv_kc_ptrs[i] = st.keycheck.ctypes.data_as(
                    ctypes.c_void_p).value
                continue
            assert st.table_keys.dtype == np.uint32 and \
                st.table_keys.flags["C_CONTIGUOUS"]
            assert st.table_vals.dtype == np.float32 and \
                st.table_vals.flags["C_CONTIGUOUS"]
            tkey_ptrs[i] = st.table_keys.ctypes.data_as(
                ctypes.c_void_p).value
            tval_ptrs[i] = st.table_vals.ctypes.data_as(
                ctypes.c_void_p).value
    ddos_keys = ddos_sums = None
    ddos_sel_ptr = None
    ddos_parent = -1
    ddos_sel_w = 0
    if do_ddos and plan.ddos_parent >= 0:
        ddos_parent = plan.ddos_parent
        ddos_sel = np.ascontiguousarray(plan.ddos_sel, dtype=np.int64)
        ddos_sel_w = ddos_sel.shape[0]
        ddos_sel_ptr = _c_arr(ddos_sel)
        ddos_keys = np.empty((max(n, 1), ddos_sel_w), np.uint32)
        ddos_sums = np.empty(max(n, 1), np.float32)
    g = lib.ff_fused_update(
        _c_arr(lanes), n, w, _c_arr(vals), p, nf,
        _c_arr(parent), _c_arr(sel), _c_arr(sel_off),
        _c_arr(depth), _c_arr(width), _c_arr(cap),
        _c_arr(conserv), _c_arr(prefilter), _c_arr(plain),
        cms_ptrs, tkey_ptrs, tval_ptrs, int(bool(do_sketch)),
        ddos_parent, ddos_sel_ptr, ddos_sel_w,
        plan.ddos_plane if ddos_parent >= 0 else -1,
        _c_arr(ddos_keys) if ddos_keys is not None else None,
        _c_arr(ddos_sums) if ddos_sums is not None else None,
        int(threads), _stats_ptr(stats),
        _c_arr(inv_flags) if inv_flags is not None else None,
        inv_ks_ptrs, inv_kc_ptrs)
    if g < 0:
        raise ValueError(f"ff_fused_update failed (rc={g}): degenerate "
                         f"shape n={n} w={w} p={p} nf={nf}")
    if ddos_parent < 0:
        return None
    g = int(g)
    return ddos_keys[:g], ddos_sums[:g]


def encode_stream(batch, out_capacity: int = 0) -> bytes:
    """Encode a FlowBatch to length-prefixed frames using the native library.

    Byte-identical to the pure-Python encoder except for all-zero addresses:
    the columnar form cannot distinguish an absent address from ``::``, and
    the native encoder omits such fields (proto3 decoders treat both the
    same; the stream is smaller)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("libflowdecode.so not built; run `make native`")
    scalar_names, addr_names = _column_order()
    n = len(batch)
    # Worst case ~ 27 fields * (2 tag + 5 varint) + addresses + prefix.
    cap = out_capacity or (n * 256 + 16)
    out = ctypes.create_string_buffer(cap)
    ptrs = (ctypes.c_void_p * (len(scalar_names) + len(addr_names)))()
    keepalive = []  # hold contiguous copies for the duration of the call
    for i, name in enumerate(scalar_names + addr_names):
        arr = np.ascontiguousarray(batch.columns[name])
        keepalive.append(arr)
        ptrs[i] = arr.ctypes.data_as(ctypes.c_void_p).value
    written = lib.flow_encode_stream(ptrs, n, out, cap)
    del keepalive
    if written < 0:
        raise ValueError("native encode: output buffer too small")
    return out.raw[: int(written)]
