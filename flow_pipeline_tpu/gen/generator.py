"""Vectorized synthetic flow generators."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..schema.batch import FlowBatch
from ..schema.message import FlowType


@dataclass
class MockerProfile:
    """Reference-parity random flows (ref: mocker/mocker.go:57-91)."""

    max_bytes: int = 1500
    max_packets: int = 100
    as_base: int = 65000
    as_count: int = 3
    etype: int = 0x86DD
    sampling_rate: int = 1
    # 2001:db8:0:1::/112 with a random final byte, both sides
    prefix: bytes = bytes(
        [0x20, 0x01, 0x0D, 0xB8, 0x00, 0x00, 0x00, 0x01, 0, 0, 0, 0, 0, 0, 0]
    )


@dataclass
class ZipfProfile:
    """Heavy-tailed traffic over a fixed key universe.

    ``n_keys`` distinct flow keys (addr pair, port pair, proto, AS pair) are
    drawn once from the seed; flows sample keys with P(rank r) ~ 1/r^alpha.
    Byte/packet sizes stay uniform like the mocker so ranking differences come
    from key frequency, which is what the sketches estimate.
    """

    n_keys: int = 10_000
    alpha: float = 1.2
    # flowspread legs: a slice of every batch is emitted by dedicated
    # spreader sources whose FAN-OUT (distinct dst addrs / dst ports per
    # source) is itself harmonically skewed — rank r touches ~fanout/(r+1)
    # distinct targets. Even ranks are superspreaders (many dst addrs, one
    # port), odd ranks are port scanners (one victim, many dst ports).
    # The default 0.0 draws nothing and keeps pre-r21 streams
    # byte-identical for any seed.
    spread_fraction: float = 0.0
    spread_sources: int = 32
    spread_fanout: int = 4096
    max_bytes: int = 1500
    max_packets: int = 100
    as_base: int = 65000
    as_count: int = 16
    etype: int = 0x86DD
    sampling_rate: int = 1
    prefix: bytes = bytes(
        [0x20, 0x01, 0x0D, 0xB8, 0x00, 0x00, 0x00, 0x01, 0, 0, 0, 0, 0, 0, 0]
    )


class FlowGenerator:
    """Seeded flow source producing columnar batches.

    Time model: flows arrive at ``rate`` flows/sec starting at ``t0``;
    time_received advances deterministically so window-boundary behavior is
    reproducible. (The reference emits ~4 msg/s wall-clock,
    ref: mocker/mocker.go:17-18,56 — here rate is a parameter because the
    framework's job is millions of flows/sec.)
    """

    def __init__(
        self,
        profile: MockerProfile | ZipfProfile | None = None,
        seed: int = 0,
        t0: int = 1_700_000_000,
        rate: float = 100_000.0,
    ):
        self.profile = profile if profile is not None else MockerProfile()
        self.rng = np.random.default_rng(seed)
        self.t0 = t0
        self.rate = rate
        self._emitted = 0  # flows so far; drives SequenceNum + timestamps
        if isinstance(self.profile, ZipfProfile):
            self._key_table = self._build_key_table(self.profile)
            self._key_probs = self._zipf_probs(self.profile)

    # ---- zipf key universe -------------------------------------------------

    def _build_key_table(self, p: ZipfProfile) -> dict[str, np.ndarray]:
        rng = np.random.default_rng(self.rng.integers(2**63))
        n = p.n_keys
        prefix_words = (
            np.frombuffer(p.prefix + b"\x00", dtype=">u4").astype(np.uint32).copy()
        )

        def addrs():
            a = np.tile(prefix_words, (n, 1))
            # random last two bytes -> up to 65536 distinct hosts per side
            a[:, 3] = (a[:, 3] & np.uint32(0xFFFF0000)) | rng.integers(
                0, 2**16, n, dtype=np.uint32
            )
            return a

        return {
            "src_addr": addrs(),
            "dst_addr": addrs(),
            "src_port": rng.integers(1024, 2**16, n, dtype=np.uint64),
            "dst_port": rng.choice(
                np.array([53, 80, 123, 443, 8080], dtype=np.uint64), n
            ),
            "proto": rng.choice(np.array([6, 17], dtype=np.uint64), n),
            "src_as": p.as_base + rng.integers(0, p.as_count, n, dtype=np.uint64),
            "dst_as": p.as_base + rng.integers(0, p.as_count, n, dtype=np.uint64),
        }

    @staticmethod
    def _zipf_probs(p: ZipfProfile) -> np.ndarray:
        ranks = np.arange(1, p.n_keys + 1, dtype=np.float64)
        w = ranks**-p.alpha
        return w / w.sum()

    # ---- batch generation --------------------------------------------------

    def batch(self, n: int) -> FlowBatch:
        """Generate the next n flows as a FlowBatch."""
        p = self.profile
        rng = self.rng
        out = FlowBatch.empty(n)
        cols = out.columns

        idx0 = self._emitted
        ts = (self.t0 + (idx0 + np.arange(n)) / self.rate).astype(np.uint64)
        cols["type"][:] = FlowType.SFLOW_5
        cols["time_received"][:] = ts
        cols["time_flow_start"][:] = ts
        cols["time_flow_end"][:] = ts
        cols["sampling_rate"][:] = p.sampling_rate
        cols["sequence_num"][:] = (idx0 + np.arange(n)) & 0xFFFFFFFF
        cols["etype"][:] = p.etype
        cols["bytes"][:] = rng.integers(0, p.max_bytes, n, dtype=np.uint64)
        cols["packets"][:] = rng.integers(0, p.max_packets, n, dtype=np.uint64)

        if isinstance(p, ZipfProfile):
            ranks = rng.choice(p.n_keys, size=n, p=self._key_probs)
            t = self._key_table
            cols["src_addr"][:] = t["src_addr"][ranks]
            cols["dst_addr"][:] = t["dst_addr"][ranks]
            for name in ("src_port", "dst_port", "proto", "src_as", "dst_as"):
                cols[name][:] = t[name][ranks].astype(cols[name].dtype)
            k = int(round(n * p.spread_fraction))
            if k:
                self._spread_legs(cols, n - k, k)
        else:
            prefix_words = (
                np.frombuffer(p.prefix + b"\x00", dtype=">u4").astype(np.uint32).copy()
            )
            for side in ("src_addr", "dst_addr"):
                a = np.tile(prefix_words, (n, 1))
                a[:, 3] = (a[:, 3] & np.uint32(0xFFFFFF00)) | rng.integers(
                    0, 256, n, dtype=np.uint32
                )
                cols[side][:] = a
            cols["src_as"][:] = p.as_base + rng.integers(0, p.as_count, n, dtype=np.uint64)
            cols["dst_as"][:] = p.as_base + rng.integers(0, p.as_count, n, dtype=np.uint64)
            cols["src_port"][:] = rng.integers(0, 2**16, n, dtype=np.uint64)
            cols["dst_port"][:] = rng.integers(0, 2**16, n, dtype=np.uint64)
            cols["proto"][:] = 0

        self._emitted += n
        return out

    def _spread_legs(self, cols: dict, off: int, k: int) -> None:
        """Overwrite the last ``k`` rows with spreader-leg flows (zipf
        profile only; see ZipfProfile.spread_fraction). Sources sit at
        fixed suffixes (0xF000 | rank); the random zipf table can collide
        into that range, which only adds background noise the detectors
        must tolerate anyway."""
        p = self.profile
        rng = self.rng
        nsrc = p.spread_sources
        ranks = rng.choice(nsrc, size=k, p=self._spread_probs(nsrc))
        # harmonic fan-out: rank r touches ~fanout/(r+1) distinct targets
        fanout = np.maximum(p.spread_fanout // (ranks + 1), 8)
        elem = rng.integers(0, fanout, k).astype(np.uint32)
        prefix_words = (
            np.frombuffer(p.prefix + b"\x00", dtype=">u4").astype(np.uint32).copy()
        )
        sl = slice(off, off + k)
        src = np.tile(prefix_words, (k, 1))
        src[:, 3] = (src[:, 3] & np.uint32(0xFFFF0000)) | np.uint32(0xF000) | ranks
        cols["src_addr"][sl] = src
        scanner = (ranks & 1) == 1
        dst = np.tile(prefix_words, (k, 1))
        # superspreaders fan across dst addrs on one port; scanners hold
        # one victim addr and fan across dst ports
        dst[:, 3] = (dst[:, 3] & np.uint32(0xFFFF0000)) | np.where(
            scanner, np.uint32(0xE000) | ranks, elem)
        cols["dst_addr"][sl] = dst
        cols["dst_port"][sl] = np.where(scanner, elem % 65536, 443)
        cols["src_port"][sl] = rng.integers(1024, 2**16, k, dtype=np.uint64)
        cols["proto"][sl] = 6
        cols["src_as"][sl] = p.as_base
        cols["dst_as"][sl] = p.as_base

    @staticmethod
    def _spread_probs(nsrc: int) -> np.ndarray:
        w = 1.0 / np.arange(1, nsrc + 1, dtype=np.float64)
        return w / w.sum()

    def batches(self, n_batches: int, batch_size: int):
        for _ in range(n_batches):
            yield self.batch(batch_size)
