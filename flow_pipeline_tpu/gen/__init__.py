"""Synthetic flow generation (the reference's "mocker" role, ref: mocker/mocker.go).

Two modes:

- ``MockerProfile``: behavior parity with the reference generator — uniform
  Bytes<1500 / Packets<100, SrcAS/DstAS in 65000..65002, 2001:db8:0:1::/112
  addresses with a random last byte, random ports, EType 0x86dd (IPv6),
  SamplingRate 1, TimeFlowStart == TimeReceived, monotonically increasing
  SequenceNum (ref: mocker/mocker.go:57-91).
- ``ZipfProfile``: seeded heavy-tailed key distribution over a configurable
  key universe, so top-K heavy-hitter error is measurable (SURVEY.md §4:
  "a seeded skewed distribution (Zipf over the 9-key tuple) so top-K error
  is measurable").

Generation is vectorized straight into columnar FlowBatch form — no
per-message Python loop on the hot path.
"""

from .generator import FlowGenerator, MockerProfile, ZipfProfile

__all__ = ["FlowGenerator", "MockerProfile", "ZipfProfile"]
