"""Durable-filesystem helpers: ONE idiom for every durable surface.

Before flowtorn the repo had three hand-rolled dialects of the same
write→flush→fsync→rename→dir-fsync sequence (mesh/journal.py,
sink/resilient.py, history/archive.py) and one durable surface with no
fsyncs at all (engine/checkpoint.py). This module is the single seam
they all go through now, which buys two things:

1. **Static checkability**: ``tools/flowlint/rules_durability.py``
   models the durable-write protocol over these helper names (and over
   the raw ``os.fsync``/``os.replace`` calls in THIS file, which is the
   one place raw calls are the implementation rather than a smell).
2. **Crash-point model checking**: every helper reports its operation
   to an injectable observer (:func:`observed`), so a real run's op log
   can be replayed prefix-by-prefix by ``utils/crashsim.py`` — the
   ALICE-style checker behind ``make crash-parity``.

The protocol, spelled out once (docs/STATIC_ANALYSIS.md states the
rule; docs/FAULT_TOLERANCE.md states what each surface promises):

- file contents become durable at ``fsync_file`` (or the fsync inside
  ``write_bytes_durable``) — never at ``flush()``;
- a fresh or renamed NAME becomes durable at ``fsync_dir`` on its
  containing directory — fsyncing contents alone does not persist the
  directory entry, power loss can drop a fully-synced file;
- an atomic publish is ``write tmp → fsync tmp → replace → fsync_dir``;
  :func:`write_bytes_durable` is that whole sentence as one call.

``suppressed(...)`` exists for the mutation smoke only: it deletes one
barrier kind (``fsync`` / ``fsync_dir`` / ``replace``) from the
recorded protocol the way a bad refactor would, so the crash-point
checker can prove each barrier is load-bearing.
"""

from __future__ import annotations

# flowlint: durable-checked

import contextlib
import os
import threading
from typing import Optional

__all__ = [
    "OpRecorder", "observed", "suppressed", "open_durable",
    "fsync_file", "fsync_dir", "write_bytes_durable", "replace",
    "rename", "remove", "rmtree",
]


# ---- the injectable observer (crash-point model checking) ---------------

class OpRecorder:
    """Append-only log of durable-filesystem operations, recorded by
    the helpers below while installed via :func:`observed`. Ops are
    plain tuples whose first element is the kind::

        ("open", path, mode)         mode in {"w", "a", "x"}
        ("write", path, offset, b"") one buffered write
        ("fsync", path)              contents durable up to here
        ("fsync_dir", dir)           names in dir durable up to here
        ("replace", src, dst)        atomic publish
        ("rename", src, dst)         atomic move (files or dirs)
        ("remove", path)             unlink
        ("rmtree", path)             recursive unlink (one entry)
        ("mark", label)              test-harness ack marker

    ``mark()`` is called by crash-point scenarios (never production
    code) to pin WHERE in the op order an ack went out — the invariant
    checks are phrased over "everything acked by this crash point".
    """

    def __init__(self) -> None:
        self.ops: list[tuple] = []
        self._lock = threading.Lock()

    def record(self, op: tuple) -> None:
        with self._lock:
            self.ops.append(op)

    def mark(self, label: str) -> None:
        self.record(("mark", label))


_observer: Optional[OpRecorder] = None
_suppress: frozenset = frozenset()

_SUPPRESSIBLE = frozenset({"fsync", "fsync_dir", "replace"})


@contextlib.contextmanager
def observed(recorder: OpRecorder):
    """Install ``recorder`` as the op observer for the duration of the
    block. Not reentrant; crash-point scenarios are single-run."""
    global _observer
    prev = _observer
    _observer = recorder
    try:
        yield recorder
    finally:
        _observer = prev


@contextlib.contextmanager
def suppressed(*kinds: str):
    """MUTATION TESTING ONLY: drop the named barrier kinds from the
    protocol (the op is neither performed nor recorded — exactly as if
    the call site had been deleted). ``replace`` degrades to a
    non-atomic in-place rewrite instead of vanishing: the file must
    still be published for the run to proceed, the mutation is losing
    its atomicity."""
    global _suppress
    unknown = set(kinds) - _SUPPRESSIBLE
    if unknown:
        raise ValueError(f"unknown suppressible barrier(s): "
                         f"{sorted(unknown)} (know {sorted(_SUPPRESSIBLE)})")
    prev = _suppress
    _suppress = prev | set(kinds)
    try:
        yield
    finally:
        _suppress = prev


def _rec(op: tuple) -> None:
    obs = _observer
    if obs is not None:
        obs.record(op)


# ---- the durable-write helpers ------------------------------------------

class DurableFile:
    """Thin binary-file proxy that reports writes to the observer.
    Supports the surface the durable writers use: ``write``, ``flush``,
    ``fileno``, ``tell``, ``close``, context manager."""

    def __init__(self, path: str, raw):
        self.path = path
        self._raw = raw

    def write(self, data) -> int:
        off = self._raw.tell()
        n = self._raw.write(data)
        _rec(("write", self.path, off, bytes(data)))
        return n

    def flush(self) -> None:
        self._raw.flush()

    def fileno(self) -> int:
        return self._raw.fileno()

    def tell(self) -> int:
        return self._raw.tell()

    def close(self) -> None:
        self._raw.close()

    @property
    def closed(self) -> bool:
        return self._raw.closed

    def __enter__(self) -> "DurableFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open_durable(path: str, mode: str = "wb") -> DurableFile:
    """Open a durable-state file for writing (binary modes only: text
    mode has opaque ``tell`` cookies, and durable surfaces frame bytes).
    The open and every subsequent write are reported to the observer."""
    if "b" not in mode or not any(c in mode for c in "wax"):
        raise ValueError(
            f"open_durable wants a binary write mode, got {mode!r}")
    existed = os.path.exists(path)
    raw = open(path, mode)  # flowlint: disable=durability-protocol -- the helper seam itself: this IS open_durable
    kind = "a" if "a" in mode and existed else \
        ("a" if "a" in mode else ("x" if "x" in mode else "w"))
    _rec(("open", path, kind))
    return DurableFile(path, raw)


def fsync_file(f) -> None:
    """Flush + fsync one open file: the CONTENT durability barrier.
    Accepts a :class:`DurableFile` or any raw file object."""
    f.flush()
    if "fsync" in _suppress:
        return
    os.fsync(f.fileno())
    _rec(("fsync", getattr(f, "path", getattr(f, "name", "?"))))


def fsync_dir(path: str) -> None:
    """Make a directory entry durable: fsyncing file CONTENTS alone
    does not persist a freshly created or renamed name — power loss
    can drop the file after its data was synced, silently voiding a
    durability contract. Best-effort on platforms whose directories
    cannot be opened for sync."""
    if "fsync_dir" in _suppress:
        return
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform-dependent
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    _rec(("fsync_dir", path))


def replace(src: str, dst: str) -> None:
    """Atomic publish: ``os.replace`` plus the op record. Callers still
    owe a :func:`fsync_dir` on the containing directory afterwards (the
    static rule enforces it)."""
    if "replace" in _suppress:
        # mutation mode: publish non-atomically (truncate + rewrite in
        # place), which is what losing the atomic step amounts to. The
        # real filesystem still sees a replace so the run proceeds; the
        # RECORDED protocol is the mutated one the checker judges.
        try:
            with open(src, "rb") as f:
                data = f.read()
        except OSError:
            data = b""
        _rec(("open", dst, "w"))
        _rec(("write", dst, 0, data))
        _rec(("remove", src))
        os.replace(src, dst)
        return
    os.replace(src, dst)
    _rec(("replace", src, dst))


def rename(src: str, dst: str) -> None:
    """Atomic move of a file OR directory tree (``os.rename``); same
    dir-fsync obligation as :func:`replace`."""
    os.rename(src, dst)
    _rec(("rename", src, dst))


def remove(path: str) -> None:
    """Unlink a durable name (recorded); the removal is durable only
    after :func:`fsync_dir` on the containing directory."""
    os.remove(path)
    _rec(("remove", path))


def rmtree(path: str) -> None:
    """Recursive unlink, recorded as ONE op (only ever used on
    superseded staging/backup trees, e.g. a checkpoint's ``.old``)."""
    import shutil
    shutil.rmtree(path, ignore_errors=True)
    _rec(("rmtree", path))


def write_bytes_durable(path: str, data: bytes) -> None:
    """The whole atomic-publish sentence as one call: write a sibling
    temp file, fsync it, atomically replace ``path``, fsync the
    containing directory. After this returns, ``path`` holds exactly
    ``data`` across any crash — or the previous contents of ``path``
    if the crash beat the replace."""
    tmp = path + ".tmp"
    with open_durable(tmp, "wb") as f:
        f.write(data)
        fsync_file(f)
    replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)) or ".")
