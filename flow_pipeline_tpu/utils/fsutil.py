"""Durable-filesystem helpers shared by the flowchaos write paths
(the coordinator journal and the sink dead-letter spill)."""

from __future__ import annotations

import os


def fsync_dir(path: str) -> None:
    """Make a directory entry durable: fsyncing file CONTENTS alone
    does not persist a freshly created or renamed name — power loss
    can drop the file after its data was synced, silently voiding a
    durability contract. Best-effort on platforms whose directories
    cannot be opened for sync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform-dependent
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
