"""Platform selection helpers.

This environment's sitecustomize registers the TPU backend at interpreter
start and overrides ``jax_platforms`` through jax.config, so the
``JAX_PLATFORMS`` env var alone cannot force CPU — and an accidental TPU
claim can block forever when a dead session holds the single chip's grant.
Every entry point that must honor or decide the platform goes through
here.
"""

from __future__ import annotations

import os
import subprocess
import sys


def force_cpu() -> None:
    """Pin this process to the CPU backend (env var for child processes,
    config update because sitecustomize overrides the env var)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def cpu_requested() -> bool:
    """True iff JAX_PLATFORMS names cpu as the only platform ("tpu,cpu"
    priority lists are NOT a CPU request)."""
    return [
        p.strip() for p in os.environ.get("JAX_PLATFORMS", "").split(",")
        if p.strip()
    ] == ["cpu"]


def honor_platform_env() -> None:
    """Enforce an explicit CPU-only request through jax.config."""
    if cpu_requested():
        force_cpu()


def resolve_platform(probe_timeout: float = 90.0) -> str:
    """resolve_platform_info without the degrade reason."""
    return resolve_platform_info(probe_timeout)[0]


def resolve_platform_info(probe_timeout: float = 90.0):
    """Decide the platform for a benchmark/driver run.

    CPU-only request -> ('cpu', None) (enforced). Otherwise probe backend
    init in a subprocess: the child reports the platform it actually got
    (so a CPU-only machine is never mislabeled), and a timeout/failure —
    the wedged-chip case — degrades to CPU instead of deadlocking.

    Returns (platform, degrade_reason): reason is None unless the probe
    DEGRADED to CPU, in which case it carries the probe's actual failure
    (child stderr for init errors, relay diagnosis for grant timeouts) so
    benchmark artifacts can say why, not just "platform: cpu".
    """
    if cpu_requested():
        force_cpu()
        return "cpu", None
    reason = None
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=probe_timeout, check=True, capture_output=True, text=True,
        )
        lines = out.stdout.strip().splitlines()
        platform = lines[-1] if lines else "unknown"
    except subprocess.TimeoutExpired:
        platform = "cpu"
        reason = (f"backend init timed out after {probe_timeout:.0f}s; "
                  + _relay_diagnosis())
    except subprocess.CalledProcessError as e:
        platform = "cpu"
        tail = (e.stderr or "").strip().splitlines()
        reason = "backend init failed: " + (tail[-1] if tail else "unknown")
    if platform == "cpu":
        force_cpu()
    return platform, reason


def _relay_diagnosis() -> str:
    """Poke the axon relay the TPU tunnel rides (AXON_POOL_SVC_OVERRIDE in
    this environment's sitecustomize). Only called AFTER a grant timeout —
    the claim channel is already suspect, and a healthy relay holds an
    accepted connection open while a dead one accepts and instantly
    closes."""
    import socket

    pool_ips = os.environ.get("PALLAS_AXON_POOL_IPS", "")
    if not pool_ips:
        return "no TPU tunnel configured in this environment"
    # the override is where the relay actually listens; without it, probe
    # the pool address itself rather than assuming loopback
    host = (os.environ.get("AXON_POOL_SVC_OVERRIDE")
            or pool_ips.split(",")[0].strip())
    try:
        s = socket.create_connection((host, 2024), timeout=3)
    except OSError as e:
        return f"TPU relay {host}:2024 unreachable ({e})"
    try:
        s.settimeout(2)
        try:
            data = s.recv(16)
        except socket.timeout:
            return "relay reachable; chip grant timed out (held elsewhere?)"
        except OSError as e:  # e.g. RST mid-probe — still just a diagnosis
            return f"relay connection dropped during probe ({e})"
        if data == b"":
            return ("TPU relay accepts and immediately closes connections "
                    "(upstream pool link down); chip grant never arrives")
        return "relay responded; grant timed out during backend init"
    finally:
        s.close()
