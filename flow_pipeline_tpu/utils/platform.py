"""Platform selection helpers.

This environment's sitecustomize registers the TPU backend at interpreter
start and overrides ``jax_platforms`` through jax.config, so the
``JAX_PLATFORMS`` env var alone cannot force CPU — and an accidental TPU
claim can block forever when a dead session holds the single chip's grant.
Every entry point that must honor or decide the platform goes through
here.
"""

from __future__ import annotations

import os
import subprocess
import sys


def force_cpu() -> None:
    """Pin this process to the CPU backend (env var for child processes,
    config update because sitecustomize overrides the env var)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def cpu_requested() -> bool:
    """True iff JAX_PLATFORMS names cpu as the only platform ("tpu,cpu"
    priority lists are NOT a CPU request)."""
    return [
        p.strip() for p in os.environ.get("JAX_PLATFORMS", "").split(",")
        if p.strip()
    ] == ["cpu"]


def honor_platform_env() -> None:
    """Enforce an explicit CPU-only request through jax.config."""
    if cpu_requested():
        force_cpu()


def resolve_platform(probe_timeout: float = 90.0) -> str:
    """Decide the platform for a benchmark/driver run.

    CPU-only request -> 'cpu' (enforced). Otherwise probe backend init in a
    subprocess: the child reports the platform it actually got (so a
    CPU-only machine is never mislabeled), and a timeout/failure — the
    wedged-chip case — degrades to CPU instead of deadlocking.
    """
    if cpu_requested():
        force_cpu()
        return "cpu"
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=probe_timeout, check=True, capture_output=True, text=True,
        )
        lines = out.stdout.strip().splitlines()
        platform = lines[-1] if lines else "unknown"
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        platform = "cpu"
    if platform == "cpu":
        force_cpu()
    return platform
