"""flowtorn crash-point model checker (the dynamic prong).

``utils/fsutil.py`` records the durable-filesystem op log of a REAL
run; this module replays every legal crash point of that log into a
fresh directory and runs the REAL recovery code over each one, so the
FAULT_TOLERANCE.md invariants are checked against every window a crash
could actually hit — not just the hand-picked points the chaos suite
samples. The model is ALICE-shaped (Pillai et al., OSDI'14: "All File
Systems Are Not Created Equal"), specialized to the repo's protocol:

**Persistence model.** A ``write`` becomes durable at the next
``fsync`` on that file; a name operation (create / rename / replace /
remove) becomes durable at the next ``fsync_dir`` on its directory.
``replace``/``rename`` are atomic: a crash exposes the old binding or
the new one, never a blend — but the INODE the new name points at
still has only its synced content, which is exactly how a missing
fsync-before-rename turns into an empty or torn published file.

**Crash states per crash point** (after each op prefix):

- everything applied (the disk happened to flush it all);
- only the durable effects (strictest legal state);
- the cross terms: names applied with only synced content (torn
  publish), synced names with applied content (dropped dir entry);
- torn tail: the last unsynced write cut at 0 / 1 / half / len-1
  bytes (a power loss mid-write);
- drop-one: each unsynced write independently lost while later
  unsynced writes landed (the disk reorders writes that no fsync
  barrier separates; holes read back as zeros).

States are deduplicated by content hash before recovery runs, so the
wall cost stays proportional to the DISTINCT on-disk states, not the
raw op count. ``tests/test_crashpoints.py`` binds this to the four
durable surfaces and ``make crash-parity`` gates it in CI.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from dataclasses import dataclass, field
from typing import Callable, Optional

from .fsutil import OpRecorder

# cap the torn-tail cut points and drop-one variants per crash point so
# pathological op logs cannot make the sweep quadratic-times-huge; the
# caps are far above what any repo scenario produces
MAX_DROP_VARIANTS = 8


class _Inode:
    """One file's content state during the walk: ``synced`` survived an
    fsync; ``pending`` writes are at the disk's mercy."""

    __slots__ = ("synced", "pending")

    def __init__(self) -> None:
        self.synced = b""
        self.pending: list[tuple[int, bytes, int]] = []  # (off, data, op idx)

    def content(self, *, include_pending: bool = True,
                drop_idx: Optional[int] = None,
                cut: Optional[tuple[int, int]] = None) -> bytes:
        """Materialize content under a policy: optionally apply pending
        writes, optionally drop the pending write with op index
        ``drop_idx`` (later writes still land; the hole is zeros),
        optionally cut the pending write with op index ``cut[0]`` to
        ``cut[1]`` bytes (torn tail)."""
        buf = bytearray(self.synced)
        if not include_pending:
            return bytes(buf)
        for off, data, idx in self.pending:
            if idx == drop_idx:
                continue
            if cut is not None and idx == cut[0]:
                data = data[:cut[1]]
            end = off + len(data)
            if end > len(buf):
                buf.extend(b"\0" * (end - len(buf)))
            buf[off:off + len(data)] = data
        return bytes(buf)


@dataclass
class _NameOp:
    """One atomic namespace transition: a list of (verb, path[, inode])
    edits applied all-or-nothing. ``durable_at`` is the op index of the
    fsync_dir that persisted it (None = still pending)."""

    idx: int
    edits: list[tuple]
    dirs: set[str]
    durable_at: Optional[int] = None


@dataclass
class Violation:
    crash_op: int
    state_kind: str
    acked: list[str]
    error: str

    def render(self) -> str:
        return (f"crash after op {self.crash_op} [{self.state_kind}] "
                f"acked={self.acked!r}: {self.error}")


@dataclass
class CrashReport:
    ops: int = 0
    crash_points: int = 0
    states_explored: int = 0
    states_deduped: int = 0
    violations: list[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self) -> str:
        head = (f"crashsim: {self.ops} ops, {self.crash_points} crash "
                f"points, {self.states_explored} distinct states "
                f"({self.states_deduped} deduped)")
        if self.ok:
            return head + " — all invariants held"
        lines = [head + f" — {len(self.violations)} VIOLATION(S):"]
        lines += ["  " + v.render() for v in self.violations[:20]]
        return "\n".join(lines)


class _Walk:
    """Replay a recorded op prefix into the persistence model."""

    def __init__(self) -> None:
        self.files: dict[str, _Inode] = {}   # runtime path -> inode
        self.name_ops: list[_NameOp] = []
        self.acked: list[str] = []

    def _bind(self, idx: int, path: str, inode: _Inode) -> None:
        self.files[path] = inode
        self.name_ops.append(_NameOp(
            idx, [("set", path, inode)], {os.path.dirname(path)}))

    def _move(self, idx: int, src: str, dst: str) -> None:
        """rename/replace of a file OR a directory subtree, as one
        atomic namespace transition."""
        edits: list[tuple] = []
        dirs = {os.path.dirname(src), os.path.dirname(dst)}
        if src in self.files:  # plain file
            inode = self.files.pop(src)
            self.files[dst] = inode
            edits = [("del", src), ("set", dst, inode)]
        else:  # directory: move every tracked path under it
            prefix = src.rstrip(os.sep) + os.sep
            moved = [p for p in self.files if p.startswith(prefix)]
            for p in moved:
                inode = self.files.pop(p)
                newp = dst.rstrip(os.sep) + os.sep + p[len(prefix):]
                self.files[newp] = inode
                edits.append(("del", p))
                edits.append(("set", newp, inode))
        self.name_ops.append(_NameOp(idx, edits, dirs))

    def apply(self, idx: int, op: tuple) -> None:
        kind = op[0]
        if kind == "open":
            _, path, mode = op
            if mode == "a" and path in self.files:
                return  # append to an existing inode: no name change
            self._bind(idx, path, _Inode())
        elif kind == "write":
            _, path, off, data = op
            inode = self.files.get(path)
            if inode is None:  # write with no recorded open: adopt
                inode = _Inode()
                self._bind(idx, path, inode)
            inode.pending.append((off, data, idx))
        elif kind == "fsync":
            inode = self.files.get(op[1])
            if inode is not None:
                inode.synced = inode.content()
                inode.pending = []
        elif kind == "fsync_dir":
            d = op[1].rstrip(os.sep)
            for nop in self.name_ops:
                if nop.durable_at is None and nop.idx < idx and \
                        any(x.rstrip(os.sep) == d for x in nop.dirs):
                    nop.durable_at = idx
        elif kind in ("replace", "rename"):
            self._move(idx, op[1], op[2])
        elif kind == "remove":
            _, path = op
            self.files.pop(path, None)
            self.name_ops.append(_NameOp(
                idx, [("del", path)], {os.path.dirname(path)}))
        elif kind == "rmtree":
            _, path = op
            prefix = path.rstrip(os.sep) + os.sep
            doomed = [p for p in self.files
                      if p == path or p.startswith(prefix)]
            for p in doomed:
                self.files.pop(p, None)
            self.name_ops.append(_NameOp(
                idx, [("del", p) for p in doomed],
                {os.path.dirname(path)}))
        elif kind == "mark":
            self.acked.append(op[1])
        else:  # pragma: no cover - future op kinds
            raise ValueError(f"crashsim: unknown op kind {kind!r}")

    # ---- crash-state construction ----------------------------------------

    def namespace(self, upto: int, *, all_names: bool) -> dict[str, _Inode]:
        """path -> inode after applying the name ops with idx <= upto
        that are durable (or all of them when ``all_names``)."""
        ns: dict[str, _Inode] = {}
        for nop in self.name_ops:
            if nop.idx > upto:
                break
            durable = nop.durable_at is not None and nop.durable_at <= upto
            if not (durable or all_names):
                continue
            for edit in nop.edits:
                if edit[0] == "set":
                    ns[edit[1]] = edit[2]
                else:
                    ns.pop(edit[1], None)
        return ns


def _state_bytes(ns: dict[str, _Inode], **content_kw) -> dict[str, bytes]:
    return {p: inode.content(**content_kw) for p, inode in ns.items()}


def _crash_states(walk: _Walk, upto: int):
    """Yield (kind, {path: bytes}) for every modeled crash state at
    this crash point."""
    ns_all = walk.namespace(upto, all_names=True)
    ns_dur = walk.namespace(upto, all_names=False)
    yield "all-applied", _state_bytes(ns_all)
    yield "durable-only", _state_bytes(ns_dur, include_pending=False)
    yield "names-applied/content-synced", \
        _state_bytes(ns_all, include_pending=False)
    yield "names-synced/content-applied", _state_bytes(ns_dur)
    # torn tail of the LAST unsynced write
    pend = [(idx, len(data))
            for inode in ns_all.values()
            for _off, data, idx in inode.pending]
    if pend:
        last_idx, last_len = max(pend)
        for cut in sorted({0, 1, last_len // 2, max(0, last_len - 1)}):
            if cut >= last_len:
                continue
            yield f"torn-tail@{cut}", \
                _state_bytes(ns_all, cut=(last_idx, cut))
        # drop-one: unsynced writes may be reordered/lost independently
        drop = sorted({idx for idx, _n in pend})[-MAX_DROP_VARIANTS:]
        for idx in drop:
            yield f"drop-write@{idx}", _state_bytes(ns_all, drop_idx=idx)


def explore(recorder: OpRecorder, workdir: str,
            check: Callable[[str, list[str]], None],
            *, fail_fast: bool = False) -> CrashReport:
    """Enumerate every crash state of the recorded run and call
    ``check(recovered_dir, acked_labels)`` on each; ``check`` runs the
    real recovery code and raises (AssertionError or any exception) on
    an invariant violation. Paths in the op log must live under
    ``workdir``; each state is materialized into a fresh directory laid
    out the same way."""
    ops = list(recorder.ops)
    workdir = os.path.abspath(workdir)
    report = CrashReport(ops=len(ops))
    seen: set[bytes] = set()
    # crash before the first op, between every pair, and after the last
    for upto in range(-1, len(ops)):
        report.crash_points += 1
        walk = _Walk()
        for i, op in enumerate(ops[:upto + 1]):
            walk.apply(i, op)
        for kind, state in _crash_states(walk, upto):
            digest = hashlib.sha256(repr(
                sorted((p, hashlib.sha256(b).digest())
                       for p, b in state.items())
            ).encode() + repr(walk.acked).encode()).digest()
            if digest in seen:
                report.states_deduped += 1
                continue
            seen.add(digest)
            report.states_explored += 1
            with tempfile.TemporaryDirectory(
                    prefix="crashsim-") as croot:
                for path, data in state.items():
                    rel = os.path.relpath(os.path.abspath(path), workdir)
                    if rel.startswith(".."):
                        raise ValueError(
                            f"crashsim: op path {path!r} escapes "
                            f"workdir {workdir!r}")
                    dst = os.path.join(croot, rel)
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    with open(dst, "wb") as f:
                        f.write(data)
                try:
                    check(croot, list(walk.acked))
                except Exception as e:  # noqa: BLE001 -- any recovery failure is the finding
                    report.violations.append(Violation(
                        upto, kind, list(walk.acked),
                        f"{type(e).__name__}: {e}"))
                    if fail_fast:
                        return report
    return report
