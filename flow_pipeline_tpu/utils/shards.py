"""Multi-host array addressability helpers (dependency-free leaf module:
both the models and parallel layers use these without importing each
other)."""

from __future__ import annotations

import numpy as np


def local_device_blocks(arr) -> np.ndarray:
    """Device-axis blocks of ``arr`` this PROCESS can address, stacked in
    device order. Fully-addressable arrays (single host) come back whole;
    multi-host arrays sharded on axis 0 yield only the local shards —
    np.asarray on the full array would fail, since no process addresses
    every shard."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    shards = sorted(arr.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
