"""Utilities: the dotted-flag config system and small shared helpers."""

from .flags import FlagSet, Flag

__all__ = ["FlagSet", "Flag"]
