"""Dotted-flag config system.

The reference configures everything through Go stdlib flags with dotted
names — ``-kafka.brokers``, ``-flush.dur``, ``-proto.fixedlen``,
``-loglevel`` (ref: inserter/inserter.go:26-42, mocker/mocker.go:15-23) —
and one env fallback ($POSTGRES_PASSWORD when -postgres.pass is unset,
ref: inserter/inserter.go:220-224). This module reproduces that exact
surface (single-dash long flags, ``-flag value`` and ``-flag=value``,
bools accepting bare ``-flag`` / ``-flag=false``) so compose command lines
written for the reference binaries carry over.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

# The single flag registry. Every flag any binary declares MUST be listed
# here (FlagSet's builders assert it; tools/flowlint's flag-registry rule
# additionally checks that every `-x.y` string literal in the repo names
# a registered flag and that every dotted flag is documented in
# README/docs — see docs/STATIC_ANALYSIS.md). A typo'd flag name in a
# bench harness or compose file otherwise parses fine and silently
# measures the wrong configuration.
KNOWN_FLAGS = frozenset({
    # common
    "loglevel", "kafka.topic", "kafka.brokers", "proto.fixedlen",
    # generator / mocker
    "produce.count", "produce.rate", "produce.seed", "produce.profile",
    "produce.batch", "produce.shard", "zipf.keys", "zipf.alpha",
    "zipf.spread", "out",
    # processor
    "processor.backend", "processor.batch", "processor.mesh",
    "processor.fused", "processor.hostassist",
    "model.flows5m", "model.talkers", "model.ips", "model.ports",
    "model.ddos",
    "sketch.width", "sketch.cms", "sketch.prefilter", "sketch.admission",
    "sketch.capacity", "sketch.topk", "sketch.backend", "hh.sketch",
    # flowspread (models/spread.py) — distinct-count detectors
    "spread.enabled", "spread.depth", "spread.width", "spread.regs",
    "spread.capacity", "spread.topk",
    "window.lateness", "archive.raw", "feed.prefetch",
    "ingest.mode", "ingest.shards", "ingest.depth", "ingest.flush_queue",
    "ingest.native_group", "ingest.fused", "ingest.threads",
    "checkpoint.path", "flush.count", "metrics.addr", "sink", "in",
    "listen.feed", "query.addr", "obs.trace", "obs.audit",
    # flowchaos (utils/faults.py, sink/resilient.py, mesh/journal.py)
    "faults", "sink.retries", "sink.deadletter",
    # flowguard (guard/) — overload control + degradation ladder
    "guard.lag", "guard.max_level", "guard.serve_queue",
    "guard.serve_deadline",
    # flowtpu-replay (the dead-letter re-ingestion subcommand)
    "replay.dir", "replay.delete",
    # flowserve (serve/)
    "serve.addr", "serve.refresh", "serve.feed_bytes",
    # flowgate (gateway/)
    "gateway.listen", "gateway.upstream", "gateway.poll",
    "gateway.adopt-restart",
    # flowhistory (history/) — durable snapshot archive + time travel
    "history.dir", "history.keyframe", "history.retain",
    "history.upstream", "history.listen", "history.poll",
    # flowmesh (mesh/)
    "mesh.workers", "mesh.role", "mesh.coordinator", "mesh.id",
    "mesh.listen", "mesh.heartbeat", "mesh.journal",
    # meshscope lineage CLI (the `lineage` subcommand)
    "lineage.model", "lineage.slot", "lineage.raw",
    # inserter
    "postgres.dsn", "postgres.pass", "sqlite", "flush.dur",
    # topic admin
    "bus.partitions",
    # collector
    "listen.netflow", "listen.sflow", "run.seconds",
})


@dataclass
class Flag:
    name: str
    default: Any
    help: str
    parse: Callable[[str], Any]
    env: Optional[str] = None  # env var fallback when flag unset
    is_bool: bool = False


def _parse_bool(s: str) -> bool:
    if s.lower() in ("1", "true", "t", "yes"):
        return True
    if s.lower() in ("0", "false", "f", "no"):
        return False
    raise ValueError(f"invalid boolean {s!r}")


class FlagSet:
    def __init__(self, prog: str):
        self.prog = prog
        self._flags: dict[str, Flag] = {}
        self.values: dict[str, Any] = {}

    def _register(self, flag: Flag) -> None:
        if flag.name not in KNOWN_FLAGS:
            raise ValueError(
                f"flag -{flag.name} is not in utils.flags.KNOWN_FLAGS; "
                "add it to the registry (and document it — `make lint` "
                "enforces both)")
        self._flags[flag.name] = flag

    def string(self, name: str, default: str, help_: str, env: str | None = None):
        self._register(Flag(name, default, help_, str, env))
        return self

    def integer(self, name: str, default: int, help_: str):
        self._register(Flag(name, default, help_, int))
        return self

    def number(self, name: str, default: float, help_: str):
        self._register(Flag(name, default, help_, float))
        return self

    def boolean(self, name: str, default: bool, help_: str):
        self._register(Flag(name, default, help_, _parse_bool, is_bool=True))
        return self

    def usage(self) -> str:
        lines = [f"Usage of {self.prog}:"]
        for name in sorted(self._flags):
            f = self._flags[name]
            lines.append(f"  -{name} (default {f.default!r})\n        {f.help}")
        return "\n".join(lines)

    def parse(self, argv: Sequence[str]) -> dict[str, Any]:
        """Parse Go-style flags; raises SystemExit on -h/-help, ValueError on
        unknown or malformed flags."""
        vals = {}
        i = 0
        argv = list(argv)
        while i < len(argv):
            arg = argv[i]
            if not arg.startswith("-"):
                raise ValueError(f"unexpected positional argument {arg!r}")
            name = arg.lstrip("-")
            value = None
            if "=" in name:
                name, value = name.split("=", 1)
            if name in ("h", "help"):
                print(self.usage())
                raise SystemExit(0)
            flag = self._flags.get(name)
            if flag is None:
                raise ValueError(f"flag provided but not defined: -{name}\n{self.usage()}")
            if value is None:
                if flag.is_bool:
                    value = "true"  # bare -flag
                else:
                    i += 1
                    if i >= len(argv):
                        raise ValueError(f"flag -{name} needs a value")
                    value = argv[i]
            try:
                vals[name] = flag.parse(value)
            except ValueError as e:
                raise ValueError(f"invalid value for -{name}: {e}") from e
            i += 1
        for name, flag in self._flags.items():
            if name not in vals:
                if flag.env and os.environ.get(flag.env):
                    vals[name] = flag.parse(os.environ[flag.env])
                else:
                    vals[name] = flag.default
        self.values = vals
        return vals
