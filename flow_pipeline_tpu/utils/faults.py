"""flowchaos deterministic fault injection.

The only way the repo exercised failure before r17 was hand-written
kill-one-worker legs; every OTHER seam where a network-wide answer is
assembled — sink writes, the member->coordinator submit/sync hops, the
Kafka adapters, the serve publisher fan-out — ran fault-free in every
test. This module injects faults at exactly those seams, DETERMINISTICALLY,
so a chaos leg is a reproducible test, not a flake generator:

- A **fault plan** names sites and per-call failure probabilities::

      sink.write:p=0.05;mesh.submit:p=0.02@seed=7

  parsed by :func:`parse_plan`; configured via the ``-faults=`` flag or
  the ``FLOWTPU_FAULTS`` env fallback (flagless processes — the same
  contract as ``FLOWTPU_TRACE``).

- Each site draws from its OWN ``random.Random`` seeded by
  ``(seed, site)``, so the Bernoulli sequence at one site is a pure
  function of (plan, call index at that site) — thread interleaving
  ACROSS sites, or adding a new site to the plan, cannot change another
  site's outcomes. Same plan + same per-site call order => same faults.

- An injected fault raises :class:`FaultInjected`, a subclass of
  ``OSError`` — the same type family real transport failures surface
  as, so every retry/dead-letter/rejoin path treats injected and real
  faults identically (the whole point: the chaos soak drives the REAL
  recovery machinery, not a parallel test-only path).

- **Off mode is one attribute read**: call sites guard with
  ``if FAULTS.active and FAULTS.should_fail("site"): ...`` — with no
  plan configured, the seam costs a single attribute load (the
  ``bench.py chaos`` paired A/B pins the engaged-but-never-firing cost
  under 2% as well).

Known sites (kept in :data:`KNOWN_SITES` so a typo'd plan fails loudly
instead of silently injecting nothing): ``sink.write``,
``mesh.submit``, ``mesh.sync``, ``kafka.send``, ``kafka.poll``,
``serve.publish``, ``bus.produce``, ``bus.poll``, ``gateway.poll``.
"""

from __future__ import annotations

# flowlint: lock-checked
# (fault rolls happen on every pipeline thread — worker, flusher,
# member drivers, publisher; one lock guards the per-site RNG streams)

import random
import threading
from typing import Optional

from ..obs import REGISTRY

# The seams the dataplane actually threads FAULTS through. configure()
# rejects unknown sites: a chaos leg whose plan names a site nothing
# checks would "pass" by injecting nothing.
KNOWN_SITES = frozenset({
    "sink.write", "mesh.submit", "mesh.sync", "kafka.send", "kafka.poll",
    "serve.publish",
    # r18: the in-process bus (collector-side chaos — the produce path
    # a collector/mocker rides and the fetch path every consumer rides)
    # and the flowgate subscription poll
    "bus.produce", "bus.poll", "gateway.poll",
})


class FaultInjected(OSError):
    """An injected transport/IO fault. Subclasses OSError so the normal
    retry/recovery paths handle it exactly like a real failure."""


def parse_plan(spec: str) -> tuple[dict[str, float], int]:
    """``"site:p=0.05;site2:p=0.02@seed=7"`` -> ({site: p}, seed).
    Raises ValueError on malformed specs, unknown sites, or
    probabilities outside [0, 1]."""
    spec = spec.strip()
    seed = 0
    if "@" in spec:
        spec, _, tail = spec.rpartition("@")
        key, _, val = tail.partition("=")
        if key.strip() != "seed":
            raise ValueError(f"expected @seed=N, got @{tail!r}")
        seed = int(val)
    sites: dict[str, float] = {}
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        site, sep, params = part.partition(":")
        site = site.strip()
        if not sep:
            raise ValueError(f"fault site {part!r} needs :p=<prob>")
        if site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: "
                f"{', '.join(sorted(KNOWN_SITES))})")
        key, _, val = params.partition("=")
        if key.strip() != "p":
            raise ValueError(f"fault site {site!r}: expected p=<prob>, "
                             f"got {params!r}")
        p = float(val)
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"fault site {site!r}: p={p} outside [0, 1]")
        sites[site] = p
    return sites, seed


class _Site:
    __slots__ = ("p", "rng", "rolls", "injected")

    def __init__(self, p: float, seed: int, name: str):
        self.p = p
        # per-site stream: the site name folds into the seed so streams
        # are independent — call interleaving across sites cannot shift
        # another site's Bernoulli sequence
        self.rng = random.Random(f"{seed}:{name}")
        self.rolls = 0
        self.injected = 0


class FaultPlan:
    """The process-global fault plan. ``configure(spec)`` arms it;
    ``configure(None)`` / ``configure("")`` disarms (tests MUST disarm
    in teardown — the plan is process state like TRACER)."""

    def __init__(self):
        # flowlint: unguarded -- armed/disarmed once at configure time (before the threads that read it); hot-path reads are a racy-but-monotone bool by design
        self.active = False
        # flowlint: unguarded -- the lock itself; bound once
        self._lock = threading.Lock()
        self._sites: dict[str, _Site] = {}  # guarded-by: _lock
        # flowlint: unguarded -- rebound only under configure (single caller at startup)
        self.spec = ""
        self.m_injected = REGISTRY.counter(
            "faults_injected_total",
            "flowchaos injected faults (label: site)")

    def configure(self, spec: Optional[str]) -> None:
        """Arm/disarm from a plan spec. Empty/None = off."""
        with self._lock:
            if not spec:
                self._sites = {}
                self.active = False
                self.spec = ""
                return
            sites, seed = parse_plan(spec)
            self._sites = {name: _Site(p, seed, name)
                           for name, p in sites.items()}
            self.spec = spec
            self.active = any(s.p > 0 for s in self._sites.values())

    def should_fail(self, site: str) -> bool:
        """One Bernoulli roll on the site's deterministic stream. Call
        guarded: ``if FAULTS.active and FAULTS.should_fail(...)``."""
        with self._lock:
            st = self._sites.get(site)
            if st is None or st.p <= 0.0:
                # p=0 sites still exist (the bench A/B runs the armed
                # path with p=0) but consume no roll — a zero-p site
                # must not perturb its own future stream
                return False
            st.rolls += 1
            hit = st.rng.random() < st.p
            if hit:
                st.injected += 1
        if hit:
            self.m_injected.inc(site=site)
        return hit

    def check(self, site: str) -> None:
        """Raise FaultInjected when the site's roll fails."""
        if self.active and self.should_fail(site):
            raise FaultInjected(f"injected fault at {site} "
                                f"(plan {self.spec!r})")

    def snapshot(self) -> dict:
        """{site: {"p", "rolls", "injected"}} — the bench artifact's
        injection record."""
        with self._lock:
            return {name: {"p": st.p, "rolls": st.rolls,
                           "injected": st.injected}
                    for name, st in self._sites.items()}


FAULTS = FaultPlan()
