"""flowchaos deterministic fault injection.

The only way the repo exercised failure before r17 was hand-written
kill-one-worker legs; every OTHER seam where a network-wide answer is
assembled — sink writes, the member->coordinator submit/sync hops, the
Kafka adapters, the serve publisher fan-out — ran fault-free in every
test. This module injects faults at exactly those seams, DETERMINISTICALLY,
so a chaos leg is a reproducible test, not a flake generator:

- A **fault plan** names sites and per-call failure probabilities::

      sink.write:p=0.05;mesh.submit:p=0.02@seed=7

  parsed by :func:`parse_plan`; configured via the ``-faults=`` flag or
  the ``FLOWTPU_FAULTS`` env fallback (flagless processes — the same
  contract as ``FLOWTPU_TRACE``).

- **Latency injection** (flowguard, r20): a site may carry a
  ``delay=<seconds>`` parameter instead of pure failure::

      sink.write:delay=0.02;bus.poll:p=0.5:delay=0.1@seed=7

  A hit at a delay site SLEEPS (outside the plan lock) instead of
  raising — a slow sink / slow upstream, not a dead one, which is the
  overload shape ``make guard-parity`` soaks. ``delay=`` without ``p=``
  means p=1 (every call stalls). A site is either a failure site
  (delay 0) or a latency site (delay > 0); the Bernoulli stream
  discipline is identical for both.

- Each site draws from its OWN ``random.Random`` seeded by
  ``(seed, site)``, so the Bernoulli sequence at one site is a pure
  function of (plan, call index at that site) — thread interleaving
  ACROSS sites, or adding a new site to the plan, cannot change another
  site's outcomes. Same plan + same per-site call order => same faults.

- An injected fault raises :class:`FaultInjected`, a subclass of
  ``OSError`` — the same type family real transport failures surface
  as, so every retry/dead-letter/rejoin path treats injected and real
  faults identically (the whole point: the chaos soak drives the REAL
  recovery machinery, not a parallel test-only path).

- **Off mode is one attribute read**: call sites guard with
  ``if FAULTS.active and FAULTS.should_fail("site"): ...`` — with no
  plan configured, the seam costs a single attribute load (the
  ``bench.py chaos`` paired A/B pins the engaged-but-never-firing cost
  under 2% as well).

Known sites (kept in :data:`KNOWN_SITES` so a typo'd plan fails loudly
instead of silently injecting nothing): ``sink.write``,
``mesh.submit``, ``mesh.sync``, ``kafka.send``, ``kafka.poll``,
``serve.publish``, ``bus.produce``, ``bus.poll``, ``gateway.poll``.
"""

from __future__ import annotations

# flowlint: lock-checked
# (fault rolls happen on every pipeline thread — worker, flusher,
# member drivers, publisher; one lock guards the per-site RNG streams)

import random
import threading
import time
from typing import Optional

from ..obs import REGISTRY

# The seams the dataplane actually threads FAULTS through. configure()
# rejects unknown sites: a chaos leg whose plan names a site nothing
# checks would "pass" by injecting nothing.
KNOWN_SITES = frozenset({
    "sink.write", "mesh.submit", "mesh.sync", "kafka.send", "kafka.poll",
    "serve.publish",
    # r18: the in-process bus (collector-side chaos — the produce path
    # a collector/mocker rides and the fetch path every consumer rides)
    # and the flowgate subscription poll
    "bus.produce", "bus.poll", "gateway.poll",
})


class FaultInjected(OSError):
    """An injected transport/IO fault. Subclasses OSError so the normal
    retry/recovery paths handle it exactly like a real failure."""


def parse_plan_full(spec: str) -> tuple[dict[str, tuple[float, float]], int]:
    """``"site:p=0.05;site2:delay=0.02@seed=7"`` ->
    ({site: (p, delay)}, seed). Raises ValueError on malformed specs,
    unknown sites, probabilities outside [0, 1], or delays outside
    [0, 60]. ``delay=`` without ``p=`` implies p=1 (every call stalls)."""
    spec = spec.strip()
    seed = 0
    if "@" in spec:
        spec, _, tail = spec.rpartition("@")
        key, _, val = tail.partition("=")
        if key.strip() != "seed":
            raise ValueError(f"expected @seed=N, got @{tail!r}")
        seed = int(val)
    sites: dict[str, tuple[float, float]] = {}
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        site, sep, params = part.partition(":")
        site = site.strip()
        if not sep:
            raise ValueError(
                f"fault site {part!r} needs :p=<prob> and/or "
                f":delay=<seconds>")
        if site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: "
                f"{', '.join(sorted(KNOWN_SITES))})")
        p: Optional[float] = None
        delay = 0.0
        for param in filter(None, (s.strip() for s in params.split(":"))):
            key, _, val = param.partition("=")
            key = key.strip()
            if key == "p":
                p = float(val)
                if not 0.0 <= p <= 1.0:
                    raise ValueError(
                        f"fault site {site!r}: p={p} outside [0, 1]")
            elif key == "delay":
                delay = float(val)
                if not 0.0 <= delay <= 60.0:
                    raise ValueError(
                        f"fault site {site!r}: delay={delay} outside "
                        f"[0, 60] seconds")
            else:
                raise ValueError(
                    f"fault site {site!r}: expected p=<prob> or "
                    f"delay=<seconds>, got {param!r}")
        if p is None:
            if delay <= 0.0:
                raise ValueError(
                    f"fault site {site!r}: expected p=<prob>, "
                    f"got {params!r}")
            p = 1.0  # delay-only site: every call stalls
        sites[site] = (p, delay)
    return sites, seed


def parse_plan(spec: str) -> tuple[dict[str, float], int]:
    """``"site:p=0.05;site2:p=0.02@seed=7"`` -> ({site: p}, seed) —
    the original probability-only view (delay parameters are parsed
    and validated, then dropped; :func:`parse_plan_full` keeps them)."""
    sites, seed = parse_plan_full(spec)
    return {name: pd[0] for name, pd in sites.items()}, seed


class _Site:
    __slots__ = ("p", "delay", "rng", "rolls", "injected", "delayed")

    def __init__(self, p: float, seed: int, name: str, delay: float = 0.0):
        self.p = p
        self.delay = delay  # > 0: a hit stalls instead of raising
        # per-site stream: the site name folds into the seed so streams
        # are independent — call interleaving across sites cannot shift
        # another site's Bernoulli sequence
        self.rng = random.Random(f"{seed}:{name}")
        self.rolls = 0
        self.injected = 0
        self.delayed = 0


class FaultPlan:
    """The process-global fault plan. ``configure(spec)`` arms it;
    ``configure(None)`` / ``configure("")`` disarms (tests MUST disarm
    in teardown — the plan is process state like TRACER)."""

    def __init__(self):
        # flowlint: unguarded -- armed/disarmed once at configure time (before the threads that read it); hot-path reads are a racy-but-monotone bool by design
        self.active = False
        # flowlint: unguarded -- the lock itself; bound once
        self._lock = threading.Lock()
        self._sites: dict[str, _Site] = {}  # guarded-by: _lock
        # flowlint: unguarded -- rebound only under configure (single caller at startup)
        self.spec = ""
        self.m_injected = REGISTRY.counter(
            "faults_injected_total",
            "flowchaos injected faults (label: site)")
        self.m_delayed = REGISTRY.counter(
            "faults_delayed_total",
            "flowchaos injected latency stalls (label: site) — delay "
            "sites slow a call instead of failing it")

    def configure(self, spec: Optional[str]) -> None:
        """Arm/disarm from a plan spec. Empty/None = off."""
        with self._lock:
            if not spec:
                self._sites = {}
                self.active = False
                self.spec = ""
                return
            sites, seed = parse_plan_full(spec)
            self._sites = {name: _Site(p, seed, name, delay)
                           for name, (p, delay) in sites.items()}
            self.spec = spec
            self.active = any(s.p > 0 for s in self._sites.values())

    def _roll(self, site: str) -> tuple[bool, float]:
        """One Bernoulli roll on the site's deterministic stream ->
        (hit, delay seconds). The roll discipline is identical for
        failure and latency sites — the delay only changes what a hit
        DOES, never the stream."""
        with self._lock:
            st = self._sites.get(site)
            if st is None or st.p <= 0.0:
                # p=0 sites still exist (the bench A/B runs the armed
                # path with p=0) but consume no roll — a zero-p site
                # must not perturb its own future stream
                return False, 0.0
            st.rolls += 1
            hit = st.rng.random() < st.p
            delay = st.delay
            if hit:
                if delay > 0.0:
                    st.delayed += 1
                else:
                    st.injected += 1
        if hit:
            if delay > 0.0:
                self.m_delayed.inc(site=site)
            else:
                self.m_injected.inc(site=site)
        return hit, delay

    def should_fail(self, site: str) -> bool:
        """One Bernoulli roll on the site's deterministic stream. Call
        guarded: ``if FAULTS.active and FAULTS.should_fail(...)``.
        Latency sites never FAIL — a hit there returns False (check()
        is where the stall happens)."""
        hit, delay = self._roll(site)
        return hit and delay <= 0.0

    def check(self, site: str) -> None:
        """Raise FaultInjected when the site's roll fails; SLEEP (the
        injected latency, outside the plan lock) when the site is a
        delay site — a slow dependency, not a dead one."""
        if not self.active:
            return
        hit, delay = self._roll(site)
        if not hit:
            return
        if delay > 0.0:
            time.sleep(delay)
            return
        raise FaultInjected(f"injected fault at {site} "
                            f"(plan {self.spec!r})")

    def snapshot(self) -> dict:
        """{site: {"p", "delay", "rolls", "injected", "delayed"}} —
        the bench artifact's injection record."""
        with self._lock:
            return {name: {"p": st.p, "delay": st.delay,
                           "rolls": st.rolls, "injected": st.injected,
                           "delayed": st.delayed}
                    for name, st in self._sites.items()}


FAULTS = FaultPlan()
