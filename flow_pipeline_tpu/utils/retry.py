"""Bounded exponential backoff with jitter — the single retry policy
every external edge shares (flowchaos).

The pipeline's external edges — sink writes, the mesh member's
submit/sync HTTP round-trips, the Kafka adapters — were all single-shot
before r17: one transient blip became a ``FlushError`` (killing the
worker) or an unhandled ``URLError`` (killing the member thread). This
module is the one place the retry discipline lives so the policy cannot
drift per edge:

- **bounded**: a hard attempt cap — unbounded retries against a dead
  dependency wedge the caller forever (and hide the outage).
- **exponential + jitter**: delays double per attempt up to a cap, with
  multiplicative jitter so N workers hitting the same dead sink do not
  retry in lockstep (the thundering-herd the reference's inserter
  exhibits on Postgres restarts).
- **retryable means transient**: the default filter is ``OSError`` —
  connection refused/reset, timeouts, and injected
  :class:`~flow_pipeline_tpu.utils.faults.FaultInjected` faults. A
  schema error or a protocol rejection is NOT retried; retrying a
  deterministic failure just triples its latency.

Callers that must not lose work on exhaustion layer their own fallback
on top (the sink dead-letter spill in ``sink/resilient.py``; the mesh
member restores its captured windows and retries on the next step).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Sequence


def backoff_delays(attempts: int, base: float, cap: float,
                   jitter: float, rng: random.Random):
    """The delay before each RETRY (attempts - 1 values): exponential
    from ``base`` doubling to ``cap``, each multiplied by a factor drawn
    uniformly from [1, 1 + jitter]."""
    for i in range(max(0, attempts - 1)):
        delay = min(cap, base * (2 ** i))
        yield delay * (1.0 + jitter * rng.random())


def retry_call(fn: Callable, *, attempts: int = 4, base: float = 0.05,
               cap: float = 2.0, jitter: float = 0.25,
               retry_on: Sequence[type] = (OSError,),
               sleep: Callable[[float], None] = time.sleep,
               on_retry: Optional[Callable] = None,
               rng: Optional[random.Random] = None):
    """Call ``fn()`` with up to ``attempts`` tries. Exceptions matching
    ``retry_on`` back off and retry; the last attempt's exception
    propagates. ``on_retry(attempt_index, exc, delay)`` observes each
    retry (metrics/log hooks). ``sleep``/``rng`` are injectable so tests
    run instantly and deterministically."""
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    rng = rng or random.Random()
    delays = backoff_delays(attempts, base, cap, jitter, rng)
    for attempt in range(attempts):
        try:
            return fn()
        except tuple(retry_on) as e:
            if attempt == attempts - 1:
                raise
            delay = next(delays)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
