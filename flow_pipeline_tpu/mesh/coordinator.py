"""flowmesh coordinator: membership, epoch-fenced partition ownership,
and the window-close merge barrier.

One coordinator owns the authoritative offset frontier of every bus
partition and merges per-worker window state into the network-wide
result (mesh/merge.py). The protocol is a miniature Kafka group
coordinator with the merge barrier fused in:

- **Membership**: members join, then heartbeat via ``sync()``. A member
  that misses ``heartbeat_timeout`` is fenced (declared dead); its
  partitions are released and the target assignment recomputed
  (epoch + 1). ``fence()`` is the same path as an admin surface (and
  the deterministic lever the churn tests use).

- **Ownership**: partitions are assigned round-robin over the sorted
  live member ids — the same deterministic rule as
  ``parallel.multihost.reassign_lost_partitions`` (every observer can
  recompute the map). A member whose owned set differs from its target
  is told to RESYNC: it submits all of its state with ``release``,
  drops its worker, and re-acquires its target set; a new owner
  acquires a partition only after the previous owner released it (or
  died), always resuming from the coordinator's ``covered`` frontier.

- **Exactness**: a submission carries, per owned partition, the offset
  range it consumed since its last accepted submission, and the state
  of every window those rows touched (closed windows as final
  contributions, the open window as a replaceable CARRY). Accept
  requires each range to extend the frontier exactly; anything never
  accepted is replayed by the successor from the frontier, anything
  accepted is in exactly one contribution. Zombies are fenced: a
  submission from a dead-declared member is rejected, so its
  un-accepted rows are replayed by the new owner and never double
  count. A window (model, slot) merges once every partition's
  watermark passes slot + window (+ lateness) or is final — at which
  point monoid-folding ALL its contributions reproduces the
  single-worker oracle exactly (tests/test_mesh.py).
"""

from __future__ import annotations

# flowlint: lock-checked
# (member-facing methods run on N member threads plus HTTP handler
# threads; every mutable attribute declares its lock below. Sink writes
# and merge math deliberately run OUTSIDE the locks — only the ready-set
# pop and the merged-rows ledger are serialized.)

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from ..obs import REGISTRY, get_logger
from . import codec
from . import merge as merge_ops

log = get_logger("mesh")

# Buckets for the window-merge wall-time histogram (seconds): sub-ms
# in-process folds up to multi-second cross-network merges.
MERGE_SECONDS_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Merged-rows ledger retention, per model: the newest slots kept for
# queries/tests/debugging. The SINKS are the durable home of merged
# output; an unbounded ledger on an endless stream is a slow OOM
# (days of 5-minute windows accumulate every historical row set).
MERGED_LEDGER_SLOTS = 16

# Metric name/help specs live here once; the deploy honesty test
# resolves the Grafana mesh panels against a constructed coordinator.
MESH_METRICS = {
    "members": ("mesh_members", "live flowmesh members"),
    "epoch": ("mesh_epoch", "current flowmesh assignment epoch"),
    "partitions": ("mesh_partitions", "bus partitions under mesh control"),
    "rebalance": ("mesh_rebalance_total",
                  "mesh rebalances (label: reason=join|leave|death)"),
    "merged": ("mesh_windows_merged_total",
               "windows merged network-wide (label: model)"),
    "merge_s": ("mesh_merge_seconds",
                "window-close merge wall time (decode+fold+extract)"),
    "flows": ("mesh_member_flows_total",
              "flows ingested per member (label: member)"),
    "submit": ("mesh_submit_total", "accepted member submissions"),
    "rejected": ("mesh_submit_rejected_total",
                 "rejected member submissions (label: reason)"),
    "late": ("mesh_late_contribution_total",
             "contributions that arrived after their window merged "
             "(label: model)"),
}


@dataclass(frozen=True)
class ModelSpec:
    """One mergeable model: name, kind tag, frozen config, extraction k,
    window cadence. Built from a worker's models dict so the coordinator
    merges exactly what the members compute."""

    name: str
    kind: str  # "wagg" | "hh" | "dense"
    config: Any
    k: int
    window_seconds: int
    allowed_lateness: int = 0


def spec_from_models(models: dict) -> tuple[ModelSpec, ...]:
    """Derive the mergeable model specs from a models dict (the same
    dict cli._build_models produces). DDoS detectors are deliberately
    absent: their per-dst rates are split across shards by the key
    hash, so mesh mode keeps detection per-shard (the HashPipe model —
    per-shard detection) and alerts flow through member sinks."""
    from ..engine.windowed import WindowedHeavyHitter
    from ..models.window_agg import WindowAggregator

    out = []
    for name, m in models.items():
        if isinstance(m, WindowAggregator):
            out.append(ModelSpec(
                name, "wagg", m.config, 0, m.config.window_seconds,
                m.config.allowed_lateness))
        elif isinstance(m, WindowedHeavyHitter):
            kind = ("hh" if m.model.snapshot_kind == "windowed_hh"
                    else "dense")
            out.append(ModelSpec(name, kind, m.config, m.k,
                                 m.window_seconds))
    return tuple(out)


class _Member:
    __slots__ = ("alive", "last_hb", "owned", "provider")

    def __init__(self, provider=None):
        self.alive = True
        self.last_hb = 0.0
        self.owned: set[int] = set()
        self.provider = provider  # callable(model)->payload | state URL


class MeshCoordinator:
    """Coordinator + merge engine. Duck-type shared with
    mesh.server.RemoteCoordinator so members run identically in-process
    and over HTTP."""

    def __init__(self, specs: Sequence[ModelSpec], n_partitions: int,
                 sinks: Sequence[Any] = (),
                 heartbeat_timeout: float = 5.0,
                 time_fn: Callable[[], float] = time.monotonic):
        self.specs = tuple(specs)
        self._by_name = {s.name: s for s in self.specs}
        self.n_partitions = int(n_partitions)
        self.sinks = list(sinks)
        self.heartbeat_timeout = heartbeat_timeout
        self._time = time_fn
        # flowlint: unguarded -- the locks themselves; bound once
        self._lock = threading.Lock()
        # flowlint: unguarded -- bound once (guards only the merged-rows ledger)
        self._merge_lock = threading.Lock()
        self.epoch = 0  # guarded-by: _lock
        self._members: dict[str, _Member] = {}  # guarded-by: _lock
        self._targets: dict[str, set[int]] = {}  # guarded-by: _lock
        self._released: set[int] = set(range(self.n_partitions))  # guarded-by: _lock
        self._covered = [0] * self.n_partitions  # guarded-by: _lock
        self._wm = [0] * self.n_partitions  # guarded-by: _lock
        self._final = [False] * self.n_partitions  # guarded-by: _lock
        # (model, slot) -> list of decoded payloads awaiting the barrier
        self._pending: dict[tuple[str, int], list] = {}  # guarded-by: _lock
        # member -> latest open-window state {slot: {model: payload}};
        # replaced on every accepted submission, promoted on death
        self._carry: dict[str, dict] = {}  # guarded-by: _lock
        self._merged_keys: set[tuple[str, int]] = set()  # guarded-by: _lock
        # (model, slot) -> [rows emitted] (late wagg partials append)
        self.merged: dict[tuple[str, int], list] = {}  # guarded-by: _merge_lock
        # eager registration: /metrics carries every mesh family (as
        # zeros) the moment a coordinator exists — the dashboard honesty
        # test resolves the mesh panels against this surface
        self._m = {k: (REGISTRY.histogram(*v, buckets=MERGE_SECONDS_BUCKETS)
                       if k == "merge_s"
                       else REGISTRY.gauge(*v) if k in
                       ("members", "epoch", "partitions")
                       else REGISTRY.counter(*v))
                   for k, v in MESH_METRICS.items()}
        self._m["partitions"].set(self.n_partitions)
        self._m["members"].set(0)
        self._m["epoch"].set(0)

    # ---- membership -------------------------------------------------------

    def join(self, member_id: str, provider=None) -> dict:
        """Register (or re-register) a member. Returns {"epoch": e}.
        A rejoin under an id that still owns partitions is treated as
        death-then-join: the old incarnation's carry is promoted and its
        partitions released (it crashed and came back before expiry)."""
        with self._lock:
            old = self._members.get(member_id)
            fold = []
            if old is not None and (old.owned or old.alive):
                # fencing can complete a merge barrier (the promoted
                # carry may be the last missing contribution) — the
                # ready list must reach _run_merges or those windows
                # are popped and silently lost
                fold = self._fence_locked(member_id, "rejoin")
            self._members[member_id] = m = _Member(provider)
            m.last_hb = self._time()
            self._rebalance_locked("join")
            epoch = self.epoch
        if fold:
            self._run_merges(fold)
        return {"epoch": epoch}

    def leave(self, member_id: str) -> None:
        """Graceful leave (after a release/final submission). A member
        leaving while still owning non-final partitions is fenced
        instead — its carry must be promoted and the partitions
        reassigned; finished (final) partitions just release."""
        fold = []
        with self._lock:
            m = self._members.get(member_id)
            if m is None:
                return
            if m.owned and not all(self._final[p] for p in m.owned):
                fold = self._fence_locked(member_id, "leave")
            else:
                self._released |= m.owned
                m.owned = set()
                m.alive = False
                self._carry.pop(member_id, None)
                self._rebalance_locked("leave")
        if fold:
            self._run_merges(fold)

    def fence(self, member_id: str) -> None:
        """Declare a member dead NOW (admin surface; the heartbeat
        timeout calls the same path). Its carry is promoted, partitions
        released, and any later submission from it rejected."""
        fold = None
        with self._lock:
            fold = self._fence_locked(member_id, "death")
        if fold:
            self._run_merges(fold)

    def expire(self, now: Optional[float] = None) -> list[str]:
        """Fence every member whose heartbeat lapsed; returns their ids."""
        now = self._time() if now is None else now
        dead = []
        fold = []
        with self._lock:
            for mid, m in list(self._members.items()):
                if m.alive and now - m.last_hb > self.heartbeat_timeout:
                    fold.extend(self._fence_locked(mid, "death") or [])
                    dead.append(mid)
        if fold:
            self._run_merges(fold)
        return dead

    def _fence_locked(self, member_id: str, reason: str):
        """Death path (caller holds _lock): promote carry into pending,
        release partitions, rebalance. Returns ready merges to run."""
        m = self._members.get(member_id)
        if m is None:
            return []
        m.alive = False
        self._released |= m.owned  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        m.owned = set()
        carry = self._carry.pop(member_id, None)
        if carry:
            self._fold_windows_locked(carry)
        self._rebalance_locked(reason)
        log.warning("mesh member %s fenced (%s); epoch now %d",
                    member_id, reason, self.epoch)
        return self._pop_ready_locked()

    def _rebalance_locked(self, reason: str) -> None:
        self.epoch += 1  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        live = sorted(mid for mid, m in self._members.items() if m.alive)
        self._targets = {mid: set() for mid in live}  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        for p in range(self.n_partitions):
            if live:
                self._targets[live[p % len(live)]].add(p)
        self._m["rebalance"].inc(reason=reason)
        self._m["members"].set(len(live))
        self._m["epoch"].set(self.epoch)

    # ---- heartbeat / assignment ------------------------------------------

    def sync(self, member_id: str) -> dict:
        """Heartbeat + assignment poll. Actions:

        - ``run``    : keep going; ``assign`` carries {partition: resume
                       offset} when ownership was (re)granted this call
        - ``resync`` : owned != target — submit all state with
                       ``release=True``, then sync again to re-acquire
        - ``wait``   : target partitions not yet released by previous
                       owners — idle and sync again
        - ``rejoin`` : unknown or fenced — abandon un-submitted state
                       (the successor replays it) and join() fresh
        """
        self.expire()
        with self._lock:
            m = self._members.get(member_id)
            if m is None or not m.alive:
                return {"epoch": self.epoch, "action": "rejoin",
                        "assign": None}
            m.last_hb = self._time()
            target = self._targets.get(member_id, set())
            if m.owned:
                if m.owned == target:
                    return {"epoch": self.epoch, "action": "run",
                            "assign": None}
                return {"epoch": self.epoch, "action": "resync",
                        "assign": None}
            if target and not (target <= self._released):
                return {"epoch": self.epoch, "action": "wait",
                        "assign": None}
            # acquire the full target set atomically (possibly empty:
            # more members than partitions -> this member idles)
            m.owned = set(target)
            self._released -= target
            assign = {p: self._covered[p] for p in sorted(target)}
            return {"epoch": self.epoch, "action": "run", "assign": assign}

    # ---- submissions ------------------------------------------------------

    def submit(self, member_id: str, payload) -> dict:
        """Accept one member contribution (codec bytes or decoded dict).
        Returns {"ok": True} or {"ok": False, "reason": ...}."""
        if isinstance(payload, (bytes, bytearray)):
            payload = codec.decode(bytes(payload))
        fold = []
        accepted = False
        with self._lock:
            m = self._members.get(member_id)
            if m is None or not m.alive:
                self._m["rejected"].inc(reason="fenced")
                return {"ok": False, "reason": "fenced"}
            m.last_hb = self._time()
            ranges = payload.get("ranges", {})
            for p, rng in ranges.items():
                p = int(p)
                if p not in m.owned or int(rng[0]) != self._covered[p] \
                        or int(rng[1]) < int(rng[0]):
                    # frontier mismatch: protocol violation or a zombie
                    # with stale state — fence, force a clean rejoin
                    self._m["rejected"].inc(reason="range")
                    fold = self._fence_locked(member_id, "death")
                    break
            else:
                fold = self._accept_locked(m, member_id, payload)
                accepted = True
        if fold:
            self._run_merges(fold)
        if accepted:
            return {"ok": True}
        return {"ok": False, "reason": "fenced"}

    def _accept_locked(self, m: _Member, member_id: str, payload: dict):
        for p, rng in payload.get("ranges", {}).items():
            self._covered[int(p)] = int(rng[1])  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        wm = int(payload.get("watermark", 0))
        for p in m.owned:
            if wm > self._wm[p]:
                self._wm[p] = wm  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        flows = int(payload.get("flows", 0))
        if flows:
            self._m["flows"].inc(flows, member=member_id)
        self._m["submit"].inc()
        self._fold_windows_locked({"windows": payload.get("closed", {})})
        open_windows = payload.get("open", {})
        if payload.get("release") or payload.get("final"):
            # the member is resetting (resync) or done: its open state
            # must not sit in a carry nobody will promote
            self._fold_windows_locked({"windows": open_windows})
            self._carry.pop(member_id, None)
        else:
            self._carry[member_id] = {"windows": open_windows}  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        if payload.get("final"):
            for p in m.owned:
                self._final[p] = True  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        if payload.get("release"):
            self._released |= m.owned  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
            m.owned = set()
        return self._pop_ready_locked()

    def _fold_windows_locked(self, contribution: dict) -> None:
        """Fold {slot: {model: payload}} into the pending barrier. A
        contribution for an already-merged window is LATE: exact wagg
        partials are emitted as additional rows (the single-worker late
        semantics — merging sinks combine them); late sketch state has
        no exact merge target left and is dropped, counted."""
        for slot, models in contribution.get("windows", {}).items():
            slot = int(slot)
            for name, payload in models.items():
                if name not in self._by_name:
                    continue
                key = (name, slot)
                if key in self._merged_keys:
                    self._m["late"].inc(model=name)
                    if payload.get("kind") == "wagg":
                        self._pending.setdefault(key, []).append(payload)
                        self._merged_keys.discard(key)  # re-merge partial
                    continue
                self._pending.setdefault(key, []).append(payload)

    def _pop_ready_locked(self) -> list:
        """Detach every pending window whose barrier condition holds:
        all partitions final, or watermark past slot + window (+
        lateness). Marks them merged so later contributions register as
        late."""
        ready = []
        for key in sorted(self._pending):
            name, slot = key
            spec = self._by_name[name]
            limit = slot + spec.window_seconds + spec.allowed_lateness
            if all(self._final[p] or self._wm[p] >= limit
                   for p in range(self.n_partitions)):
                ready.append((name, slot, self._pending.pop(key)))
                self._merged_keys.add(key)
        return ready

    # ---- merging ----------------------------------------------------------

    def _run_merges(self, ready: list) -> None:
        """Fold + extract + emit each detached window. Runs on the
        submitting thread with NO coordinator lock held (merge math and
        sink writes must not serialize member heartbeats)."""
        for name, slot, payloads in ready:
            t0 = time.perf_counter()
            spec = self._by_name[name]
            rows = self._merge_one(spec, slot, payloads)
            for sink in self.sinks:
                sink.write(name, rows)
            with self._merge_lock:
                self.merged.setdefault((name, slot), []).append(rows)
                # bounded retention (newest slots win); _merged_keys is
                # NOT evicted — late-contribution detection must keep
                # working for windows whose rows have aged out
                slots = sorted(s for n, s in self.merged if n == name)
                for s in slots[:-MERGED_LEDGER_SLOTS]:
                    del self.merged[(name, s)]
            self._m["merge_s"].observe(time.perf_counter() - t0)
            self._m["merged"].inc(model=name)
            log.info("mesh merged window model=%s slot=%d contribs=%d",
                     name, slot, len(payloads))

    @staticmethod
    def _merge_one(spec: ModelSpec, slot: int, payloads: list) -> dict:
        if spec.kind == "wagg":
            from ..models.window_agg import rows_from_stores

            store = merge_ops.merge_wagg(payloads)
            return rows_from_stores(spec.config, [(slot, store)])
        if spec.kind == "hh":
            merged = merge_ops.merge_hh(payloads, spec.config)
            return merge_ops.hh_top_rows(merged, spec.config, spec.k, slot)
        totals = merge_ops.merge_dense(payloads)
        return merge_ops.dense_top_rows(totals, spec.config, spec.k, slot)

    # ---- live queries (mesh-aware /topk) ----------------------------------

    def query_topk(self, model: Optional[str] = None,
                   k: Optional[int] = None) -> dict:
        """Fan the query to every live member's state provider and
        answer from the merged open-window view — the network-wide
        equivalent of QueryServer._topk's single-worker answer."""
        spec = None
        if model:
            spec = self._by_name.get(model)
            if spec is None or spec.kind == "wagg":
                raise KeyError(f"no mesh top-K model named {model!r}")
        else:
            # default selection mirrors the single-worker QueryServer:
            # the first model with a top-K surface, dense-backed included
            spec = next((s for s in self.specs
                         if s.kind in ("hh", "dense")), None)
            if spec is None:
                raise KeyError("no top-K model configured")
        with self._lock:
            providers = [(mid, m.provider)
                         for mid, m in self._members.items()
                         if m.alive and m.provider is not None]
            # NOT the carries: every carry belongs to a LIVE member
            # (death promotes them into _pending), and a live member's
            # provider state is a superset of its own carry — folding
            # both would double-count everything since its last
            # submission. What CAN be missing from the providers is a
            # dead member's promoted-but-unmerged contribution: that
            # sits in _pending, disjoint from its successor's state
            # (the successor resumed at the covered frontier).
            pending = {slot: list(payloads)
                       for (name, slot), payloads in self._pending.items()
                       if name == spec.name}
        states: list[tuple[int, dict]] = []
        for mid, provider in providers:
            try:
                res = provider(spec.name)
            except (OSError, ValueError) as e:
                # a dying-but-not-yet-fenced member must DEGRADE the
                # answer (its un-submitted open rows are missing until
                # the fence promotes/replays), never black out /topk
                log.warning("mesh /topk: member %s state fetch failed "
                            "(%s); answering without it", mid, e)
                continue
            if isinstance(res, (bytes, bytearray)):
                res = codec.decode(bytes(res))
            if res and res.get("slot") is not None:
                states.append((int(res["slot"]), res["payload"]))
        slots = [s for s, _ in states] + list(pending)
        if not slots:
            return {"model": spec.name, "window_start": None, "rows": []}
        slot = max(slots)
        payloads = [p for s, p in states if s == slot] + \
            pending.get(slot, [])
        from ..sink.base import rows_to_records

        kk = k or spec.k or spec.config.capacity
        if spec.kind == "hh":
            merged = merge_ops.merge_hh(payloads, spec.config)
            rows = merge_ops.hh_top_rows(merged, spec.config, kk, slot)
        else:
            rows = merge_ops.dense_top_rows(
                merge_ops.merge_dense(payloads), spec.config, kk, slot)
        return {"model": spec.name, "window_start": slot,
                "rows": rows_to_records(rows)}

    # ---- introspection ----------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            return {
                "epoch": self.epoch,
                "partitions": self.n_partitions,
                "members": {
                    mid: {"alive": m.alive,
                          "owned": sorted(m.owned),
                          "target": sorted(self._targets.get(mid, ()))}
                    for mid, m in self._members.items()
                },
                "covered": list(self._covered),
                "watermarks": list(self._wm),
                "final": list(self._final),
                "pending_windows": sorted(
                    f"{n}:{s}" for n, s in self._pending),
            }

    def merged_rows(self, name: str, slot: Optional[int] = None) -> list:
        """Emitted merged rows for one model (all slots, or one) — the
        test/debug ledger."""
        with self._merge_lock:
            if slot is not None:
                return list(self.merged.get((name, slot), []))
            return [rows for (n, _), rs in sorted(self.merged.items())
                    if n == name for rows in rs]
