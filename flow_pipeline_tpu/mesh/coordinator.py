"""flowmesh coordinator: membership, epoch-fenced partition ownership,
and the window-close merge barrier.

One coordinator owns the authoritative offset frontier of every bus
partition and merges per-worker window state into the network-wide
result (mesh/merge.py). The protocol is a miniature Kafka group
coordinator with the merge barrier fused in:

- **Membership**: members join, then heartbeat via ``sync()``. A member
  that misses ``heartbeat_timeout`` is fenced (declared dead); its
  partitions are released and the target assignment recomputed
  (epoch + 1). ``fence()`` is the same path as an admin surface (and
  the deterministic lever the churn tests use).

- **Ownership**: partitions are assigned round-robin over the sorted
  live member ids — the same deterministic rule as
  ``parallel.multihost.reassign_lost_partitions`` (every observer can
  recompute the map). A member whose owned set differs from its target
  is told to RESYNC: it submits all of its state with ``release``,
  drops its worker, and re-acquires its target set; a new owner
  acquires a partition only after the previous owner released it (or
  died), always resuming from the coordinator's ``covered`` frontier.

- **Exactness**: a submission carries, per owned partition, the offset
  range it consumed since its last accepted submission, and the state
  of every window those rows touched (closed windows as final
  contributions, the open window as a replaceable CARRY). Accept
  requires each range to extend the frontier exactly; anything never
  accepted is replayed by the successor from the frontier, anything
  accepted is in exactly one contribution. Zombies are fenced: a
  submission from a dead-declared member is rejected, so its
  un-accepted rows are replayed by the new owner and never double
  count. A window (model, slot) merges once every partition's
  watermark passes slot + window (+ lateness) or is final — at which
  point monoid-folding ALL its contributions reproduces the
  single-worker oracle exactly (tests/test_mesh.py).
"""

from __future__ import annotations

# flowlint: lock-checked
# (member-facing methods run on N member threads plus HTTP handler
# threads; every mutable attribute declares its lock below. Sink writes
# and merge math deliberately run OUTSIDE the locks — only the ready-set
# pop and the merged-rows ledger are serialized.)
# flowlint: durable-checked
# (the journal call sites: every append under _lock must reach a
# _journal.sync() barrier before the caller acks — in-method, or via
# the annotated group-commit seam the public callers all cross)

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from ..families import registry
from ..obs import REGISTRY, get_logger
from ..obs.audit import (audit_report, publish_report,
                         register_audit_metrics)
from ..obs.buildinfo import publish_build_info
from ..obs.trace import TRACER
from . import codec

log = get_logger("mesh")

# Buckets for the window-merge wall-time histogram (seconds): sub-ms
# in-process folds up to multi-second cross-network merges.
MERGE_SECONDS_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# Buckets for the meshscope SLO latencies (seconds): barrier waits and
# submit->merge intervals span "every shard already past the close"
# (ms) to "one shard stalled most of a window" (minutes).
BARRIER_SECONDS_BUCKETS = (
    0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0,
)

# Buckets for rebalance durations (trigger -> every partition owned
# again): in-process handoffs are ms; cross-process ones ride the
# heartbeat cadence.
REBALANCE_SECONDS_BUCKETS = (
    0.01, 0.05, 0.25, 1.0, 2.5, 5.0, 15.0, 60.0, 300.0,
)

# Merged-rows ledger retention, per model: the newest slots kept for
# queries/tests/debugging. The SINKS are the durable home of merged
# output; an unbounded ledger on an endless stream is a slow OOM
# (days of 5-minute windows accumulate every historical row set).
MERGED_LEDGER_SLOTS = 16

# Lineage-ledger retention, per model (same discipline as the merged
# ledger, looser bound: a lineage record is a few hundred bytes of
# metadata, not row sets, so more history fits the same budget).
LINEAGE_SLOTS = 64

# Merged-audit-cohort retention, per model (sketchwatch): the newest
# slots' merged sampled-exact counters, kept for the mesh-vs-oracle
# bit-equality gate and /query/audit debugging. Cohorts are ~1/256 of
# keys — small, but still bounded like every other ledger here.
AUDIT_LEDGER_SLOTS = 16

# Metric name/help specs live here once; the deploy honesty test
# resolves the Grafana mesh panels against a constructed coordinator.
MESH_METRICS = {
    "members": ("mesh_members", "live flowmesh members"),
    "epoch": ("mesh_epoch", "current flowmesh assignment epoch"),
    "partitions": ("mesh_partitions", "bus partitions under mesh control"),
    "rebalance": ("mesh_rebalance_total",
                  "mesh rebalances (label: reason=join|leave|death)"),
    "merged": ("mesh_windows_merged_total",
               "windows merged network-wide (label: model)"),
    "merge_s": ("mesh_merge_seconds",
                "window-close merge wall time (decode+fold+extract)"),
    "flows": ("mesh_member_flows_total",
              "flows ingested per member (label: member)"),
    "submit": ("mesh_submit_total", "accepted member submissions"),
    "rejected": ("mesh_submit_rejected_total",
                 "rejected member submissions (label: reason)"),
    "late": ("mesh_late_contribution_total",
             "contributions that arrived after their window merged "
             "(label: model)"),
    # meshscope SLO families (r13): mesh-wide freshness + merge-path
    # latency decomposition
    "commit_wm": ("mesh_commit_watermark_seconds",
                  "mesh-wide event-time watermark: min over live "
                  "members' reported watermarks"),
    "member_wm": ("mesh_member_watermark_seconds",
                  "per-member event-time watermark (label: member)"),
    "wm_skew": ("mesh_watermark_skew_seconds",
                "per-member watermark lag behind the mesh leader "
                "(label: member) — the stalled-shard signal"),
    "barrier_s": ("mesh_barrier_wait_seconds",
                  "window first-contribution -> barrier-release wait"),
    "sub2merge_s": ("mesh_submit_to_merge_seconds",
                    "contribution accept -> network-wide merge latency"),
    "rebalance_s": ("mesh_rebalance_duration_seconds",
                    "rebalance trigger -> every partition owned again "
                    "(label: reason)"),
    # flowchaos journal families (r17): write-ahead durability health.
    # Registered eagerly like every other mesh family so the dashboard/
    # alert honesty tests resolve them against a constructed coordinator
    # whether or not a journal is configured.
    "journal_records": ("mesh_journal_records_total",
                        "coordinator WAL records appended (label: kind="
                        "sub|fence|epoch|merged)"),
    "journal_unsynced": ("mesh_journal_unsynced_records",
                         "journal records appended but not yet fsynced "
                         "(group commit drains this to 0 at every ack)"),
    "journal_lag": ("mesh_journal_lag_seconds",
                    "age of the oldest unfsynced journal record "
                    "(0 = clean; sustained > 0 means acks are running "
                    "ahead of durability)"),
    "journal_bytes": ("mesh_journal_bytes",
                      "coordinator WAL file size on disk — compaction "
                      "(checkpoint + truncate at merged-window "
                      "boundaries) is what keeps this bounded at "
                      "production cadence"),
}

# Which MESH_METRICS keys register as what (everything else: counter).
_MESH_GAUGES = frozenset(
    {"members", "epoch", "partitions", "commit_wm", "member_wm",
     "wm_skew", "journal_unsynced", "journal_lag", "journal_bytes"})
_MESH_HISTOGRAMS = {
    "merge_s": MERGE_SECONDS_BUCKETS,
    "barrier_s": BARRIER_SECONDS_BUCKETS,
    "sub2merge_s": BARRIER_SECONDS_BUCKETS,
    "rebalance_s": REBALANCE_SECONDS_BUCKETS,
}


@dataclass(frozen=True)
class ModelSpec:
    """One mergeable model: name, kind tag, frozen config, extraction k,
    window cadence. Built from a worker's models dict so the coordinator
    merges exactly what the members compute."""

    name: str
    kind: str  # "wagg" | "hh" | "dense" | "spread"
    config: Any
    k: int
    window_seconds: int
    allowed_lateness: int = 0


def spec_from_models(models: dict) -> tuple[ModelSpec, ...]:
    """Derive the mergeable model specs from a models dict (the same
    dict cli._build_models produces). DDoS detectors are deliberately
    absent: their per-dst rates are split across shards by the key
    hash, so mesh mode keeps detection per-shard (the HashPipe model —
    per-shard detection) and alerts flow through member sinks."""
    from ..engine.windowed import WindowedHeavyHitter
    from ..models.window_agg import WindowAggregator

    out = []
    for name, m in models.items():
        if isinstance(m, WindowAggregator):
            out.append(ModelSpec(
                name, "wagg", m.config, 0, m.config.window_seconds,
                m.config.allowed_lateness))
        elif isinstance(m, WindowedHeavyHitter):
            fam = registry.family_for_snapshot(m.model.snapshot_kind)
            kind = fam.kind if fam is not None else "dense"
            out.append(ModelSpec(name, kind, m.config, m.k,
                                 m.window_seconds))
    return tuple(out)


class _Member:
    __slots__ = ("alive", "last_hb", "owned", "provider", "trace_url",
                 "clock_offset", "clock_rtt", "watermark", "last_sub")

    def __init__(self, provider=None, trace_url=None):
        self.alive = True
        self.last_hb = 0.0
        self.owned: set[int] = set()
        # newest accepted submission id (span.sub) from this incarnation
        # — the lost-ack retry dedupe key. 0 = nothing accepted yet
        # (member ids are minted from 1); a rejoin builds a fresh
        # _Member, and a member object's _sub_seq is monotone across its
        # own rejoins, so ids never run backwards within an incarnation.
        self.last_sub = 0
        self.provider = provider  # callable(model)->payload | state URL
        # meshscope: the member's /debug/trace URL (HTTP mesh; None
        # in-process — everything already records into one TRACER)
        self.trace_url = trace_url
        # member_clock - coordinator_clock, heartbeat-estimated (NTP
        # midpoint, min-RTT sample; mesh/scope.py); None until the
        # member's first clock report
        self.clock_offset: Optional[float] = None
        self.clock_rtt: float = 0.0
        # newest event-time watermark this member reported
        self.watermark: int = 0


class MeshCoordinator:
    """Coordinator + merge engine. Duck-type shared with
    mesh.server.RemoteCoordinator so members run identically in-process
    and over HTTP."""

    def __init__(self, specs: Sequence[ModelSpec], n_partitions: int,
                 sinks: Sequence[Any] = (),
                 heartbeat_timeout: float = 5.0,
                 time_fn: Callable[[], float] = time.monotonic,
                 journal: Optional[str] = None,
                 journal_compact_bytes: int = 64 << 20):
        self.specs = tuple(specs)
        self._by_name = {s.name: s for s in self.specs}
        self.n_partitions = int(n_partitions)
        self.sinks = list(sinks)
        self.heartbeat_timeout = heartbeat_timeout
        self._time = time_fn
        # flowlint: unguarded -- the locks themselves; bound once
        self._lock = threading.Lock()
        # flowlint: unguarded -- bound once (guards only the merged-rows ledger)
        self._merge_lock = threading.Lock()
        self.epoch = 0  # guarded-by: _lock
        self._members: dict[str, _Member] = {}  # guarded-by: _lock
        self._targets: dict[str, set[int]] = {}  # guarded-by: _lock
        self._released: set[int] = set(range(self.n_partitions))  # guarded-by: _lock
        self._covered = [0] * self.n_partitions  # guarded-by: _lock
        self._wm = [0] * self.n_partitions  # guarded-by: _lock
        self._final = [False] * self.n_partitions  # guarded-by: _lock
        # (model, slot) -> list of decoded payloads awaiting the barrier
        self._pending: dict[tuple[str, int], list] = {}  # guarded-by: _lock
        # member -> latest open-window state {slot: {model: payload}};
        # replaced on every accepted submission, promoted on death
        self._carry: dict[str, dict] = {}  # guarded-by: _lock
        self._merged_keys: set[tuple[str, int]] = set()  # guarded-by: _lock
        # windows popped off the barrier but not yet emitted+journaled:
        # _pop_ready_locked marks a window merged BEFORE the lock-free
        # merge/sink-emit runs, so a checkpoint taken in that gap would
        # record it merged while its rows exist nowhere durable —
        # compaction defers while this is non-empty
        self._inflight_keys: set[tuple[str, int]] = set()  # guarded-by: _lock
        # (model, slot) -> [rows emitted] (late wagg partials append)
        self.merged: dict[tuple[str, int], list] = {}  # guarded-by: _merge_lock
        # meshscope lineage ledger: per (model, slot), who contributed
        # what and when. Pending records ride the merge barrier next to
        # _pending; merged records move to _lineage_done (retention-
        # bounded like the merged-rows ledger — LINEAGE_SLOTS). Late
        # annotations that land in the pop->seal gap (the merge runs
        # lock-free between them) buffer in _lineage_orphans until the
        # seal drains them.
        self._lineage_pending: dict[tuple[str, int], dict] = {}  # guarded-by: _lock
        self._lineage_done: dict[tuple[str, int], dict] = {}  # guarded-by: _lock
        self._lineage_orphans: dict[tuple[str, int], list] = {}  # guarded-by: _lock
        # rebalance-duration timeline: (wall t0, reason) of the oldest
        # unsettled rebalance; cleared when every live member owns
        # exactly its target set again
        self._rebalance_start: Optional[tuple[float, str]] = None  # guarded-by: _lock
        # flowserve hook (serve.MeshServePublisher.attach): a completed
        # merge wakes the publisher so the MERGED snapshot refreshes —
        # readers then never fan out to members per query.
        # flowlint: unguarded -- bound once at wiring (before members join), then read on merge threads only
        self.serve = None
        # eager registration: /metrics carries every mesh family (as
        # zeros) the moment a coordinator exists — the dashboard honesty
        # test resolves the mesh panels against this surface
        self._m = {k: (REGISTRY.histogram(*v,
                                          buckets=_MESH_HISTOGRAMS[k])
                       if k in _MESH_HISTOGRAMS
                       else REGISTRY.gauge(*v) if k in _MESH_GAUGES
                       else REGISTRY.counter(*v))
                   for k, v in MESH_METRICS.items()}
        self._m["partitions"].set(self.n_partitions)
        self._m["members"].set(0)
        self._m["epoch"].set(0)
        # sketchwatch: merged-cohort audit state. Metrics registered
        # eagerly (the coordinator process publishes the NETWORK-WIDE
        # sketch_* families; members keep their own processes' series).
        # flowlint: unguarded -- registered once here, read-only after
        self._audit_m = register_audit_metrics()
        # (model, slot) -> merged audit partial {keys, vals, evictions}
        self.audit_merged: dict[tuple[str, int], dict] = {}  # guarded-by: _merge_lock
        # model -> newest JSON-safe network-wide audit report
        self._audit_reports: dict[str, dict] = {}  # guarded-by: _merge_lock
        # the hh_sketch label reflects the family the mesh MERGES —
        # dashboards must be able to tell which sketch produced the
        # network-wide series (bench artifacts join against it)
        hh_modes = {getattr(s.config, "hh_sketch", "table")
                    for s in self.specs if s.kind == "hh"}
        publish_build_info(
            "coordinator",
            hh_sketch=("none" if not hh_modes
                       else "table" if hh_modes == {"table"}
                       else "invertible" if hh_modes == {"invertible"}
                       else "mixed"))
        # flowchaos write-ahead journal (-mesh.journal=<dir>): accepted
        # submissions, fences, epoch bumps and merged-window keys become
        # durable; a restarted coordinator recovers its frontier/epoch/
        # ledger from them (mesh/journal.py states the contract).
        # flowlint: unguarded -- bound once here; the journal carries its own lock
        self._journal = None
        # compaction trigger (r18): checkpoint + truncate once the WAL
        # crosses this size, checked at merged-window boundaries (the
        # point where carries/subs become provably superseded). 0
        # disables the automatic trigger; compact_journal() stays
        # callable either way.
        self.journal_compact_bytes = int(journal_compact_bytes)
        if journal:
            from .journal import CoordinatorJournal

            self._journal = CoordinatorJournal(journal, metrics={
                "records": self._m["journal_records"],
                "unsynced": self._m["journal_unsynced"],
                "lag": self._m["journal_lag"],
                "bytes": self._m["journal_bytes"],
            })
            with self._lock:
                ready = self._recover_locked()
            self._journal.sync()
            if ready:
                self._run_merges(ready)

    # ---- membership -------------------------------------------------------

    def join(self, member_id: str, provider=None,
             trace_url: Optional[str] = None) -> dict:
        """Register (or re-register) a member. Returns {"epoch": e}.
        A rejoin under an id that still owns partitions is treated as
        death-then-join: the old incarnation's carry is promoted and its
        partitions released (it crashed and came back before expiry)."""
        fenced = False
        with self._lock:
            old = self._members.get(member_id)
            fold = []
            if old is not None and (old.owned or old.alive):
                # fencing can complete a merge barrier (the promoted
                # carry may be the last missing contribution) — the
                # ready list must reach _run_merges or those windows
                # are popped and silently lost
                fold = self._fence_locked(member_id, "rejoin")
                fenced = True
            self._members[member_id] = m = _Member(provider, trace_url)
            m.last_hb = self._time()
            self._rebalance_locked("join")
            epoch = self.epoch
        if self._journal is not None:
            self._journal.sync()
        if fold:
            self._run_merges(fold)
        if fenced:
            # crash-restart before expiry: the old incarnation died
            # without a dump — leave the flight-recorder breadcrumb
            # the post-mortem needs (same contract as a worker error)
            self._dump_flight(f"member {member_id} rejoined while "
                              "fenced-alive (crash-restart)")
        return {"epoch": epoch}

    def leave(self, member_id: str) -> None:
        """Graceful leave (after a release/final submission). A member
        leaving while still owning non-final partitions is fenced
        instead — its carry must be promoted and the partitions
        reassigned; finished (final) partitions just release."""
        fold = []
        with self._lock:
            m = self._members.get(member_id)
            if m is None:
                return
            if m.owned and not all(self._final[p] for p in m.owned):
                fold = self._fence_locked(member_id, "leave")
            else:
                self._released |= m.owned
                m.owned = set()
                m.alive = False
                self._carry.pop(member_id, None)
                self._rebalance_locked("leave")
                # same stale-series discipline as the fence path: a
                # departed laggard's frozen skew must not keep paging
                self._m["member_wm"].remove(member=member_id)
                self._m["wm_skew"].remove(member=member_id)
                self._m["sub2merge_s"].remove(member=member_id)
                self._publish_watermarks_locked()
        if self._journal is not None:
            self._journal.sync()
        if fold:
            self._run_merges(fold)

    def fence(self, member_id: str) -> None:
        """Declare a member dead NOW (admin surface; the heartbeat
        timeout calls the same path). Its carry is promoted, partitions
        released, and any later submission from it rejected."""
        fold = []
        fenced = False
        with self._lock:
            m = self._members.get(member_id)
            fenced = m is not None and (m.alive or bool(m.owned))
            fold = self._fence_locked(member_id, "death")
        if self._journal is not None:
            self._journal.sync()
        if fold:
            self._run_merges(fold)
        if fenced:
            self._dump_flight(f"member {member_id} fenced")

    def expire(self, now: Optional[float] = None) -> list[str]:
        """Fence every member whose heartbeat lapsed; returns their ids."""
        now = self._time() if now is None else now
        dead = []
        fold = []
        with self._lock:
            for mid, m in list(self._members.items()):
                if m.alive and now - m.last_hb > self.heartbeat_timeout:
                    fold.extend(self._fence_locked(mid, "death") or [])
                    dead.append(mid)
        if dead and self._journal is not None:
            self._journal.sync()
        if fold:
            self._run_merges(fold)
        if dead:
            self._dump_flight(
                f"member(s) {', '.join(dead)} expired (heartbeat)")
        return dead

    def _fence_locked(self, member_id: str, reason: str):
        """Death path (caller holds _lock): promote carry into pending,
        release partitions, rebalance. Returns ready merges to run."""
        m = self._members.get(member_id)
        if m is None:
            return []
        now = time.time()
        m.alive = False
        self._released |= m.owned  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        m.owned = set()
        if self._journal is not None:
            # the fence (and the carry promotion it implies) must replay
            # at this exact point in the record order, or a recovered
            # coordinator would promote an already-promoted carry twice
            # durable: group-commit=fence -- *_locked helper: every public caller (join/leave/fence/expire/submit) calls _journal.sync() after releasing _lock, before its ack
            self._journal.append("fence", {"member": member_id})
        carry = self._carry.pop(member_id, None)
        TRACER.record("mesh_fence", now, time.time(), member=member_id,
                      reason=reason, promoted=bool(carry))
        if carry:
            span = carry.get("span") or {}
            windows = carry.get("windows", {})
            self._fold_windows_locked(
                windows, member=member_id, span=span,
                ranges=carry.get("ranges"), accepted=now,
                kind="carry-promoted")
            TRACER.record("mesh_carry_promotion", now, time.time(),
                          member=member_id, sub=span.get("sub"),
                          slots=sorted(int(s) for s in windows))
        self._rebalance_locked(reason)
        # the mesh watermark/skew must re-derive over the LIVE set —
        # a dead laggard no longer holds the min down — and the dead
        # member's own series must go away, or its frozen last skew
        # reads as an eternally stalled shard on the dashboards
        self._m["member_wm"].remove(member=member_id)
        self._m["wm_skew"].remove(member=member_id)
        self._m["sub2merge_s"].remove(member=member_id)
        self._publish_watermarks_locked()
        log.warning("mesh member %s fenced (%s); epoch now %d",
                    member_id, reason, self.epoch)
        return self._pop_ready_locked()

    def _rebalance_locked(self, reason: str) -> None:
        self.epoch += 1  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        if self._journal is not None:
            # durable: group-commit=fence -- *_locked helper: every public caller (join/leave/fence/expire) calls _journal.sync() after releasing _lock, before its ack
            self._journal.append("epoch", {"epoch": self.epoch,
                                           "reason": reason})
        live = sorted(mid for mid, m in self._members.items() if m.alive)
        self._targets = {mid: set() for mid in live}  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        for p in range(self.n_partitions):
            if live:
                self._targets[live[p % len(live)]].add(p)
        self._m["rebalance"].inc(reason=reason)
        self._m["members"].set(len(live))
        self._m["epoch"].set(self.epoch)
        # rebalance-duration timeline: the clock starts at the FIRST
        # unsettled trigger and keeps its original reason if another
        # rebalance lands mid-flight (the duration then measures the
        # whole disturbance, which is what an operator pages on)
        if self._rebalance_start is None:
            self._rebalance_start = (time.time(), reason)  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        self._check_rebalance_settled_locked()

    def _check_rebalance_settled_locked(self) -> None:
        """Close the rebalance timeline once every live member owns
        exactly its target set (and every partition is owned)."""
        if self._rebalance_start is None:
            return
        live = [(mid, m) for mid, m in self._members.items() if m.alive]
        if not live:
            return
        owned = sum(len(m.owned) for _, m in live)
        if owned != self.n_partitions:
            return
        if any(m.owned != self._targets.get(mid, set())
               for mid, m in live):
            return
        t0, reason = self._rebalance_start
        self._rebalance_start = None  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        now = time.time()
        self._m["rebalance_s"].observe(now - t0, reason=reason)
        TRACER.record("mesh_rebalance", t0, now, reason=reason,
                      epoch=self.epoch)

    def _dump_flight(self, why: str) -> None:
        """Flight-recorder dump on a coordinator-side death/zombie event
        (fence, expiry, crash-restart rejoin, rejected submission): the
        member that died cannot leave its own breadcrumb, so the
        coordinator's ring — which holds the protocol spans around the
        event, including the rejected submission's span context — is
        the post-mortem. Never raises; no-op when tracing is off."""
        path = TRACER.dump_on_error("coordinator")
        if path:
            log.warning("meshscope: %s; flight recorder dumped to %s",
                        why, path)

    # ---- journal recovery (flowchaos) -------------------------------------

    def _recover_locked(self):
        """Rebuild frontier/epoch/pending/carries/merged-keys by
        replaying the journal through the live fold paths (caller holds
        _lock; runs once, from __init__). Returns the ready merges to
        run lock-free: windows whose barrier had passed but whose
        ``merged`` record never landed re-merge and re-emit here."""
        n = 0
        for kind, meta, blob in self._journal.replay():
            n += 1
            if kind == "chk":
                # a compaction checkpoint: the full recoverable state
                # at the moment of compaction — everything before it
                # was folded in; later records replay on top
                self._restore_checkpoint_locked(codec.decode(blob))
            elif kind == "sub":
                self._replay_submission_locked(meta["member"],
                                               codec.decode(blob))
            elif kind == "fence":
                self._replay_fence_locked(meta["member"])
            elif kind == "epoch":
                if int(meta["epoch"]) > self.epoch:
                    self.epoch = int(meta["epoch"])  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
            elif kind == "merged":
                # merged AND emitted pre-crash: its contributions must
                # not re-emit — pop them; the key stays remembered so
                # late contributions for it keep registering late
                key = (meta["model"], int(meta["slot"]))
                self._pending.pop(key, None)
                self._lineage_pending.pop(key, None)
                self._merged_keys.add(key)
        if n == 0:
            return []
        # the old incarnation's members are all presumed dead: promote
        # every remaining carry (journaling those fences so a SECOND
        # crash replays identically) and bump the epoch. The members
        # themselves are simply unknown to this incarnation — their next
        # sync gets ``rejoin``, they abandon un-acked state and replay
        # from the recovered frontier: the same zombie/rejoin machinery
        # (and the same exactness argument) as a worker death.
        for member in sorted(self._carry):
            # durable: group-commit=fence -- recovery-time records; __init__ calls _journal.sync() right after _recover_locked returns, before any member traffic (fence() names the same barrier)
            self._journal.append("fence", {"member": member})
            self._replay_fence_locked(member)
        self.epoch += 1  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        # durable: group-commit=fence -- recovery-time record; __init__ calls _journal.sync() right after _recover_locked returns, before any member traffic (fence() names the same barrier)
        self._journal.append("epoch", {"epoch": self.epoch,
                                       "reason": "recovery"})
        self._m["epoch"].set(self.epoch)
        log.warning("mesh coordinator recovered from journal: %d "
                    "records, epoch now %d, frontier %s",
                    n, self.epoch, self._covered)
        return self._pop_ready_locked()

    def _replay_submission_locked(self, member: str, payload: dict) -> None:
        """One journaled accepted submission, re-applied. Mirrors
        ``_accept_locked`` minus membership/metrics: ranges were
        validated before the record was written, and a submission's
        ranges cover exactly its owned set."""
        span = payload.get("span") or {}
        ranges = {int(p): [int(r[0]), int(r[1])]
                  for p, r in payload.get("ranges", {}).items()}
        for p, rng in ranges.items():
            if rng[1] > self._covered[p]:
                self._covered[p] = rng[1]  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        wm = int(payload.get("watermark", 0))
        for p in ranges:
            if wm > self._wm[p]:
                self._wm[p] = wm  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        self._fold_windows_locked(payload.get("closed", {}),
                                  member=member, span=span, ranges=ranges,
                                  kind="closed")
        open_windows = payload.get("open", {})
        if payload.get("release") or payload.get("final"):
            self._fold_windows_locked(open_windows, member=member,
                                      span=span, ranges=ranges,
                                      kind="final-open")
            self._carry.pop(member, None)
        else:
            self._carry[member] = {"windows": open_windows,  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
                                   "span": span, "ranges": ranges}
        if payload.get("final"):
            for p in ranges:
                self._final[p] = True  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)

    def _checkpoint_state_locked(self) -> dict:
        """The journal-compaction checkpoint: exactly the state
        ``_recover_locked`` rebuilds by replay — the offset frontier,
        watermarks, finality, epoch, the CURRENT carries (every
        superseded carry envelope is dropped here: this is the 379
        MB -> small lever), the pending barrier contributions, and the
        merged-window keys late detection needs. Lineage/metrics are
        deliberately NOT durable (same contract as uncompacted replay,
        which never rebuilt them either)."""
        return {
            "v": 1,
            "epoch": int(self.epoch),
            "covered": [int(x) for x in self._covered],
            "wm": [int(x) for x in self._wm],
            "final": [bool(x) for x in self._final],
            "carry": self._carry,
            "pending": self._pending,
            "merged_keys": sorted([list(k) for k in self._merged_keys]),
        }

    def _restore_checkpoint_locked(self, state: dict) -> None:
        self._covered = [int(x) for x in state["covered"]]  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        self._wm = [int(x) for x in state["wm"]]  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        self._final = [bool(x) for x in state["final"]]  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        if int(state["epoch"]) > self.epoch:
            self.epoch = int(state["epoch"])  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        self._carry = {m: dict(c) for m, c in state["carry"].items()}  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        self._pending = {(str(k[0]), int(k[1])): list(v)  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
                         for k, v in state["pending"].items()}
        self._merged_keys = {(str(m), int(s))  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
                             for m, s in state["merged_keys"]}

    def compact_journal(self) -> bool:
        """Checkpoint + truncate the WAL NOW (r17's named follow-on).
        Runs under the coordinator lock so no append can race into the
        about-to-be-replaced file; the journal swap is atomic and
        fsynced (mesh/journal.py states the crash-safety argument).
        Returns whether a compaction ran — it DEFERS (False) while any
        window is popped-but-unemitted on another submit thread: such a
        window is already in ``_merged_keys`` but its rows are in no
        sink and its ``merged`` record unwritten, so a checkpoint taken
        now would truncate the ``sub`` records recovery needs to
        re-merge it (the trigger simply fires again at the next merge
        boundary)."""
        if self._journal is None:
            return False
        with self._lock:
            if self._inflight_keys:
                return False
            state = self._checkpoint_state_locked()
            self._journal.compact({"epoch": int(self.epoch)},
                                  codec.encode(state))
        return True

    def _maybe_compact(self) -> None:
        """Merged-window-boundary compaction trigger: every superseded
        carry/sub envelope up to this barrier is now dead weight, so
        once the WAL crosses the size threshold, fold it into one
        checkpoint record."""
        if self._journal is None or self.journal_compact_bytes <= 0:
            return
        if self._journal.size_bytes() >= self.journal_compact_bytes:
            self.compact_journal()

    def _replay_fence_locked(self, member: str) -> None:
        """One journaled fence, re-applied: promote the member's carry
        into the pending barrier exactly as ``_fence_locked`` did live."""
        carry = self._carry.pop(member, None)
        if carry:
            self._fold_windows_locked(
                carry.get("windows", {}), member=member,
                span=carry.get("span") or {},
                ranges=carry.get("ranges"), kind="carry-promoted")

    def close(self) -> None:
        """Release the journal (final fsync + file close). The
        coordinator has no other owned resources; safe to call twice."""
        if self._journal is not None:
            self._journal.close()

    # ---- heartbeat / assignment ------------------------------------------

    def sync(self, member_id: str, clock: Optional[dict] = None) -> dict:
        """Heartbeat + assignment poll. Actions:

        - ``run``    : keep going; ``assign`` carries {partition: resume
                       offset} when ownership was (re)granted this call
        - ``resync`` : owned != target — submit all state with
                       ``release=True``, then sync again to re-acquire
        - ``wait``   : target partitions not yet released by previous
                       owners — idle and sync again
        - ``rejoin`` : unknown or fenced — abandon un-submitted state
                       (the successor replays it) and join() fresh

        ``clock`` is the member's heartbeat-estimated clock report
        ({"offset": coordinator-member s, "rtt": s}, mesh/scope.py);
        every response carries ``now`` (this coordinator's wall clock)
        so the member can keep estimating. Both are what lets
        ``/debug/trace`` emit ONE clock-aligned mesh trace."""
        self.expire()
        now_wall = time.time()
        with self._lock:
            m = self._members.get(member_id)
            if m is None or not m.alive:
                return {"epoch": self.epoch, "action": "rejoin",
                        "assign": None, "now": now_wall}
            m.last_hb = self._time()
            if clock:
                # the member measured coordinator_clock - member_clock;
                # the aggregator wants member - coordinator
                m.clock_offset = -float(clock.get("offset", 0.0))
                m.clock_rtt = float(clock.get("rtt", 0.0))
            target = self._targets.get(member_id, set())
            if m.owned:
                if m.owned == target:
                    return {"epoch": self.epoch, "action": "run",
                            "assign": None, "now": now_wall}
                return {"epoch": self.epoch, "action": "resync",
                        "assign": None, "now": now_wall}
            if target and not (target <= self._released):
                return {"epoch": self.epoch, "action": "wait",
                        "assign": None, "now": now_wall}
            # acquire the full target set atomically (possibly empty:
            # more members than partitions -> this member idles)
            m.owned = set(target)
            self._released -= target
            assign = {p: self._covered[p] for p in sorted(target)}
            self._check_rebalance_settled_locked()
            return {"epoch": self.epoch, "action": "run",
                    "assign": assign, "now": now_wall}

    # ---- submissions ------------------------------------------------------

    def submit(self, member_id: str, payload) -> dict:
        """Accept one member contribution (codec bytes or decoded dict).
        Returns {"ok": True} or {"ok": False, "reason": ...}."""
        raw = None
        if isinstance(payload, (bytes, bytearray)):
            raw = bytes(payload)
            payload = codec.decode(raw)
        t_recv = time.time()
        span = payload.get("span") or {}
        fold = []
        accepted = False
        duplicate = False
        reject_reason = None
        with self._lock:
            m = self._members.get(member_id)
            if m is None or not m.alive:
                self._m["rejected"].inc(reason="fenced")
                reject_reason = "fenced"
            elif span.get("sub") is not None and \
                    int(span["sub"]) <= m.last_sub:
                # lost-ack retry of an ALREADY-ACCEPTED submission:
                # idempotent accept — fold nothing, journal nothing. The
                # frontier-extend check alone cannot catch this when the
                # retried ranges are empty ([covered, covered] — a final
                # or idle-flush submission with no new offsets), and
                # re-folding its closed windows would double-count them.
                m.last_hb = self._time()
                duplicate = True
            else:
                m.last_hb = self._time()
                ranges = payload.get("ranges", {})
                for p, rng in ranges.items():
                    p = int(p)
                    if p not in m.owned or int(rng[0]) != self._covered[p] \
                            or int(rng[1]) < int(rng[0]):
                        # frontier mismatch: protocol violation or a
                        # zombie with stale state — fence, force a
                        # clean rejoin
                        self._m["rejected"].inc(reason="range")
                        reject_reason = "range"
                        fold = self._fence_locked(member_id, "death")
                        break
                else:
                    fold = self._accept_locked(m, member_id, payload,
                                               t_recv, span)
                    accepted = True
                    if self._journal is not None:
                        # under _lock so journal order == accept order;
                        # a buffered append, never an fsync (sync below)
                        self._journal.append(
                            "sub", {"member": member_id},
                            raw if raw is not None
                            else codec.encode(payload))
        if self._journal is not None and (accepted or
                                          reject_reason == "range"):
            # group-commit durability barrier BEFORE the ok ack: an
            # acked submission is always recoverable. The fsync runs
            # with no coordinator lock held; concurrent acks share one
            # disk flush. A "range" rejection journaled a FENCE record
            # (the carry promotion) — it must not linger unfsynced with
            # no later ack to flush it, or the lag gauge would sit
            # frozen while the record stays undurable.
            self._journal.sync()
        if duplicate:
            TRACER.record("mesh_submit_accept", t_recv, time.time(),
                          member=member_id, sub=span.get("sub"),
                          chunk=span.get("chunk"), duplicate=True,
                          windows=0)
            log.info("mesh member %s resubmitted sub=%s (lost ack); "
                     "acked idempotently", member_id, span.get("sub"))
            return {"ok": True, "duplicate": True}
        if fold:
            self._run_merges(fold)
        if accepted:
            TRACER.record("mesh_submit_accept", t_recv, time.time(),
                          member=member_id, sub=span.get("sub"),
                          chunk=span.get("chunk"),
                          windows=len(payload.get("closed", {})))
            return {"ok": True}
        # the rejected submission's span context goes INTO the ring
        # before the dump: a zombie rejection is exactly the event the
        # crash-restart post-mortem needs to see, with the member's
        # own submission id / chunk / wall-clock anchor attached
        TRACER.record("mesh_submit_reject", t_recv, time.time(),
                      member=member_id, reason=reject_reason,
                      sub=span.get("sub"), chunk=span.get("chunk"),
                      sent=span.get("sent"))
        self._dump_flight(
            f"rejected submission from {member_id} ({reject_reason})")
        # the honest cause: "range" (frontier mismatch — a protocol
        # bug to debug) vs "fenced" (zombie — the expected churn path);
        # the member's rejection log prints it
        return {"ok": False, "reason": reject_reason}

    def _accept_locked(self, m: _Member, member_id: str, payload: dict,
                       t_recv: float, span: dict):
        if span.get("sub") is not None:
            m.last_sub = max(m.last_sub, int(span["sub"]))
        for p, rng in payload.get("ranges", {}).items():
            self._covered[int(p)] = int(rng[1])  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        wm = int(payload.get("watermark", 0))
        for p in m.owned:
            if wm > self._wm[p]:
                self._wm[p] = wm  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        if wm > m.watermark:
            m.watermark = wm
        self._publish_watermarks_locked()
        flows = int(payload.get("flows", 0))
        if flows:
            self._m["flows"].inc(flows, member=member_id)
        self._m["submit"].inc()
        ranges = {int(p): [int(r[0]), int(r[1])]
                  for p, r in payload.get("ranges", {}).items()}
        self._fold_windows_locked(
            payload.get("closed", {}), member=member_id, span=span,
            ranges=ranges, accepted=t_recv, kind="closed")
        open_windows = payload.get("open", {})
        if payload.get("release") or payload.get("final"):
            # the member is resetting (resync) or done: its open state
            # must not sit in a carry nobody will promote
            self._fold_windows_locked(
                open_windows, member=member_id, span=span,
                ranges=ranges, accepted=t_recv, kind="final-open")
            self._carry.pop(member_id, None)
        else:
            self._carry[member_id] = {"windows": open_windows,  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
                                      "span": span, "ranges": ranges}
        if payload.get("final"):
            for p in m.owned:
                self._final[p] = True  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        if payload.get("release"):
            self._released |= m.owned  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
            m.owned = set()
        return self._pop_ready_locked()

    def _publish_watermarks_locked(self) -> None:
        """Mesh-wide freshness gauges: the commit watermark is the MIN
        over live members' reported watermarks (the merge barrier can
        never be past the slowest shard), and each member's skew is its
        lag behind the mesh leader — the stalled-shard pager signal.
        Members that have never reported (watermark 0: just joined, no
        submission yet) are EXCLUDED — event time is epoch seconds, so
        folding a 0 into the min would crater the watermark by ~56
        years and report the newcomer's skew as the full epoch."""
        wms = {mid: mm.watermark for mid, mm in self._members.items()
               if mm.alive and mm.watermark > 0}
        if not wms:
            return
        hi = max(wms.values())
        self._m["commit_wm"].set(min(wms.values()))
        for mid, w in wms.items():
            self._m["member_wm"].set(w, member=mid)
            self._m["wm_skew"].set(hi - w, member=mid)

    def _fold_windows_locked(self, windows: dict, member=None,
                             span=None, ranges=None, accepted=None,
                             kind: str = "closed") -> None:
        """Fold {slot: {model: payload}} into the pending barrier. A
        contribution for an already-merged window is LATE: exact wagg
        partials are emitted as additional rows (the single-worker late
        semantics — merging sinks combine them); late sketch state has
        no exact merge target left and is dropped, counted.

        The keyword context feeds the meshscope lineage ledger: every
        pending window accumulates WHO contributed (member, submission
        id, offset ranges, member send anchor vs coordinator accept
        wall) and HOW (a closed window, a promoted carry, a late
        partial) — the record `/debug/lineage` answers from."""
        span = span or {}
        for slot, models in windows.items():
            slot = int(slot)
            for name, payload in models.items():
                if name not in self._by_name:
                    continue
                key = (name, slot)
                late = key in self._merged_keys
                if late:
                    self._m["late"].inc(model=name)
                    if payload.get("kind") != "wagg":
                        # dropped — but the lineage of the MERGED window
                        # must still show the late arrival
                        self._lineage_note_late_locked(key, member, span)
                        continue
                    self._pending.setdefault(key, []).append(payload)
                    self._merged_keys.discard(key)  # re-merge partial
                else:
                    self._pending.setdefault(key, []).append(payload)
                rec = self._lineage_pending.get(key)
                if rec is None:
                    rec = self._lineage_pending[key] = {  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
                        "model": name, "slot": slot, "status": "pending",
                        "contributions": [], "carries_promoted": [],
                        "late": 0,
                        "first_contribution": accepted or time.time(),
                    }
                    if late:
                        # the record is a RE-opening of a merged window
                        # — mark it so the seal treats it as a re-merge
                        # even when the prior lineage record was
                        # retention-evicted (_merged_keys outlives
                        # _lineage_done by design)
                        rec["late_reopen"] = True
                rec["contributions"].append({
                    "member": member,
                    "sub": span.get("sub"),
                    "kind": "late" if late else kind,
                    "ranges": ranges,
                    "submitted": span.get("sent"),
                    "accepted": accepted,
                    "chunk": span.get("chunk"),
                })
                if late:
                    rec["late"] += 1
                if kind == "carry-promoted" and \
                        member not in rec["carries_promoted"]:
                    rec["carries_promoted"].append(member)

    def _lineage_note_late_locked(self, key, member, span) -> None:
        """A late contribution whose window's rows are final (dropped
        sketch state): annotate the merged lineage record if sealed, or
        buffer the annotation if the window is mid-merge (popped from
        pending but not yet sealed — the merge itself runs without any
        lock) so the seal drains it. Caller holds _lock."""
        entry = {
            "member": member, "sub": span.get("sub"),
            "kind": "late-dropped", "ranges": None,
            "submitted": span.get("sent"),
            "accepted": time.time(), "chunk": span.get("chunk"),
        }
        rec = self._lineage_done.get(key)
        if rec is not None:
            rec["late"] += 1
            rec["contributions"].append(entry)
            return
        self._lineage_orphans.setdefault(key, []).append(entry)
        # bound the buffer: an orphan for a retention-EVICTED window
        # (not mid-merge) has no seal left to drain it — drop the
        # oldest slots past a small cap instead of leaking forever
        while len(self._lineage_orphans) > 64:
            del self._lineage_orphans[min(self._lineage_orphans,
                                          key=lambda k: k[1])]

    def _pop_ready_locked(self) -> list:
        """Detach every pending window whose barrier condition holds:
        all partitions final, or watermark past slot + window (+
        lateness). Marks them merged so later contributions register as
        late."""
        ready = []
        now = time.time()
        for key in sorted(self._pending):
            name, slot = key
            spec = self._by_name[name]
            limit = slot + spec.window_seconds + spec.allowed_lateness
            if all(self._final[p] or self._wm[p] >= limit
                   for p in range(self.n_partitions)):
                lin = self._lineage_pending.pop(key, None)
                if lin is not None:
                    lin["barrier_released"] = now
                ready.append((name, slot, self._pending.pop(key), lin))
                self._merged_keys.add(key)
                self._inflight_keys.add(key)
        return ready

    # ---- merging ----------------------------------------------------------

    def _run_merges(self, ready: list) -> None:
        """Fold + extract + emit each detached window. Runs on the
        submitting thread with NO coordinator lock held (merge math and
        sink writes must not serialize member heartbeats)."""
        for name, slot, payloads, lin in ready:
            t0 = time.perf_counter()
            t0_wall = time.time()
            spec = self._by_name[name]
            rows = self._merge_one(spec, slot, payloads)
            t_merged = time.time()
            TRACER.record("mesh_merge", t0_wall, t_merged, model=name,
                          slot=slot, contribs=len(payloads))
            for sink in self.sinks:
                sink.write(name, rows)
            t_emitted = time.time()
            if self._journal is not None:
                # AFTER the sink writes: a crash inside the sink-write ->
                # journal gap re-merges and re-emits this window on
                # recovery — the same irreducible at-least-once window
                # as the worker's flush -> snapshot gap
                self._journal.append("merged", {"model": name,
                                                "slot": int(slot)})
                # ... and fsync IMMEDIATELY: deferring this record to
                # the next member-ack group commit would leave it
                # sitting in the file buffer while the rows are already
                # in the sinks — a crash in that (arbitrarily long:
                # members may be idle) gap re-emits the window on
                # recovery. One fsync per merged window shrinks the
                # at-least-once gap back to the sink-write itself.
                self._journal.sync()
            # only now is the window safe to checkpoint as merged: its
            # rows are in the sinks and (if journaling) its "merged"
            # record is appended. A merge that raises leaves the key
            # in-flight — compaction stays deferred, preserving the
            # uncompacted journal's recovery exactly
            with self._lock:
                self._inflight_keys.discard((name, slot))
            n_rows = self._count_rows(rows)
            TRACER.record("mesh_emit", t_merged, t_emitted, model=name,
                          slot=slot, rows=n_rows)
            # the new contributions BEFORE any re-merge fold-in: the
            # submit->merge latency must only observe this round's
            new_contribs = list(lin["contributions"]) if lin else []
            remerge = False
            with self._merge_lock:
                self.merged.setdefault((name, slot), []).append(rows)
                # bounded retention (newest slots win); _merged_keys is
                # NOT evicted — late-contribution detection must keep
                # working for windows whose rows have aged out
                slots = sorted(s for n, s in self.merged if n == name)
                for s in slots[:-MERGED_LEDGER_SLOTS]:
                    del self.merged[(name, s)]
            if lin is not None:
                # sealing is cheap metadata work: it runs under _lock so
                # late contributions can never fall between "marked
                # merged" and "record sealed" unobserved (the orphan
                # buffer catches the mid-merge gap)
                with self._lock:
                    remerge = self._finish_lineage_locked(
                        name, slot, lin, t0_wall, t_merged, t_emitted,
                        n_rows)
            self._m["merge_s"].observe(time.perf_counter() - t0)
            self._m["merged"].inc(model=name)
            if lin is not None:
                if not remerge:
                    # a late-partial re-merge has no honest barrier
                    # interval (its "first contribution" IS the late
                    # arrival) — observing it would feed bogus ~0
                    # samples into the SLO histogram. The interval ends
                    # at BARRIER RELEASE (the _pop_ready_locked stamp),
                    # not merge start: with several windows detached in
                    # one batch, window B must not absorb window A's
                    # merge+emit wall as "barrier wait".
                    first = lin["first_contribution"]
                    released = lin.get("barrier_released", t0_wall)
                    self._m["barrier_s"].observe(
                        max(0.0, released - first))
                    TRACER.record("mesh_barrier_wait", first, released,
                                  model=name, slot=slot,
                                  contribs=len(new_contribs))
                for c in new_contribs:
                    if c.get("accepted") is not None:
                        # labeled by member: a slow shard's submit->merge
                        # tail is its own series (and is REMOVED when
                        # the member is fenced/leaves — Histogram.remove
                        # mirrors the r13 Gauge.remove fix, so a dead
                        # member's frozen latency never pages)
                        self._m["sub2merge_s"].observe(
                            max(0.0, t_merged - c["accepted"]),
                            member=str(c.get("member") or "unknown"))
            log.info("mesh merged window model=%s slot=%d contribs=%d",
                     name, slot, len(payloads))
        if ready and self._journal is not None:
            self._journal.sync()
            self._maybe_compact()
        if ready and self.serve is not None:
            # wake the flowserve publisher (no lock held here); the
            # fan-out/extract runs on ITS thread, never the submitter's
            self.serve.on_merge()

    def _finish_lineage_locked(self, name: str, slot: int, lin: dict,
                               t0_wall: float, t_merged: float,
                               t_emitted: float, n_rows: int) -> bool:
        """Seal a lineage record at merge time (caller holds _lock) and
        age the per-model ledger (LINEAGE_SLOTS newest slots —
        metadata-sized records, same discipline as the merged-rows
        ledger). A late-partial RE-merge must not destroy the original
        window's lineage — the prior sealed record's contributions,
        first-contribution time, promoted carries and late count fold
        into the new one — and orphaned late annotations buffered
        during the lock-free merge gap drain here. Returns whether
        this was a re-merge."""
        key = (name, slot)
        reopen = lin.pop("late_reopen", False)
        prior = self._lineage_done.get(key)
        if prior is None and reopen:
            # re-merge of a retention-evicted window: nothing to fold,
            # but it IS a re-merge — without this the evicted case
            # would feed the bogus ~0 barrier sample the remerge
            # exclusion exists to prevent
            lin["remerges"] = lin.get("remerges", 0) + 1
        if prior is not None:
            lin["contributions"] = prior["contributions"] + \
                lin["contributions"]
            # min, not prior's: concurrent merges of the same slot may
            # seal in either order
            lin["first_contribution"] = min(prior["first_contribution"],
                                            lin["first_contribution"])
            lin["carries_promoted"] = prior["carries_promoted"] + [
                m for m in lin["carries_promoted"]
                if m not in prior["carries_promoted"]]
            lin["late"] += prior["late"]
            lin["remerges"] = prior.get("remerges", 0) + 1
        orphans = self._lineage_orphans.pop(key, None)
        if orphans:
            lin["contributions"] = lin["contributions"] + orphans
            lin["late"] += len(orphans)
        lin["status"] = "merged"
        lin["members"] = sorted({c["member"]
                                 for c in lin["contributions"]
                                 if c["member"] is not None})
        lin["merge_started"] = t0_wall
        lin["merged"] = t_merged
        lin["emitted"] = t_emitted
        lin["merge_wall_s"] = round(t_merged - t0_wall, 6)
        lin["barrier_wait_s"] = round(
            max(0.0, lin.get("barrier_released", t0_wall)
                - lin["first_contribution"]), 6)
        lin["rows"] = n_rows
        self._lineage_done[key] = lin  # flowlint: disable=lock-discipline -- *_locked helper: every caller holds _lock (the checker is per-write-site)
        slots = sorted(s for n, s in self._lineage_done if n == name)
        for s in slots[:-LINEAGE_SLOTS]:
            del self._lineage_done[(name, s)]
        return prior is not None or reopen

    @staticmethod
    def _count_rows(rows) -> int:
        """Emitted-row count for one merged window (lineage/emit span):
        top-K dicts carry a validity mask, wagg dicts a timeslot
        column, alert lists are plain."""
        if isinstance(rows, dict):
            if "valid" in rows:
                return int(rows["valid"].sum())
            ts = rows.get("timeslot")
            return int(len(ts)) if ts is not None else 0
        return len(rows)

    def _merge_one(self, spec: ModelSpec, slot: int, payloads: list) -> dict:
        # kind-agnostic: the family registry supplies merge + rows hooks
        # per spec.kind; only the hh sampled-cohort audit (carried inside
        # the merged payload) needs a side effect here
        fam = registry.family(spec.kind)
        merged = registry.hook(fam, "merge")(payloads, spec.config)
        if isinstance(merged, dict):
            audit = merged.get("audit")
            if audit is not None:
                self._audit_merged_window(spec, slot, merged, audit)
        return registry.hook(fam, "top_rows")(merged, spec.config,
                                              spec.k, slot)

    def _audit_merged_window(self, spec: ModelSpec, slot: int,
                             merged: dict, audit: dict) -> None:
        """sketchwatch, network-wide: the members shipped per-shard
        sampled exact cohorts inside their hh payloads; merge_hh folded
        them (uint64 per-key sums — the same linearity as the CMS).
        Audit the MERGED sketch against the MERGED cohort, so the error
        metrics this coordinator publishes describe the network-wide
        answer — not any one shard's."""
        report = audit_report(audit["keys"], audit["vals"], merged,
                              spec.config, spec.k or spec.config.capacity,
                              slot=slot, scale=int(audit.get("scale", 1)))
        evictions = int(audit.get("evictions", 0))
        if evictions:
            self._audit_m["evictions"].inc(evictions, family=spec.name)
        report["evictions"] = evictions
        report = publish_report(spec.name, report,
                                metrics=self._audit_m)
        with self._merge_lock:
            self._audit_reports[spec.name] = report
            self.audit_merged[(spec.name, slot)] = audit
            slots = sorted(s for n, s in self.audit_merged
                           if n == spec.name)
            for s in slots[:-AUDIT_LEDGER_SLOTS]:
                del self.audit_merged[(spec.name, s)]

    def audit_reports(self) -> dict:
        """{model: newest network-wide audit report} — the flowserve
        snapshot's /query/audit view of the mesh."""
        with self._merge_lock:
            return dict(self._audit_reports)

    def audit_cohort(self, name: str, slot: int) -> Optional[dict]:
        """Merged audit partial for one (model, slot) — the ledger the
        mesh-vs-oracle bit-equality gate reads."""
        with self._merge_lock:
            return self.audit_merged.get((name, slot))

    # ---- live queries (mesh-aware /topk) ----------------------------------

    def open_window_payloads(self, name: str,
                             ) -> tuple[Optional[int], list]:
        """(newest open slot, its contribution payloads) for one top-K
        model: every live member's provider state plus the slot's
        pending barrier contributions. The coordinator lock covers only
        the provider/pending SNAPSHOT — the member fan-out runs
        lock-free, and an unreachable member degrades the answer
        instead of blacking it out. Shared by the per-query ``/topk``
        fan-out and the flowserve publisher (which amortizes one call
        over every reader until the next publish)."""
        with self._lock:
            providers = [(mid, m.provider)
                         for mid, m in self._members.items()
                         if m.alive and m.provider is not None]
            # NOT the carries: every carry belongs to a LIVE member
            # (death promotes them into _pending), and a live member's
            # provider state is a superset of its own carry — folding
            # both would double-count everything since its last
            # submission. What CAN be missing from the providers is a
            # dead member's promoted-but-unmerged contribution: that
            # sits in _pending, disjoint from its successor's state
            # (the successor resumed at the covered frontier).
            pending = {slot: list(payloads)
                       for (n, slot), payloads in self._pending.items()
                       if n == name}
        states: list[tuple[int, dict]] = []
        for mid, provider in providers:
            try:
                res = provider(name)
            except (OSError, ValueError) as e:
                # a dying-but-not-yet-fenced member must DEGRADE the
                # answer (its un-submitted open rows are missing until
                # the fence promotes/replays), never black out /topk
                log.warning("mesh /topk: member %s state fetch failed "
                            "(%s); answering without it", mid, e)
                continue
            if isinstance(res, (bytes, bytearray)):
                res = codec.decode(bytes(res))
            if res and res.get("slot") is not None:
                states.append((int(res["slot"]), res["payload"]))
        slots = [s for s, _ in states] + list(pending)
        if not slots:
            return None, []
        slot = max(slots)
        return slot, [p for s, p in states if s == slot] + \
            pending.get(slot, [])

    def commit_watermark(self) -> int:
        """Mesh-wide event-time watermark: min over live members'
        reported watermarks (never-reported newcomers excluded — the
        same rule as the mesh_commit_watermark_seconds gauge)."""
        with self._lock:
            wms = [m.watermark for m in self._members.values()
                   if m.alive and m.watermark > 0]
        return min(wms) if wms else 0

    def query_topk(self, model: Optional[str] = None,
                   k: Optional[int] = None) -> dict:
        """Fan the query to every live member's state provider and
        answer from the merged open-window view — the network-wide
        equivalent of QueryServer._topk's single-worker answer."""
        spec = None
        if model:
            spec = self._by_name.get(model)
            if spec is None or spec.kind == "wagg":
                raise KeyError(f"no mesh top-K model named {model!r}")
        else:
            # default selection mirrors the single-worker QueryServer:
            # the first model with a top-K surface, dense-backed included
            spec = next((s for s in self.specs
                         if s.kind in ("hh", "dense")), None)
            if spec is None:
                raise KeyError("no top-K model configured")
        slot, payloads = self.open_window_payloads(spec.name)
        if slot is None:
            return {"model": spec.name, "window_start": None, "rows": []}
        from ..sink.base import rows_to_records

        kk = k or spec.k or spec.config.capacity
        fam = registry.family(spec.kind)
        merged = registry.hook(fam, "merge")(payloads, spec.config)
        rows = registry.hook(fam, "top_rows")(merged, spec.config, kk,
                                              slot)
        return {"model": spec.name, "window_start": slot,
                "rows": rows_to_records(rows)}

    # ---- introspection ----------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            return {
                "epoch": self.epoch,
                "partitions": self.n_partitions,
                "members": {
                    mid: {"alive": m.alive,
                          "owned": sorted(m.owned),
                          "target": sorted(self._targets.get(mid, ()))}
                    for mid, m in self._members.items()
                },
                "covered": list(self._covered),
                "watermarks": list(self._wm),
                "final": list(self._final),
                "pending_windows": sorted(
                    f"{n}:{s}" for n, s in self._pending),
            }

    def merged_rows(self, name: str, slot: Optional[int] = None) -> list:
        """Emitted merged rows for one model (all slots, or one) — the
        test/debug ledger."""
        with self._merge_lock:
            if slot is not None:
                return list(self.merged.get((name, slot), []))
            return [rows for (n, _), rs in sorted(self.merged.items())
                    if n == name for rows in rs]

    def lineage(self, model: Optional[str] = None,
                slot: Optional[int] = None) -> list[dict]:
        """The meshscope window-lineage ledger (JSON-safe copies):
        merged records first (newest-LINEAGE_SLOTS per model), then the
        still-pending windows riding the barrier. Each record answers
        "which members built this window, from which offset ranges,
        when, and through which path (closed / promoted carry / late)"
        — served at ``/debug/lineage`` and by the ``lineage`` CLI."""
        def keep(n, s):
            return (model is None or n == model) and \
                (slot is None or s == slot)

        with self._lock:
            out = [dict(rec, contributions=list(rec["contributions"]))
                   for (n, s), rec in sorted(self._lineage_done.items())
                   if keep(n, s)]
            out += [dict(rec, contributions=list(rec["contributions"]))
                    for (n, s), rec in
                    sorted(self._lineage_pending.items()) if keep(n, s)]
        return out

    def trace_sources(self) -> list[tuple]:
        """(member_id, trace_url, clock_offset, clock_rtt) for every
        live member that advertised a trace endpoint — the
        ``/debug/trace`` fan-out list. ``clock_offset`` is
        member_clock - coordinator_clock (None until the member's first
        heartbeat clock report; the fan-out then estimates its own from
        the fetch round-trip)."""
        with self._lock:
            return [(mid, m.trace_url, m.clock_offset, m.clock_rtt)
                    for mid, m in self._members.items()
                    if m.alive and m.trace_url]
