"""flowmesh in-process runtime: N members + coordinator on one box.

The harness behind ``cli.py pipeline -mesh.workers N``, ``bench.py
mesh`` and ``make mesh-parity``: flows are sharded by KEY-HASH across
bus partitions (every row of a flow key lands on the same partition, so
per-shard sketches see each key's complete substream), N MeshMember
threads consume their assigned partitions, and the coordinator merges
window state network-wide at close. The same member/coordinator objects
run across real processes through mesh/server.py — this module only
supplies the single-process wiring.
"""

from __future__ import annotations

# flowlint: lock-checked
# (the runtime mutates its attributes from the driver thread only;
# member threads touch members, which carry their own contract)

import threading
import time
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..engine.hostfused import _key_lanes_np
from ..engine.worker import WorkerConfig
from ..obs import get_logger
from ..schema import wire
from ..schema.batch import FlowBatch
from ..schema.keys import hash_words_np
from ..transport import Consumer, InProcessBus
from .coordinator import MeshCoordinator, spec_from_models
from .member import MeshMember

log = get_logger("mesh")

# The canonical shard key: the finest key family (5-tuple). Families
# whose key tuple CONTAINS the shard key (the top-talkers family) get
# the strongest guarantee: each of their keys lands wholly on one shard,
# so merged candidate tables are a disjoint union with exact per-key
# sums. Subset families (per-IP, per-port) necessarily spread one key
# across shards — no single shard key can colocate every projection —
# and merge as standard sketch monoids instead: the CMS element-sum is
# still a true union-stream sketch (count-min is linear), and the table
# fold sums per-shard resident values — exact whenever a key is
# resident in every shard that saw it (always, while distinct keys <=
# capacity: the regime `make mesh-parity` pins bit-exact), and
# otherwise upper-bounded by the est columns with per-shard
# Misra-Gries admission bounds (the HashPipe per-shard trade).
SHARD_KEY_COLS = ("src_addr", "dst_addr", "src_port", "dst_port", "proto")


def shard_ids(batch: FlowBatch, n_partitions: int,
              key_cols: Sequence[str] = SHARD_KEY_COLS):
    """Per-row partition ids: murmur3 over the uint32 key lanes mod P —
    deterministic, so every leg of an A/B (and a replay) shards the
    stream identically."""
    lanes = _key_lanes_np(batch.columns, tuple(key_cols))
    return hash_words_np(lanes) % np.uint32(n_partitions)


def produce_sharded(bus: InProcessBus, topic: str, batch: FlowBatch,
                    n_partitions: int,
                    key_cols: Sequence[str] = SHARD_KEY_COLS) -> int:
    """Append one generated batch to the bus, key-hash sharded. Row
    order within each partition preserves the batch's time order."""
    pids = shard_ids(batch, n_partitions, key_cols)
    for p in range(n_partitions):
        idx = np.flatnonzero(pids == p)
        if not len(idx):
            continue
        part = FlowBatch({k: v[idx] for k, v in batch.columns.items()})
        bus.produce_many(topic, wire.iter_raw_frames(part.to_wire()),
                         partition=p)
    return len(batch)


class InProcessMesh:
    """Coordinator + N member threads over one in-process bus."""

    def __init__(self, bus: InProcessBus, topic: str, n_workers: int,
                 model_factory: Callable[[], dict],
                 config: WorkerConfig = WorkerConfig(),
                 sinks: Sequence[Any] = (),
                 member_sinks: Sequence[Any] = (),
                 heartbeat_timeout: float = 30.0,
                 submit_every: int = 0,
                 sync_interval: float = 0.05,
                 journal: Optional[str] = None):
        self.bus = bus
        self.topic = topic
        # one throwaway model set derives the merge specs — members
        # build their own fresh sets per assignment epoch
        self.coordinator = MeshCoordinator(
            spec_from_models(model_factory()), bus.partitions(topic),
            sinks=sinks, heartbeat_timeout=heartbeat_timeout,
            journal=journal)
        self.members = []
        for i in range(n_workers):
            mid = f"w{i}"
            self.members.append(MeshMember(
                mid, self.coordinator,
                consumer_factory=self._consumer_factory(mid),
                model_factory=model_factory, config=config,
                sinks=list(member_sinks), submit_every=submit_every,
                sync_interval=sync_interval))
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def _consumer_factory(self, member_id: str):
        def factory(partitions):
            return Consumer(self.bus, self.topic,
                            group=f"mesh-{member_id}", fixedlen=True,
                            partitions=list(partitions))
        return factory

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> "InProcessMesh":
        # pre-join every member before any thread consumes: the first
        # assignment is computed once over the FULL membership, instead
        # of member 0 grabbing all partitions and resyncing immediately
        for m in self.members:
            self.coordinator.join(m.member_id, provider=m._query_state)
            m._joined = True
        for m in self.members:
            t = threading.Thread(target=m.run, args=(self._stop,),
                                 name=f"mesh-{m.member_id}", daemon=True)
            self._threads.append(t)
            t.start()
        return self

    def kill_member(self, i: int, fence: bool = True) -> str:
        """Abrupt member death (churn): stop it WITHOUT submission, then
        (by default) fence it at the coordinator immediately — the
        deterministic stand-in for the heartbeat timeout."""
        m = self.members[i]
        m.kill()
        if fence:
            self.coordinator.fence(m.member_id)
        return m.member_id

    def wait_idle(self, idle_rounds: int = 20, timeout: float = 300.0,
                  poll: float = 0.02) -> None:
        """Block until every live member has been idle for
        ``idle_rounds`` consecutive steps AND every partition is owned
        (pre-produced streams: everything consumed and every rebalance
        settled — members idling mid-handoff, with partitions released
        but not yet re-acquired, do NOT count as quiescence)."""
        deadline = time.monotonic() + timeout
        streak = 0
        while time.monotonic() < deadline:
            live = [m for m in self.members if not m._dead]
            ok = live and all(m.idle_streak >= idle_rounds for m in live)
            if ok:
                st = self.coordinator.status()
                owned = sum(len(v["owned"])
                            for v in st["members"].values())
                ok = owned == st["partitions"]
            # two consecutive successful polls: closes the sliver where
            # a member was just granted ownership but has not yet reset
            # its (stale) idle streak from the waiting phase
            streak = streak + 1 if ok else 0
            if streak >= 2:
                return
            time.sleep(poll)
        raise TimeoutError("mesh did not quiesce within timeout")

    def finalize(self) -> None:
        """Stop member threads, final-submit every live member, merge
        everything outstanding, release the coordinator's journal."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=60)
        for m in self.members:
            m.finalize()
        self.coordinator.close()

    def run(self, idle_rounds: int = 20, timeout: float = 300.0) -> float:
        """start() -> wait_idle() -> finalize(); returns the wall-clock
        seconds between start and quiescence (the bench number)."""
        t0 = time.perf_counter()
        self.start()
        try:
            self.wait_idle(idle_rounds=idle_rounds, timeout=timeout)
            elapsed = time.perf_counter() - t0
        finally:
            self.finalize()
        return elapsed
