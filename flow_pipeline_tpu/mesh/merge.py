"""flowmesh window-close merges: the monoid algebra, host-side.

These are the `parallel/sharded.py` collective merges lifted off the
device mesh onto serialized payloads (PAPERS.md's data-plane HH model —
HashPipe 1611.04825, 1902.06993: per-shard detection, network-wide
exact merge):

- exact window aggregates : per-key uint64 SUM (associative, exact)
- CMS planes              : element-wise uint64 SUM — the count-min
                            sketch is linear in the stream, so the sum
                            of per-shard sketches IS the sketch of the
                            union stream (bit-identical for the plain
                            update; a valid, slightly looser upper
                            bound under conservative update)
- top-K candidate tables  : concat -> group-by-key sum -> rank by
                            primary desc with the stable lexicographic
                            tie-break (`jnp.argsort(-primary)`'s exact
                            behavior — the same table-table fold
                            ops.topk.topk_merge runs on device). With
                            key-hash sharding the key sets are
                            disjoint, so the per-key sum degenerates to
                            a copy and the merged values are exact.
- dense accumulators      : element-wise integer sum (the (lo, hi)
                            planes recombine exactly at extraction)

Pure numpy — the coordinator merges without touching a device.
"""

from __future__ import annotations

import numpy as np

from ..hostsketch.engine import np_cms_query
from ..models.heavy_hitter import HeavyHitterConfig, key_width
from ..ops.hostgroup import _lex_regroup
from ..schema.batch import lane_width

_SENTINEL = np.uint32(0xFFFFFFFF)


# ---- exact window aggregates ----------------------------------------------


def merge_wagg(payloads: list[dict], config=None) -> dict:
    """Fold wagg payloads (keys [G, L] u32, vals [G, V] u64) into one
    window-store dict {key tuple -> uint64 vec} — per-key sums, exact.

    ``config`` is unused (the fold is shape-generic) but accepted so
    every registered family's merge hook shares one signature
    (families/registry.py)."""
    real = [p for p in payloads if len(p["keys"])]
    if not real:
        return {}
    keys = np.concatenate([p["keys"].astype(np.uint32) for p in real])
    vals = np.concatenate([p["vals"].astype(np.uint64) for p in real])
    order, starts = _lex_regroup(keys)
    uniq = keys[order][starts]
    sums = np.add.reduceat(vals[order], starts, axis=0)
    return {tuple(int(x) for x in uniq[i]): sums[i]
            for i in range(len(starts))}


# ---- heavy-hitter sketch state --------------------------------------------


def merge_hh(payloads: list[dict], config: HeavyHitterConfig) -> dict:
    """Fold hh payloads into one merged {cms, table_keys, table_vals}.

    CMS: uint64 element sum. Table: the table-table fold — every real
    row from every table, grouped by key (lexicographic), per-key plane
    sums, ranked by plane-0 descending with the stable lex tie-break,
    truncated to capacity.

    Invertible payloads (kind="hh_inv", the -hh.sketch=invertible
    family) dispatch to :func:`merge_hh_inv`: every plane merges by a
    plain element-wise u64 sum — no table folds, no device-rank
    semantics — and the merged table view is DECODED from the merged
    sketch. Either way the merged dict carries {cms, table_keys,
    table_vals}, so extraction, serving and the audit consume one
    shape.
    """
    if any(p.get("kind") == "hh_inv" for p in payloads):
        if not all(p.get("kind") == "hh_inv" for p in payloads):
            # one family must run ONE sketch flavor mesh-wide: a mixed
            # fold has no exactness story (u64 planes vs f32 tables)
            raise ValueError(
                "cannot merge mixed hh/hh_inv payloads for one family "
                "— every member must run the same -hh.sketch")
        return merge_hh_inv(payloads, config)
    planes = len(config.value_cols) + 1
    kw = key_width(config)
    cms = np.zeros((planes, config.depth, config.width), np.uint64)
    rows_k, rows_v = [], []
    for p in payloads:
        cms += p["cms"].astype(np.uint64)
        tk = p["table_keys"].astype(np.uint32)
        tv = p["table_vals"].astype(np.float32)
        real = (tk != _SENTINEL).any(axis=1)
        rows_k.append(tk[real])
        rows_v.append(tv[real])
    new_keys = np.full((config.capacity, kw), _SENTINEL, np.uint32)
    new_vals = np.zeros((config.capacity, planes), np.float32)
    keys = np.concatenate(rows_k) if rows_k else new_keys[:0]
    vals = np.concatenate(rows_v) if rows_v else new_vals[:0]
    if len(keys):
        order, starts = _lex_regroup(keys)
        uniq = keys[order][starts]
        sums = np.add.reduceat(vals[order], starts,
                               axis=0).astype(np.float32)
        top = np.argsort(-sums[:, 0], kind="stable")[:config.capacity]
        new_keys[:len(top)] = uniq[top]
        new_vals[:len(top)] = sums[top]
    out = {"kind": "hh", "cms": cms, "table_keys": new_keys,
           "table_vals": new_vals}
    # sketchwatch: per-member sampled exact cohorts ride inside the hh
    # payloads; their fold is the same uint64 per-key sum the CMS
    # linearity argument rests on — the merged cohort IS the cohort a
    # single worker seeing the whole stream would have built
    audits = [p["audit"] for p in payloads if p.get("audit") is not None]
    if audits:
        out["audit"] = merge_audit(audits)
    return out


def merge_hh_inv(payloads: list[dict], config: HeavyHitterConfig) -> dict:
    """Fold invertible-family payloads: element-wise u64 wrap sum of
    the count/value planes AND the key-recovery planes — the whole
    merge (the sketch is linear in the stream, so the sum of per-shard
    states IS the state of the union stream, bit-exactly). The merged
    table view is then decoded ONCE from the merged sketch
    (hostsketch.engine.inv_extract), so `hh_top_rows`, the serve
    publisher and the merged-cohort audit consume the same
    {cms, table_keys, table_vals} shape table merges produce."""
    from ..hostsketch.engine import inv_extract

    planes = len(config.value_cols) + 1
    kw = key_width(config)
    cms = np.zeros((planes, config.depth, config.width), np.uint64)
    keysum = np.zeros((config.depth, config.width, kw), np.uint64)
    keycheck = np.zeros((config.depth, config.width), np.uint64)
    with np.errstate(over="ignore"):
        for p in payloads:
            # asarray, not astype: hh_inv payloads are u64 by
            # construction (codec._u64_plane) — astype would allocate a
            # throwaway copy of every plane set per member per merge
            cms += np.asarray(p["cms"], dtype=np.uint64)
            keysum += np.asarray(p["keysum"], dtype=np.uint64)
            keycheck += np.asarray(p["keycheck"], dtype=np.uint64)
    table_keys, table_vals = inv_extract(
        {"cms": cms, "keysum": keysum, "keycheck": keycheck},
        config.capacity)
    out = {"kind": "hh", "cms": cms, "table_keys": table_keys,
           "table_vals": table_vals, "keysum": keysum,
           "keycheck": keycheck}
    audits = [p["audit"] for p in payloads
              if p.get("audit") is not None]
    if audits:
        out["audit"] = merge_audit(audits)
    return out


def merge_audit(parts: list[dict]) -> dict:
    """Fold audit partials ({keys [K, W] u32, vals [K, P+1] u64}) into
    one: per-key uint64 sums, keys in lexicographic order (the same
    canonical order members serialize, so merge(one part) == the part
    bit-for-bit and the mesh-vs-oracle equality is array equality)."""
    real = [p for p in parts if len(p["keys"])]
    evictions = int(sum(int(p.get("evictions", 0)) for p in parts))
    scale = int(max(int(p.get("scale", 1)) for p in parts))
    if not real:
        first = parts[0]
        return {"keys": first["keys"][:0].astype(np.uint32),
                "vals": first["vals"][:0].astype(np.uint64),
                "evictions": evictions, "scale": scale}
    keys = np.concatenate([p["keys"].astype(np.uint32) for p in real])
    vals = np.concatenate([p["vals"].astype(np.uint64) for p in real])
    order, starts = _lex_regroup(keys)
    return {"keys": np.ascontiguousarray(keys[order][starts]),
            "vals": np.add.reduceat(vals[order], starts, axis=0),
            "evictions": evictions, "scale": scale}


def hh_top_rows(merged: dict, config: HeavyHitterConfig, k: int,
                slot: int) -> dict[str, np.ndarray]:
    """Columnar top-k rows from one merged hh payload — the numpy twin of
    models.heavy_hitter._top_from_state plus the timeslot column
    WindowedHeavyHitter stamps at window close, so merged output rows are
    shape- and dtype-identical to a single worker's."""
    k = min(k, config.capacity)
    keys = merged["table_keys"][:k]
    vals = merged["table_vals"][:k]
    valid = (keys != _SENTINEL).any(axis=1)
    ests = np_cms_query(merged["cms"], keys)[:k]
    out: dict[str, np.ndarray] = {}
    col = 0
    for name in config.key_cols:
        w = lane_width(name)
        out[name] = keys[:, col:col + w] if w == 4 else keys[:, col]
        col += w
    for j, name in enumerate(config.value_cols):
        out[name] = vals[:, j]
        out[f"{name}_est"] = ests[:, j]
    out["count"] = vals[:, -1]
    out["count_est"] = ests[:, -1]
    out["valid"] = valid
    out["timeslot"] = np.full(len(valid), slot, dtype=np.uint64)
    return out


# ---- spread (distinct-count) sketch state ---------------------------------


def merge_spread(payloads: list[dict], config) -> dict:
    """Fold spread payloads into one merged {regs, table_keys,
    table_metric}.

    Registers: element-wise u8 MAX — the HLL register plane is an exact
    max monoid over the element stream (ops/spread.py), so the max of
    per-shard planes IS the plane of the union stream, bit-exactly,
    for any member count and any stream split. Candidate tables:
    concat -> group-by-key SUM of the admission metric (each member's
    metric is its accumulated per-chunk distinct-pair count — a valid
    union-bound upper bound on the key's true distinct count; the sum
    preserves that bound but is NOT chunking-invariant, since members
    chunk their own sub-streams), ranked metric-descending with the
    stable lex tie-break, truncated to capacity. The metric only
    decides which keys stay tracked — reported spread values are
    decoded from the merged registers at extraction (spread_top_rows),
    never from the metric, so merged answers are exact wherever the
    register planes are."""
    from ..models.spread import spread_key_width

    if any(p.get("kind") != "spread" for p in payloads):
        # one family must fold ONE payload shape mesh-wide: a spread
        # max fold has no meaning over hh/dense sum payloads
        raise ValueError(
            "cannot merge mixed spread/non-spread payloads for one "
            "family — every member must run the same model kind")
    regs = np.zeros((config.depth, config.width, config.registers),
                    np.uint8)
    rows_k, rows_m = [], []
    for p in payloads:
        np.maximum(regs, np.asarray(p["regs"], dtype=np.uint8), out=regs)
        tk = p["table_keys"].astype(np.uint32)
        tm = p["table_metric"].astype(np.float32)
        real = (tk != _SENTINEL).any(axis=1)
        rows_k.append(tk[real])
        rows_m.append(tm[real])
    kw = spread_key_width(config)
    new_keys = np.full((config.capacity, kw), _SENTINEL, np.uint32)
    new_metric = np.zeros(config.capacity, np.float32)
    keys = np.concatenate(rows_k) if rows_k else new_keys[:0]
    metric = np.concatenate(rows_m) if rows_m else new_metric[:0]
    if len(keys):
        order, starts = _lex_regroup(keys)
        uniq = keys[order][starts]
        sums = np.add.reduceat(metric[order], starts).astype(np.float32)
        top = np.argsort(-sums, kind="stable")[:config.capacity]
        new_keys[:len(top)] = uniq[top]
        new_metric[:len(top)] = sums[top]
    return {"kind": "spread", "regs": regs, "table_keys": new_keys,
            "table_metric": new_metric}


def spread_top_rows(merged: dict, config, k: int,
                    slot: int) -> dict[str, np.ndarray]:
    """Columnar top-k rows from one merged spread payload — the shared
    decode-at-read extraction (models.spread.spread_top_from: rank by
    register-decoded spread, stable lex tie-break) plus the timeslot
    column WindowedHeavyHitter stamps at window close, so merged output
    rows are shape- and dtype-identical to a single worker's."""
    from ..models.spread import spread_top_from

    top = spread_top_from(merged, config, k)
    top["timeslot"] = np.full(len(top["valid"]), slot, dtype=np.uint64)
    return top


# ---- dense accumulators ---------------------------------------------------


def merge_dense(payloads: list[dict], config=None) -> np.ndarray:
    """Element-wise int64 sum of dense (lo, hi) planes. ``config`` is
    unused (the sum is shape-generic) but accepted so every registered
    family's merge hook shares one signature (families/registry.py)."""
    out = payloads[0]["totals"].astype(np.int64).copy()
    for p in payloads[1:]:
        out += p["totals"].astype(np.int64)
    return out


def dense_top_rows(totals: np.ndarray, config, k: int,
                   slot: int) -> dict[str, np.ndarray]:
    """Top-k rows from merged dense totals, via the model's own exact
    extraction (summed lo planes stay far below int32 before the exact
    lo + (hi << 16) recombination)."""
    from ..models.dense_top import DenseTopKModel

    model = DenseTopKModel.__new__(DenseTopKModel)
    model.config = config
    model.totals = np.asarray(totals, dtype=np.int64).astype(np.int32)
    top = model.top(k)
    top["timeslot"] = np.full(len(top["valid"]), slot, dtype=np.uint64)
    return top
