"""meshscope: mesh-wide trace aggregation with cross-process clock
alignment.

r11's flowtrace answers "why was THIS chunk slow" inside one process;
a flowmesh spreads one window's life across a coordinator and N member
processes, each with its own wall clock. This module supplies the two
primitives that turn N per-process flight recorders into ONE causal
timeline:

- **Clock offset estimation** (NTP-style midpoint): a requester stamps
  ``t0``/``t1`` around a round-trip whose reply carries the remote
  wall clock; ``offset = remote_now - (t0 + t1) / 2`` estimates
  ``remote_clock - local_clock`` with error bounded by ``rtt / 2``
  (the reply could have been generated anywhere inside the trip).
  ``ClockSync`` keeps a sliding window of samples and answers with the
  minimum-RTT one — the tightest bound observed — which the member
  piggybacks on its heartbeat so the coordinator always holds a fresh
  per-member estimate.

- **Trace aggregation**: ``aggregate_traces`` merges per-process
  Chrome traces into one, assigning each source its own ``pid`` lane
  (with ``process_name`` metadata so Perfetto labels the lanes) and
  shifting every member timestamp by its estimated offset onto the
  coordinator clock. The shift is a constant per lane, i.e. a MONOTONE
  transformation: each lane's internal event order is preserved
  exactly, and cross-lane ordering is correct up to the per-lane
  ``rtt / 2`` error bound recorded in ``otherData.lanes``.

The coordinator's ``/debug/trace`` (mesh/server.py) fans out to every
member's ``/debug/trace`` and feeds the results through here; the
heartbeat estimates ride ``sync()`` (mesh/member.py _call_sync).
"""

from __future__ import annotations

# flowlint: lock-checked
# (ClockSync instances live on a single member driver thread; the
# aggregation functions are pure)

from collections import deque
from dataclasses import dataclass
from typing import Optional


def estimate_offset(t0: float, t1: float,
                    remote_now: float) -> tuple[float, float]:
    """One NTP-midpoint sample: ``(offset, rtt)`` where ``offset`` is
    the estimate of ``remote_clock - local_clock`` in seconds and the
    true offset lies within ``rtt / 2`` of it."""
    rtt = max(0.0, t1 - t0)
    return remote_now - (t0 + t1) / 2.0, rtt


class ClockSync:
    """Sliding best-of-N offset estimator (member side). ``add()`` one
    sample per heartbeat round-trip; ``best()`` answers with the
    minimum-RTT sample in the window — RTT spikes (a stalled executor,
    a slow accept loop) widen the midpoint bound, so the tightest trip
    wins. Single-threaded by construction (the member driver thread)."""

    def __init__(self, window: int = 16):
        # flowlint: unguarded -- driver thread only (see module header)
        self._samples: deque[tuple[float, float]] = deque(maxlen=window)

    def add(self, t0: float, t1: float, remote_now: float) -> None:
        offset, rtt = estimate_offset(t0, t1, remote_now)
        self._samples.append((rtt, offset))

    def best(self) -> Optional[tuple[float, float]]:
        """(offset, rtt) of the tightest sample, or None before any."""
        if not self._samples:
            return None
        rtt, offset = min(self._samples)
        return offset, rtt

    def report(self) -> Optional[dict]:
        """The heartbeat payload: {"offset": remote-local s, "rtt": s}
        (None before the first sample — sync() omits the field)."""
        best = self.best()
        if best is None:
            return None
        return {"offset": best[0], "rtt": best[1]}


@dataclass
class TraceLane:
    """One process's contribution to the aggregate: its Chrome trace
    plus the clock estimate that aligns it. ``offset_s`` is this
    process's clock minus the reference (coordinator) clock; the
    reference lane passes 0."""

    name: str
    trace: dict
    offset_s: float = 0.0
    rtt_s: float = 0.0


def aggregate_traces(lanes: list[TraceLane]) -> dict:
    """Merge per-process Chrome traces into one clock-aligned trace.

    The FIRST lane is the reference clock (the coordinator). Each lane
    gets its own synthetic ``pid`` (stable: list order) with
    ``process_name`` / ``process_sort_index`` metadata events so
    Perfetto renders one labeled process track per mesh node; member
    event timestamps are shifted by ``-offset_s`` onto the reference
    clock (a constant per lane — order within a lane is preserved).
    ``otherData.lanes`` records each lane's offset, RTT, and the
    ``rtt/2`` alignment error bound."""
    events: list[dict] = []
    meta_lanes: list[dict] = []
    for i, lane in enumerate(lanes):
        pid = i + 1  # synthetic: the real pids may collide across hosts
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": lane.name}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": pid, "args": {"sort_index": i}})
        shift_us = lane.offset_s * 1e6
        n = 0
        for ev in lane.trace.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") != "M" and "ts" in ev:
                ev["ts"] = round(ev["ts"] - shift_us, 1)
            events.append(ev)
            n += 1
        other = lane.trace.get("otherData") or {}
        meta_lanes.append({
            "name": lane.name,
            "pid": pid,
            "events": n,
            "clock_offset_ms": round(lane.offset_s * 1e3, 3),
            "rtt_ms": round(lane.rtt_s * 1e3, 3),
            "alignment_error_bound_ms": round(lane.rtt_s * 1e3 / 2, 3),
            "mode": other.get("mode"),
            "dropped_spans": other.get("dropped_spans", 0),
        })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "flow-pipeline-tpu meshscope",
            "reference": lanes[0].name if lanes else None,
            "lanes": meta_lanes,
        },
    }
