"""flowchaos coordinator write-ahead journal.

The mesh coordinator was the one process in the estate with NO recovery
story: the partition frontiers, epoch, carries and merged-window ledger
lived purely in memory, so a coordinator crash lost the network-wide
merge the whole mesh exists to compute. This journal makes the
coordinator's protocol decisions durable with an append-only,
fsync-batched log (``-mesh.journal=<dir>``):

Record kinds (``mesh/coordinator.py`` appends, ``replay()`` yields):

- ``sub``    one ACCEPTED member submission — the member id plus the
             verbatim codec envelope (ranges that extended the
             frontier, watermark, closed windows, the open-window
             CARRY replacement, final/release flags). Journaled under
             the coordinator lock, fsynced BEFORE the ok ack returns,
             so an acked submission is always recoverable.
- ``fence``  a member death/zombie fence — its carry was promoted into
             the pending barrier at this point in the record order.
- ``epoch``  an assignment-epoch bump (rebalance).
- ``merged`` one (model, slot) window merged AND emitted to the sinks
             — replay skips re-emitting it. Written AFTER the sink
             writes: a crash inside the sink-write -> journal gap
             re-merges and re-emits that window on recovery, the same
             irreducible at-least-once window as the worker's
             flush -> snapshot gap (docs/FAULT_TOLERANCE.md).
- ``chk``    a COMPACTION checkpoint: the coordinator's recoverable
             state (frontier, epoch, current carries, pending barrier
             contributions, merged-window keys) as one codec envelope.
             Written by :meth:`CoordinatorJournal.compact` as the FIRST
             record of a fresh file that atomically replaces the old
             one — every superseded record (every carry an accepted
             submission replaced, every sub folded into an
             already-merged window) is dropped. BENCH_r17 measured 379
             MB for 35 records precisely because each ``sub`` carries
             its full envelope (CMS planes included); compaction is
             what lets a long-running mesh journal at production
             cadence. Recovery from a compacted journal is bit-exact
             vs replaying the uncompacted history (tests/test_chaos.py
             pins it).

Durability contract: ``append()`` buffers under the journal lock (the
caller may hold the coordinator lock — appends are a buffered write,
never an fsync); ``sync()`` is the group-commit barrier — one
flush+fsync covers every record appended since the last, so N members
acking concurrently share one disk flush.

Recovery (coordinator ``__init__`` with a journal): replay every record
in order through the SAME fold paths the live protocol uses, tolerant
of a torn tail (a crash mid-append leaves a short/CRC-failing final
record — everything before it was the acked state). The recovered
coordinator then fences the old incarnation's remaining carries
(journaling those fences so a second crash replays identically), bumps
the epoch, and lets the zombie/rejoin machinery re-admit the members:
an old-incarnation member is simply unknown, gets ``rejoin``, abandons
its un-acked state and replays from the recovered frontier — which is
exactly the exactness argument the kill-one-WORKER leg already pins,
now applied to the coordinator itself.

Wire format: ``FJRNL1\\n`` file magic, then per record
``u32 body_len | u32 crc32(body) | body`` where ``body`` is one JSON
header line + ``\\n`` + an optional binary blob (the codec envelope).
The file is append-only between compactions: at merged-window
boundaries the coordinator snapshots live protocol state into one
``chk`` record and truncates the superseded history (the journal holds
protocol metadata + open-window state, not merged row history — sinks
remain the durable home of output).
"""

from __future__ import annotations

# flowlint: lock-checked
# (appends come from member-facing coordinator paths on many threads;
# one lock guards the file handle and the dirty/lag bookkeeping. The
# fsync in sync() runs under that lock — a deliberate group-commit
# serialization, documented above.)
# flowlint: durable-checked
# (every write goes through utils/fsutil so the durability-protocol
# rule can check the sequence and the crash-point model checker can
# record it — docs/STATIC_ANALYSIS.md "durability-protocol")

import json
import os
import struct
import threading
import time
import zlib
from typing import Iterator, Optional

from ..obs import get_logger
from ..utils import fsutil

log = get_logger("mesh")

MAGIC = b"FJRNL1\n"
_HEAD = struct.Struct("<II")  # body_len, crc32(body)

JOURNAL_FILE = "coordinator.journal"


class CoordinatorJournal:
    """One append-only journal file under ``dir``. ``metrics`` is an
    optional dict with ``records`` (Counter, label kind),
    ``unsynced`` (Gauge) and ``lag`` (Gauge) — the coordinator passes
    its eagerly-registered families so dashboards resolve whether or
    not a journal exists."""

    def __init__(self, dir_: str, metrics: Optional[dict] = None):
        os.makedirs(dir_, exist_ok=True)
        self.dir = dir_
        self.path = os.path.join(dir_, JOURNAL_FILE)
        size = os.path.getsize(self.path) \
            if os.path.exists(self.path) else 0
        if 0 < size < len(MAGIC):
            # a crash during the very FIRST init tore the magic write
            # (nothing was ever acked against this file): start fresh
            # rather than wedging every subsequent startup on it
            log.warning("journal %s: torn file magic (%d bytes); "
                        "starting a fresh journal", self.path, size)
            # flowlint: disable=durability-protocol -- deliberate raw truncate: nothing was ever acked against a torn-magic file, and the fresh magic below rides the full fsync+dir-fsync sequence
            os.truncate(self.path, 0)
            size = 0
        # flowlint: unguarded -- the lock itself; bound once
        self._lock = threading.Lock()
        self._f = fsutil.open_durable(self.path, "ab")  # guarded-by: _lock
        self._dirty = 0  # records appended, not yet fsynced  # guarded-by: _lock
        self._oldest_dirty = 0.0  # wall stamp of the oldest unsynced append  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self._bytes = size  # file size incl. magic  # guarded-by: _lock
        self._m = metrics or {}
        if self._m.get("bytes") is not None:
            self._m["bytes"].set(size)
        if size == 0:
            with self._lock:
                self._f.write(MAGIC)
                fsutil.fsync_file(self._f)
                self._bytes = len(MAGIC)
            if self._m.get("bytes") is not None:
                self._m["bytes"].set(len(MAGIC))
            # the DIRECTORY entry must be durable too: fsyncing file
            # contents alone does not persist a freshly created name —
            # power loss could otherwise drop the whole journal file
            # after acks went out, silently voiding the recovery
            # contract
            fsutil.fsync_dir(dir_)

    # ---- write side --------------------------------------------------------

    def append(self, kind: str, meta: dict, blob: bytes = b"") -> None:
        """Buffer one record (cheap: an in-process file write). Callers
        that need durability call ``sync()`` before acking."""
        header = json.dumps({"t": kind, **meta}).encode() + b"\n"
        body = header + blob
        rec = _HEAD.pack(len(body), zlib.crc32(body)) + body
        now = time.time()
        with self._lock:
            if self._closed:
                return
            # durable: group-commit=sync -- appends are buffered by design; sync() is the fsync barrier every acking caller crosses first
            self._f.write(rec)
            self._bytes += len(rec)
            nbytes = self._bytes
            if self._dirty == 0:
                self._oldest_dirty = now
            self._dirty += 1
            dirty = self._dirty
            oldest = self._oldest_dirty
        if self._m:
            self._m["records"].inc(kind=kind)
            self._m["unsynced"].set(dirty)
            self._m["lag"].set(now - oldest)
            if self._m.get("bytes") is not None:
                self._m["bytes"].set(nbytes)

    def sync(self) -> None:
        """Group-commit barrier: flush + fsync everything appended so
        far. A no-op when clean; concurrent callers whose records were
        covered by another caller's fsync return immediately."""
        with self._lock:
            if self._closed or self._dirty == 0:
                return
            fsutil.fsync_file(self._f)
            self._dirty = 0
        if self._m:
            self._m["unsynced"].set(0)
            self._m["lag"].set(0.0)

    def size_bytes(self) -> int:
        """Current journal file size (buffered writes included) — the
        compaction trigger's input and the mesh_journal_bytes gauge."""
        with self._lock:
            return self._bytes

    def compact(self, meta: dict, blob: bytes) -> None:
        """Checkpoint + truncate: atomically replace the journal with a
        fresh file whose FIRST (and only) record is a ``chk`` carrying
        the coordinator's recoverable state. The caller must serialize
        against its own appenders (the coordinator holds its _lock —
        an append racing the swap would land in the dead file and be
        silently lost). Crash-safe at every step: the new file is
        fully written + fsynced BEFORE the rename, the rename is atomic,
        and the directory entry is fsynced after — a crash leaves either
        the complete old journal or the complete compacted one."""
        header = json.dumps({"t": "chk", **meta}).encode() + b"\n"
        body = header + blob
        rec = _HEAD.pack(len(body), zlib.crc32(body)) + body
        tmp = self.path + ".compact"
        with self._lock:
            if self._closed:
                return
            # flush the old handle first: buffered appends must not
            # outlive the swap and resurface via the stale fd
            fsutil.fsync_file(self._f)
            with fsutil.open_durable(tmp, "wb") as f:
                f.write(MAGIC)
                f.write(rec)
                fsutil.fsync_file(f)
            fsutil.replace(tmp, self.path)
            self._f.close()
            self._f = fsutil.open_durable(self.path, "ab")
            self._bytes = len(MAGIC) + len(rec)
            self._dirty = 0
            nbytes = self._bytes
        fsutil.fsync_dir(self.dir)
        if self._m:
            self._m["records"].inc(kind="chk")
            self._m["unsynced"].set(0)
            self._m["lag"].set(0.0)
            if self._m.get("bytes") is not None:
                self._m["bytes"].set(nbytes)
        log.info("journal %s compacted to %d bytes (checkpoint + "
                 "truncate)", self.path, nbytes)

    def close(self) -> None:
        self.sync()
        with self._lock:
            if not self._closed:
                self._closed = True
                self._f.close()

    # ---- read side ---------------------------------------------------------

    def replay(self) -> Iterator[tuple[str, dict, bytes]]:
        """Yield (kind, meta, blob) for every intact record, stopping —
        with a warning, not an error — at a torn tail (truncated or
        CRC-failing final record: the crash interrupted an append whose
        ack never went out)."""
        yield from replay_journal(self.path)


def replay_journal(path: str) -> Iterator[tuple[str, dict, bytes]]:
    """Replay a journal file (see :class:`CoordinatorJournal.replay`)."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if len(magic) < len(MAGIC):
            # torn first-init write: nothing was ever acked against
            # this file — recover to empty, don't wedge startup
            log.warning("journal %s: torn file magic; treating as "
                        "empty", path)
            return
        if magic != MAGIC:
            # a FULL-length mismatch is a foreign file, not a torn
            # write — refuse rather than silently ignore its contents
            raise ValueError(f"{path}: not a coordinator journal "
                             "(bad magic)")
        n = 0
        while True:
            head = f.read(_HEAD.size)
            if len(head) < _HEAD.size:
                if head:
                    log.warning("journal %s: torn record header after "
                                "%d records; recovering to there", path, n)
                return
            body_len, crc = _HEAD.unpack(head)
            body = f.read(body_len)
            if len(body) < body_len or zlib.crc32(body) != crc:
                log.warning("journal %s: torn/corrupt record after %d "
                            "records; recovering to there", path, n)
                return
            nl = body.index(b"\n")
            meta = json.loads(body[:nl].decode())
            kind = meta.pop("t")
            n += 1
            yield kind, meta, body[nl + 1:]
