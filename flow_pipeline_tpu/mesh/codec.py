"""flowmesh merge codec: serialized per-window sketch/aggregate state.

Contributions cross the mesh (member -> coordinator) as one framed byte
envelope: a JSON structure tree plus an in-memory ``.npz`` archive of
every array leaf — the same no-pickle split engine.checkpoint uses for
durable snapshots, so a payload is safe to accept from another trust
domain and survives encode -> decode BIT-exactly on the uint64
envelope (dtype + shape + every word preserved; tests/test_mesh.py
round-trips u64 extremes and hostsketch engine state).

The canonical heavy-hitter payload keeps the CMS in **uint64** (the
exact merge monoid — element sums cannot lose counts the way float
addition can), converting device f32 sketches through hostsketch's
proven clamp conversions. Table keys stay uint32, table values float32
(the device accumulation dtype — merging sums them per key, which for
key-hash-sharded streams is a disjoint union and therefore exact).
"""

from __future__ import annotations

import io
import json

import numpy as np

from ..engine.checkpoint import _decode, _encode
from ..families import registry
from ..hostsketch.state import (HostHHState, frozen_cms, is_inv_state)

MAGIC = b"FMSH1\n"


def encode(obj) -> bytes:
    """Nested dicts/lists/tuples/scalars/arrays -> framed bytes."""
    arrays: dict[str, np.ndarray] = {}
    meta = json.dumps(_encode(obj, arrays, "r")).encode()
    buf = io.BytesIO()
    # savez (uncompressed): payloads are hot-path window state, and the
    # arrays (CMS planes) are incompressible counter noise anyway
    np.savez(buf, **arrays)
    return MAGIC + len(meta).to_bytes(8, "little") + meta + buf.getvalue()


def decode(data: bytes):
    """Framed bytes -> the original structure with numpy array leaves."""
    if not data.startswith(MAGIC):
        raise ValueError("not a flowmesh payload (bad magic)")
    off = len(MAGIC)
    meta_len = int.from_bytes(data[off:off + 8], "little")
    off += 8
    meta = json.loads(data[off:off + meta_len].decode())
    blob = data[off + meta_len:]
    arrays = np.load(io.BytesIO(blob)) if blob else {}
    return _decode(meta, arrays)


# ---- model-state capture --------------------------------------------------
#
# One payload shape per model kind, all plain numpy (no jax arrays cross
# the mesh). ``kind`` tags dispatch the coordinator-side merge.


def _u64_plane(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a), dtype=np.uint64).copy()


def hh_payload(state) -> dict:
    """Device/host HHState (or checkpoint field-dict) -> canonical
    uint64-CMS payload. Accepts jax or numpy leaves; always copies
    (frozen_cms is the shared hostsketch export seam).

    Invertible-family states (InvState / HostInvState / field dicts
    with key-recovery planes) ship as ``kind="hh_inv"``: the three u64
    plane sets verbatim — self-contained and LINEAR, so the
    coordinator's merge is a plain element-wise u64 sum (merge_hh
    dispatches on the kind) and there is no extracted table to ship
    until the merged window is decoded at close."""
    if is_inv_state(state):
        if isinstance(state, dict):
            ks, kc = state["keysum"], state["keycheck"]
        else:
            ks, kc = state.keysum, state.keycheck
        return {"kind": "hh_inv", "cms": frozen_cms(state),
                "keysum": _u64_plane(ks), "keycheck": _u64_plane(kc)}
    if isinstance(state, HostHHState):
        return {"kind": "hh", "cms": frozen_cms(state),
                "table_keys": state.table_keys.copy(),
                "table_vals": state.table_vals.copy()}
    if isinstance(state, dict):
        tk, tv = state["table_keys"], state["table_vals"]
    else:
        tk, tv = state.table_keys, state.table_vals
    return {
        "kind": "hh",
        "cms": frozen_cms(state),
        "table_keys": np.ascontiguousarray(np.asarray(tk),
                                           dtype=np.uint32).copy(),
        "table_vals": np.ascontiguousarray(np.asarray(tv),
                                           dtype=np.float32).copy(),
    }


def wagg_payload(store: dict) -> dict:
    """One window-store dict {key tuple -> uint64 [values..., count]} ->
    columnar (keys [G, L] uint32, vals [G, V] uint64) payload."""
    if not store:
        return {"kind": "wagg",
                "keys": np.zeros((0, 0), np.uint32),
                "vals": np.zeros((0, 0), np.uint64)}
    lanes = len(next(iter(store)))
    keys = np.fromiter((x for key in store for x in key), dtype=np.uint64,
                       count=len(store) * lanes).reshape(len(store), lanes)
    vals = np.stack([np.asarray(v, dtype=np.uint64)
                     for v in store.values()])
    return {"kind": "wagg", "keys": keys.astype(np.uint32), "vals": vals}


def dense_payload(totals) -> dict:
    """Dense accumulator planes -> payload (int64: the (lo, hi) int32
    planes sum across members, and int64 headroom makes N-member merge
    overflow a non-issue before renormalization)."""
    return {"kind": "dense",
            "totals": np.asarray(totals).astype(np.int64)}


def spread_payload(state) -> dict:
    """SpreadState (or checkpoint field dict) -> canonical spread
    payload. The u8 register planes are already the exact max-monoid
    canonical form (models/spread.py), so the payload ships them
    verbatim; the candidate table rides as u32 keys + f32 admission
    metric, exactly like the hh table legs."""
    if isinstance(state, dict):
        regs, tk, tm = (state["regs"], state["table_keys"],
                        state["table_metric"])
    else:
        regs, tk, tm = state.regs, state.table_keys, state.table_metric
    return {
        "kind": "spread",
        "regs": np.ascontiguousarray(np.asarray(regs),
                                     dtype=np.uint8).copy(),
        "table_keys": np.ascontiguousarray(np.asarray(tk),
                                           dtype=np.uint32).copy(),
        "table_metric": np.ascontiguousarray(np.asarray(tm),
                                             dtype=np.float32).copy(),
    }


def capture_model(model) -> dict:
    """State payload for one windowed model (the object WindowedHeavyHitter
    wraps): the family registry maps the model's snapshot_kind tag to
    its payload hook and state attribute."""
    kind = getattr(model, "snapshot_kind", None)
    fam = registry.family_for_snapshot(kind) if kind else None
    if fam is None or fam.payload is None or fam.state_attr is None:
        raise TypeError(f"no mesh payload for model kind {kind!r}")
    return registry.hook(fam, "payload")(getattr(model, fam.state_attr))
