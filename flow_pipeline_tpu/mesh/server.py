"""flowmesh HTTP transport: the coordinator protocol across processes.

The in-process objects (MeshCoordinator / MeshMember) speak plain
method calls; this module carries the same calls over HTTP so the
compose topology (deploy/compose/mesh.yml: coordinator + N worker
containers) runs the identical protocol:

    POST /mesh/join    {"member": id, "state_url": url|null,
                        "trace_url": url|null}
    POST /mesh/sync    {"member": id, "clock": {offset, rtt}|null}
    POST /mesh/submit?member=id   (octet-stream: mesh/codec envelope)
    POST /mesh/leave   {"member": id}
    GET  /topk?model=M&k=N        merged open-window view (fan-out)
    GET  /debug/lineage?model=M&slot=S   meshscope window lineage
    GET  /debug/trace             ONE clock-aligned mesh-wide Chrome
                                  trace (coordinator lane + fan-out to
                                  every member's /debug/trace)
    GET  /healthz /state          liveness + protocol introspection

``RemoteCoordinator`` duck-types MeshCoordinator for MeshMember, and
``MemberStateServer`` is the member-side /meshstate endpoint the
coordinator's /topk fan-out queries.
"""

from __future__ import annotations

# flowlint: lock-checked
# (handlers delegate to the coordinator/member objects, which carry
# their own locking contracts; the servers themselves only bind
# immutable attributes after __init__)
# flowlint: net-checked
# (every urlopen here crosses a process boundary during churn — the
# exact moment a peer may be hung; the r13 trace fan-out bug was one
# missing timeout in this module's class of call)

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..obs import get_logger
from . import scope
from .coordinator import MeshCoordinator

log = get_logger("mesh")


def _url_provider(state_url: str):
    """Wrap a member's /meshstate URL as a coordinator provider."""
    def provider(model: str):
        req = urllib.request.Request(
            f"{state_url}?model={urllib.parse.quote(model)}")
        with urllib.request.urlopen(req, timeout=5) as resp:
            if resp.status == 204:
                return None
            return resp.read()
    return provider


class MeshCoordinatorServer:
    """HTTP front of one MeshCoordinator + a background expiry sweep."""

    def __init__(self, coordinator: MeshCoordinator, port: int = 8090,
                 host: str = "127.0.0.1"):
        self.coordinator = coordinator
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                url = urlparse(self.path)
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                try:
                    if url.path == "/mesh/submit":
                        q = {k: v[0] for k, v in
                             parse_qs(url.query).items()}
                        out = outer.coordinator.submit(q["member"], body)
                    elif url.path in ("/mesh/join", "/mesh/sync",
                                      "/mesh/leave"):
                        req = json.loads(body or b"{}")
                        member = req["member"]
                        if url.path == "/mesh/join":
                            provider = (_url_provider(req["state_url"])
                                        if req.get("state_url") else None)
                            out = outer.coordinator.join(
                                member, provider=provider,
                                trace_url=req.get("trace_url"))
                        elif url.path == "/mesh/sync":
                            out = outer.coordinator.sync(
                                member, clock=req.get("clock"))
                        else:
                            outer.coordinator.leave(member)
                            out = {}
                    else:
                        self._reply(404, {"error": url.path})
                        return
                    self._reply(200, out)
                except (KeyError, ValueError) as e:
                    self._reply(400, {"error": str(e)})

            def do_GET(self):  # noqa: N802
                url = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(url.query).items()}
                try:
                    if url.path == "/topk":
                        k = int(q["k"]) if "k" in q else None
                        out = outer.coordinator.query_topk(
                            q.get("model"), k)
                    elif url.path == "/debug/lineage":
                        slot = int(q["slot"]) if "slot" in q else None
                        out = outer.coordinator.lineage(
                            q.get("model"), slot)
                    elif url.path == "/debug/trace":
                        out = outer.aggregated_trace()
                    elif url.path == "/healthz":
                        st = outer.coordinator.status()
                        out = {"ok": True, "epoch": st["epoch"],
                               "members": len(st["members"])}
                    elif url.path == "/state":
                        out = outer.coordinator.status()
                    else:
                        self._reply(404, {"error": url.path})
                        return
                    self._reply(200, out)
                except (KeyError, ValueError) as e:
                    # ValueError covers malformed query params
                    # (e.g. /topk?k=abc) — 400, not a handler traceback
                    self._reply(400, {"error": str(e)})

            def _reply(self, code, obj):
                from ..obs.server import reply_json

                reply_json(self, obj, code, default=str)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="mesh-http",
            daemon=True)
        self._sweep_stop = threading.Event()
        self._sweeper = threading.Thread(
            target=self._sweep, name="mesh-expiry", daemon=True)

    def _sweep(self) -> None:
        period = max(0.5, self.coordinator.heartbeat_timeout / 2)
        while not self._sweep_stop.wait(period):
            for mid in self.coordinator.expire():
                log.warning("mesh expiry: fenced silent member %s", mid)

    def aggregated_trace(self) -> dict:
        """meshscope: ONE clock-aligned Chrome trace for the whole
        mesh. The coordinator's own flight recorder is the reference
        lane; every live member that advertised a trace_url at join is
        fetched, its clock aligned by the heartbeat-estimated offset
        (mesh/scope.py — falling back to an estimate from THIS fetch's
        round-trip when no heartbeat sample exists yet), and an
        unreachable member degrades the aggregate (logged, lane
        skipped) rather than blacking it out."""
        from concurrent.futures import ThreadPoolExecutor

        from ..obs.trace import TRACER

        def fetch(source):
            mid, trace_url, offset, rtt = source
            t0 = time.time()
            try:
                with urllib.request.urlopen(trace_url, timeout=5) as resp:
                    tr = json.loads(resp.read().decode())
            except (OSError, ValueError) as e:
                log.warning("meshscope: member %s trace fetch failed "
                            "(%s); aggregating without it", mid, e)
                return None
            t1 = time.time()
            if offset is None:
                now = (tr.get("otherData") or {}).get("now")
                if now is not None:
                    offset, rtt = scope.estimate_offset(t0, t1,
                                                        float(now))
                else:
                    offset, rtt = 0.0, 0.0
            return scope.TraceLane(mid, tr, offset, rtt)

        lanes = [scope.TraceLane("coordinator", TRACER.chrome_trace())]
        sources = self.coordinator.trace_sources()
        if sources:
            # concurrent fan-out: the fetches are independent, and the
            # aggregate is wanted most during churn — exactly when some
            # members are unreachable. Serial fetches would stack one
            # 5s timeout per dead member onto the handler thread.
            with ThreadPoolExecutor(
                    max_workers=min(8, len(sources))) as pool:
                lanes += [lane for lane in pool.map(fetch, sources)
                          if lane is not None]
        return scope.aggregate_traces(lanes)

    def start(self) -> "MeshCoordinatorServer":
        self._thread.start()
        self._sweeper.start()
        log.info("mesh coordinator on http://%s:%d", self.host, self.port)
        return self

    def stop(self) -> None:
        self._sweep_stop.set()
        self._server.shutdown()
        self._server.server_close()


class RemoteCoordinator:
    """MeshCoordinator duck type over HTTP (the member side)."""

    def __init__(self, base_url: str, state_url: str | None = None,
                 timeout: float = 10.0,
                 trace_url: str | None = None):
        self.base_url = base_url.rstrip("/")
        self.state_url = state_url
        self.trace_url = trace_url
        self.timeout = timeout

    def _post_json(self, path: str, obj: dict) -> dict:
        req = urllib.request.Request(
            self.base_url + path, data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode())

    def join(self, member_id: str, provider=None,
             trace_url: str | None = None) -> dict:
        # provider callables cannot cross HTTP; the member's state URL
        # (served by MemberStateServer) plays that role remotely, and
        # the trace URL is where the coordinator's mesh-wide
        # /debug/trace fans out to
        return self._post_json("/mesh/join", {
            "member": member_id, "state_url": self.state_url,
            "trace_url": trace_url or self.trace_url})

    def sync(self, member_id: str, clock: dict | None = None) -> dict:
        return self._post_json("/mesh/sync",
                               {"member": member_id, "clock": clock})

    def leave(self, member_id: str) -> None:
        self._post_json("/mesh/leave", {"member": member_id})

    def submit(self, member_id: str, payload: bytes) -> dict:
        req = urllib.request.Request(
            f"{self.base_url}/mesh/submit?member="
            f"{urllib.parse.quote(member_id)}",
            data=payload,
            headers={"Content-Type": "application/octet-stream"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode())


class MemberStateServer:
    """The member-side /meshstate endpoint for the /topk fan-out."""

    def __init__(self, member, port: int = 0, host: str = "127.0.0.1"):
        from . import codec

        outer_member = member

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                url = urlparse(self.path)
                q = {k: v[0] for k, v in parse_qs(url.query).items()}
                if url.path == "/healthz":
                    # compose healthchecks probe liveness here instead
                    # of inferring it from protocol traffic
                    from ..obs.server import reply_json

                    reply_json(self, {"ok": True})
                    return
                if url.path != "/meshstate" or "model" not in q:
                    self.send_response(404)
                    self.end_headers()
                    return
                state = outer_member._query_state(q["model"])
                if state is None:
                    self.send_response(204)
                    self.end_headers()
                    return
                body = codec.encode(state)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}/meshstate"
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="mesh-state",
            daemon=True)

    def start(self) -> "MemberStateServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
