"""flowmesh member: one StreamWorker under coordinator control.

A member wraps a full StreamWorker (free to run the fused host
dataplane — the models, pipelines, prefetch and flusher machinery are
untouched) and adds the mesh contract around it:

- window-close CAPTURE: the WindowAggregator / WindowedHeavyHitter
  capture hooks hand raw per-window state to the member instead of
  extracting rows locally; the member ships it to the coordinator as a
  serialized contribution (mesh/codec.py) tagged with the per-partition
  offset ranges it covers.
- OPEN-window carry: every submission also snapshots the still-open
  windows, so a member death costs its successor at most the rows since
  the last accepted submission (``submit_every`` bounds that mid-window)
  and never loses a window.
- assignment lifecycle: ``sync()`` heartbeats the coordinator; on a
  target change the member RESYNCs — final-submits everything with
  ``release``, drops the worker, and rebuilds fresh on its new
  partition set from the coordinator's offset frontier. A fenced
  (zombie) member abandons its un-submitted state — the successor
  replays those rows, which is exactly what keeps the merge exact.

DDoS detectors (when configured) stay per-shard: their alerts flow
through the member's own sinks, per the HashPipe per-shard-detection
model (PAPERS.md 1611.04825).
"""

from __future__ import annotations

# flowlint: lock-checked
# (a member is single-threaded by construction: step()/run() execute on
# ONE driver thread, and the capture hooks fire inside worker.run_once
# on that same thread. The only cross-thread entry is the coordinator's
# state-provider fan-out, which takes worker.lock and mutates nothing.)

import dataclasses
import time
from typing import Any, Callable, Optional, Sequence

from ..engine.prefetch import PrefetchConsumer
from ..engine.windowed import WindowedHeavyHitter
from ..engine.worker import StreamWorker, WorkerConfig
from ..models.window_agg import WindowAggregator
from ..obs import REGISTRY, get_logger
from ..obs.trace import TRACER
from ..utils.faults import FAULTS
from ..utils.retry import retry_call
from . import codec
from .scope import ClockSync

log = get_logger("mesh")

# flowchaos retry discipline on the member->coordinator HTTP edge
# (submit/sync/join/leave): bounded exponential backoff + jitter around
# transient transport failures. Retrying a submit is SAFE: if the lost
# ack was actually an accept, the coordinator dedupes on the span's
# per-member submission id and acks idempotently (folding nothing) —
# and for payloads without a span id, the frontier-extend contract
# rejects the non-extending ranges, after which the member abandons and
# rejoins, replaying from the covered frontier (no loss, no double
# count either way; tests/test_chaos.py pins both paths).
COORD_RETRIES = 5
COORD_BACKOFF = 0.05
COORD_BACKOFF_MAX = 1.0


class MeshMember:
    """Coordinator-driven StreamWorker shard."""

    def __init__(self, member_id: str, coordinator,
                 consumer_factory: Callable[[Sequence[int]], Any],
                 model_factory: Callable[[], dict],
                 config: WorkerConfig = WorkerConfig(),
                 sinks: Sequence[Any] = (),
                 submit_every: int = 0,
                 sync_interval: float = 0.2,
                 trace_url: Optional[str] = None):
        self.member_id = member_id
        self.coordinator = coordinator
        self.consumer_factory = consumer_factory
        self.model_factory = model_factory
        self.config = config
        self.sinks = list(sinks)
        # meshscope: this member's /debug/trace URL, advertised at
        # join() so the coordinator's mesh-wide /debug/trace can fan
        # out to it (None in-process: one shared TRACER already holds
        # every lane)
        self.trace_url = trace_url
        # >0: also submit a progress carry every N applied batches even
        # without a window close — bounds a successor's replay (and the
        # carry the coordinator can promote) to N batches mid-window
        self.submit_every = submit_every
        self.sync_interval = sync_interval
        # flowlint: unguarded -- driver thread only (see module header)
        self.worker: Optional[StreamWorker] = None
        # flowlint: unguarded -- driver thread only
        self._frontier: dict[int, int] = {}
        # slot -> {model: payload}: closed windows since the last submit
        # flowlint: unguarded -- driver thread only (capture hooks run inside run_once on this thread)
        self._captured: dict[int, dict] = {}
        # sketchwatch: slot -> {model: audit partial} sealed at the same
        # window closes — attached INSIDE the hh payloads at submit so
        # per-member exact cohorts merge at the coordinator as uint64
        # sums (network-wide accuracy, not per-shard)
        # flowlint: unguarded -- driver thread only (audit capture fires inside run_once on this thread)
        self._audit_captured: dict[int, dict] = {}
        # flowlint: unguarded -- driver thread only
        self._flows_reported = 0
        # flowlint: unguarded -- driver thread only
        self._batches_since_submit = 0
        # flowlint: unguarded -- driver thread only
        self._last_sync = 0.0
        # flowlint: unguarded -- driver thread only
        self._joined = False
        # flowlint: unguarded -- written by kill() (runtime thread) and read by run(); a plain latch flag
        self._dead = False
        # flowlint: unguarded -- written by the driver thread, read by the runtime's quiescence poll; a monotone-ish progress signal, not state
        self.idle_streak = 0
        # meshscope: per-member monotonic submission ids (the span
        # context every submission carries)
        # flowlint: unguarded -- driver thread only
        self._sub_seq = 0
        # heartbeat-fed clock offset estimator (mesh/scope.py): every
        # sync() round-trip adds an NTP-midpoint sample; the best
        # (min-RTT) estimate rides the next sync to the coordinator
        # flowlint: unguarded -- driver thread only
        self._clock = ClockSync()
        # one identity per process: the inner StreamWorker publishes
        # flow_build_info, and in a member process it must say so —
        # a second role="worker" series would be a double identity
        self.config = dataclasses.replace(self.config,
                                          build_role="member")
        # flowchaos: last coordinator-unreachable warning stamp (the
        # sync path retries every step — one log line per outage window,
        # not one per attempt)
        # flowlint: unguarded -- driver thread only
        self._last_down_log = 0.0
        self.m_retries = REGISTRY.counter(
            "mesh_member_retries_total",
            "member->coordinator calls retried after a transport "
            "failure (label: op)")

    # ---- coordinator transport (flowchaos retries) ------------------------

    def _coord_call(self, op: str, fn):
        """One coordinator round-trip under the bounded retry policy.
        ``op`` is the fault-injection site suffix and the retry-counter
        label; OSError (real or injected) backs off and retries, the
        final failure propagates to the caller's recovery path.

        A coordinator dying MID-RESPONSE surfaces from the HTTP
        transport as ``http.client.HTTPException`` (IncompleteRead,
        BadStatusLine) or a ``json.JSONDecodeError`` on the truncated
        body — neither is an OSError, so they are normalized here:
        every transport-shaped failure must reach the same retry and
        keep-alive paths, or the exact outage flowchaos exists to
        survive would kill the member thread instead."""
        import http.client
        import json

        site = f"mesh.{op if op in ('submit', 'sync') else 'sync'}"

        def call():
            if FAULTS.active:
                FAULTS.check(site)
            try:
                return fn()
            except (http.client.HTTPException,
                    json.JSONDecodeError) as e:
                raise ConnectionError(
                    f"coordinator {op} transport failure: "
                    f"{type(e).__name__}: {e}") from e

        def on_retry(i, exc, delay):
            self.m_retries.inc(op=op)
            log.warning("mesh member %s %s to coordinator failed (%s); "
                        "retry %d/%d in %.2fs", self.member_id, op, exc,
                        i + 1, COORD_RETRIES - 1, delay)

        return retry_call(call, attempts=COORD_RETRIES,
                          base=COORD_BACKOFF, cap=COORD_BACKOFF_MAX,
                          retry_on=(OSError,), on_retry=on_retry)

    # ---- capture hooks ----------------------------------------------------

    def _install_hooks(self, models: dict) -> None:
        for name, m in models.items():
            if isinstance(m, WindowAggregator):
                m.capture = self._wagg_capture(name)
            elif isinstance(m, WindowedHeavyHitter):
                m.capture = self._whh_capture(name)

    def _wagg_capture(self, name: str):
        def capture(popped):
            for slot, store in popped:
                self._captured.setdefault(int(slot), {})[name] = \
                    codec.wagg_payload(store)
        return capture

    def _whh_capture(self, name: str):
        def capture(slot, model):
            self._captured.setdefault(int(slot), {})[name] = \
                codec.capture_model(model)
        return capture

    def _audit_capture(self, name: str, slot: int, part: dict) -> None:
        """SketchAudit capture hook — fires inside the model's close,
        immediately before the model capture for the same window."""
        self._audit_captured.setdefault(int(slot), {})[name] = part

    # ---- assignment lifecycle --------------------------------------------

    def _call_sync(self) -> dict:
        """One heartbeat round-trip, clock-instrumented: the response's
        ``now`` (coordinator wall clock) plus our t0/t1 stamps form an
        NTP-midpoint offset sample; the best (min-RTT) estimate is
        reported back on the next call so the coordinator always holds
        a fresh per-member clock alignment for /debug/trace."""
        t0 = time.time()
        resp = self._coord_call(
            "sync", lambda: self.coordinator.sync(
                self.member_id, clock=self._clock.report()))
        t1 = time.time()
        now = resp.get("now")
        if now is not None:
            self._clock.add(t0, t1, float(now))
        return resp

    def _sync(self) -> None:
        if not self._joined:
            self._coord_call(
                "join", lambda: self.coordinator.join(
                    self.member_id, provider=self._query_state,
                    trace_url=self.trace_url))
            self._joined = True
        resp = self._call_sync()
        action = resp.get("action")
        if action == "rejoin":
            # fenced: our un-submitted state is the successor's replay
            self._abandon()
            self._joined = False
            return
        if action == "resync":
            self._resync()
            # try to re-acquire immediately
            resp = self._call_sync()
            action = resp.get("action")
        if action == "run" and resp.get("assign") is not None:
            self._start_worker(resp["assign"])

    def _start_worker(self, assign: dict) -> None:
        assign = {int(p): int(off) for p, off in assign.items()}
        self._frontier = dict(assign)
        self._captured = {}
        if not assign:
            self.worker = None
            return
        consumer = self.consumer_factory(sorted(assign))
        if hasattr(consumer, "positions"):
            for p, off in assign.items():
                consumer.positions[p] = off
        models = self.model_factory()
        self._install_hooks(models)
        self.worker = StreamWorker(consumer, models, self.sinks,
                                   self.config)
        self._audit_captured = {}
        aud = getattr(self.worker.fused, "audit", None)
        if aud is not None:
            # mesh citizenship: closes ship the sealed cohort here
            # instead of evaluating per-shard — the coordinator audits
            # the MERGED sketch against the MERGED cohort
            aud.capture = self._audit_capture
        self._flows_reported = 0
        self._batches_since_submit = 0
        # fresh ownership means fresh (possibly large) backlog: the
        # runtime's quiescence poll must not read a stale idle streak
        # from the waiting-for-assignment phase
        self.idle_streak = 0
        log.info("mesh member %s serving partitions %s",
                 self.member_id, sorted(assign))

    def _resync(self) -> None:
        log.info("mesh member %s resyncing (assignment changed)",
                 self.member_id)
        if self.worker is not None:
            w = self.worker
            w.finalize()  # force-close -> capture hooks fire
            ok = self._submit(release=True)
            self.worker = None
            self._close_consumer(w)
            if not ok and self._joined:
                # transport failure mid-resync: the release never
                # landed and the worker is already torn down — rejoin
                # fresh. join()'s rejoin-fence promotes our last
                # ACCEPTED carry; everything since replays from the
                # frontier (the same exactness path as a death).
                self._abandon()
                self._joined = False
        else:
            try:
                payload = codec.encode({
                    "member": self.member_id, "ranges": {},
                    "watermark": 0, "closed": {}, "open": {}, "flows": 0,
                    "release": True, "final": False,
                    "span": self._next_span((), ())})
                self._coord_call(
                    "submit", lambda: self.coordinator.submit(
                        self.member_id, payload))
            except OSError as e:
                log.warning("mesh member %s empty-release submit failed "
                            "(%s); rejoining fresh", self.member_id, e)
                self._joined = False
        self._captured = {}
        self._audit_captured = {}
        self._frontier = {}

    def _abandon(self) -> None:
        """Drop the worker WITHOUT submitting (we were fenced): stop its
        threads; state is discarded — the successor replays our rows."""
        w, self.worker = self.worker, None
        self._captured = {}
        self._audit_captured = {}
        self._frontier = {}
        if w is not None:
            self._stop_worker_threads(w)

    @staticmethod
    def _stop_worker_threads(w: StreamWorker) -> None:
        if w.executor is not None:
            w.executor.stop()
        if w.flusher is not None:
            w.flusher.stop()
        if isinstance(w.consumer, PrefetchConsumer):
            w.consumer.stop()
        MeshMember._close_consumer(w)

    @staticmethod
    def _close_consumer(w: StreamWorker) -> None:
        """Release the dropped worker's broker connection. Every
        rebalance builds a fresh consumer, so a churny mesh would
        otherwise leak one kafka-python connection per resync per
        member; the in-process bus consumer has no close() and needs
        none."""
        raw = w.consumer
        if isinstance(raw, PrefetchConsumer):
            raw = raw.inner
        close = getattr(raw, "close", None)
        if close is not None:
            close()

    # ---- submissions ------------------------------------------------------

    def _watermark(self, w: StreamWorker) -> int:
        wm = 0
        for m in w.models.values():
            if isinstance(m, WindowAggregator):
                wm = max(wm, int(m.watermark))
            elif isinstance(m, WindowedHeavyHitter) and \
                    m.current_slot is not None:
                wm = max(wm, int(m.current_slot))
        return wm

    def _collect_open(self, w: StreamWorker) -> dict:
        """{slot: {model: payload}} for every still-open window. Caller
        holds worker.lock and has synced sketch states."""
        out: dict[int, dict] = {}
        for name, m in w.models.items():
            if isinstance(m, WindowAggregator):
                m._drain()
                for slot, store in m.windows.items():
                    out.setdefault(int(slot), {})[name] = \
                        codec.wagg_payload(store)
            elif isinstance(m, WindowedHeavyHitter) and \
                    m.current_slot is not None:
                payload = codec.capture_model(m.model)
                aud = getattr(w.fused, "audit", None)
                if aud is not None and payload.get("kind") in (
                        "hh", "hh_inv"):
                    # the carry must snapshot the open cohort too:
                    # a promoted carry's audit partial has to cover
                    # exactly the rows its sketch state covers
                    part = aud.peek_partial(name)
                    if part is not None:
                        payload["audit"] = part
                out.setdefault(int(m.current_slot), {})[name] = payload
        return out

    def _next_span(self, closed_slots, open_slots,
                   chunk: int = -1) -> dict:
        """Mint the span context one submission carries across the
        process boundary: submission id, the window slots it touches,
        the newest chunk id that fed it, and this member's wall-clock
        send anchor — what ties the coordinator's submit-accept /
        merge / carry-promotion spans back to the member spans that
        produced the state."""
        self._sub_seq += 1
        return {
            "sub": self._sub_seq,
            "member": self.member_id,
            "sent": time.time(),
            "chunk": int(chunk),
            "windows": sorted({int(s) for s in closed_slots} |
                              {int(s) for s in open_slots}),
        }

    def _submit(self, final: bool = False, release: bool = False) -> bool:
        w = self.worker
        if w is None:
            return True
        closed, self._captured = self._captured, {}
        audit_closed, self._audit_captured = self._audit_captured, {}
        for slot, models in closed.items():
            for name, model_payload in models.items():
                part = audit_closed.get(slot, {}).get(name)
                if part is not None and \
                        model_payload.get("kind") in ("hh", "hh_inv"):
                    model_payload["audit"] = part
        with w.lock:
            w.sync_sketch_states()
            # final/release submissions follow a worker.finalize(): every
            # window was force-closed into `closed` and nothing is open;
            # a normal submission ships the open windows as the carry
            open_windows = {} if (final or release) \
                else self._collect_open(w)
            ranges = {}
            for p, start in self._frontier.items():
                to = max(int(w._covered.get(p, start)), start)
                ranges[p] = [start, to]
            watermark = self._watermark(w)
            flows = w.flows_seen
            chunk = getattr(w, "_trace_chunk", -1)
        span = self._next_span(closed, open_windows, chunk)
        payload = {
            "member": self.member_id,
            "ranges": ranges,
            "watermark": watermark,
            "closed": closed,
            "open": open_windows,
            "flows": flows - self._flows_reported,
            "final": final,
            "release": release,
            "span": span,
        }
        encoded = codec.encode(payload)
        try:
            resp = self._coord_call(
                "submit", lambda: self.coordinator.submit(
                    self.member_id, encoded))
        except OSError as e:
            # transport exhausted (coordinator down/restarting): restore
            # the captured windows — nothing else ran on this thread
            # since they were popped — and retry on a later step. If the
            # lost ack was actually an accept, the retried ranges no
            # longer extend the frontier: the coordinator rejects them,
            # and the rejection path below abandons + rejoins (exact by
            # the frontier-extend contract).
            log.warning("mesh member %s submission transport failure "
                        "(%s); keeping state for retry",
                        self.member_id, e)
            TRACER.record("mesh_submit", span["sent"], time.time(),
                          member=self.member_id, sub=span["sub"],
                          chunk=span["chunk"], ok=False,
                          windows=len(closed))
            for slot, models in closed.items():
                self._captured.setdefault(slot, {}).update(models)
            for slot, parts in audit_closed.items():
                self._audit_captured.setdefault(slot, {}).update(parts)
            return False
        TRACER.record("mesh_submit", span["sent"], time.time(),
                      member=self.member_id, sub=span["sub"],
                      chunk=span["chunk"], ok=bool(resp.get("ok")),
                      windows=len(closed))
        if not resp.get("ok"):
            log.warning("mesh member %s submission rejected (%s); "
                        "abandoning state and rejoining",
                        self.member_id, resp.get("reason"))
            self._abandon()
            self._joined = False
            return False
        self._flows_reported = flows
        self._batches_since_submit = 0
        for p, rng in ranges.items():
            self._frontier[p] = rng[1]
        return True

    # ---- driver loop ------------------------------------------------------

    def step(self) -> bool:
        """One poll/process/submit round. Returns False when idle."""
        if self._dead:
            return False
        now = time.monotonic()
        # unassigned members poll for an assignment faster than the
        # heartbeat cadence, but still BOUNDED — an idle fleet must not
        # hammer the coordinator with per-step sync round-trips
        interval = self.sync_interval if self.worker is not None \
            else min(self.sync_interval, 0.05)
        if now - self._last_sync >= interval:
            self._last_sync = now
            try:
                self._sync()
            except OSError as e:
                # coordinator unreachable past the retry budget (it may
                # be restarting from its journal): stay alive, keep our
                # state, and heartbeat again next step. One log line per
                # outage window — not one per retry.
                if time.monotonic() - self._last_down_log >= 5.0:
                    self._last_down_log = time.monotonic()
                    log.warning("mesh member %s: coordinator "
                                "unreachable (%s); will keep retrying",
                                self.member_id, e)
                return False
        w = self.worker  # kill() may null the attribute mid-step
        if w is None or self._dead:
            return False
        progressed = w.run_once()
        if progressed:
            self._batches_since_submit += 1
        if self._captured or (
                self.submit_every
                and self._batches_since_submit >= self.submit_every):
            self._submit()
        elif not progressed and self._batches_since_submit:
            # going idle with consumed-but-unreported progress: flush it
            # now, or this member's watermark never reaches the
            # coordinator and every partition it owns stalls the
            # mesh-wide merge barrier until the NEXT row arrives. (A
            # shard that never saw a row at all still holds the barrier
            # — there is no event time to report; see ARCHITECTURE.md
            # "flowmesh" failure model.)
            self._submit()
        return progressed

    def run(self, stop, idle_sleep: float = 0.01) -> None:
        """Thread target: step until ``stop`` (threading.Event) is set."""
        while not stop.is_set() and not self._dead:
            try:
                progressed = self.step()
            except Exception:
                if self._dead:
                    return  # kill() tore the worker down mid-step
                raise
            if progressed:
                self.idle_streak = 0
            else:
                self.idle_streak += 1
                stop.wait(idle_sleep)

    def finalize(self) -> None:
        """End of stream: force-close everything, final-submit, leave."""
        if self._dead:
            return
        if self.worker is not None:
            w = self.worker
            w.finalize()  # capture hooks grab all open windows
            if not self._submit(final=True):
                log.error("mesh member %s final submission failed; the "
                          "coordinator will fence this member and "
                          "promote its last accepted carry",
                          self.member_id)
            self.worker = None
            self._close_consumer(w)
        if self._joined:
            try:
                self._coord_call(
                    "leave",
                    lambda: self.coordinator.leave(self.member_id))
            except OSError as e:
                # best effort: an unreachable coordinator fences us by
                # heartbeat timeout, which is the same protocol path
                log.warning("mesh member %s leave failed (%s); relying "
                            "on heartbeat expiry", self.member_id, e)
            self._joined = False

    def kill(self) -> None:
        """Abrupt death (churn tests / emergency stop): no submission,
        no leave — the coordinator fences us by heartbeat timeout (or
        an explicit fence()) and promotes the last accepted carry."""
        self._dead = True
        w, self.worker = self.worker, None
        if w is not None:
            self._stop_worker_threads(w)

    # ---- live-query provider (coordinator fan-out) ------------------------

    def _query_state(self, model_name: str):
        """Open-window sketch state for the mesh /topk fan-out. Runs on
        the coordinator's thread; worker.lock gives it the same
        consistent view QueryServer gets on a single worker."""
        w = self.worker
        if w is None:
            return None
        with w.lock:
            m = w.models.get(model_name)
            if not isinstance(m, WindowedHeavyHitter) or \
                    m.current_slot is None:
                return None
            w.sync_sketch_states()
            return {"slot": int(m.current_slot),
                    "payload": codec.capture_model(m.model)}
