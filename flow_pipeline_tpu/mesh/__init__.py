"""flowmesh: N-worker sharded sketch mesh with window-close merge and
live rebalance (ROADMAP item 3).

Flows shard by key-hash across bus partitions to N independent
StreamWorker members; per-window sketch/wagg/top-K state merges
network-wide at window close through the coordinator's monoid folds —
`parallel/sharded.py`'s on-device collective merges lifted to a
serialized exchange — and membership churn rebalances partitions with
epoch fencing so no window is lost or double-counted
(docs/ARCHITECTURE.md "flowmesh" states the contract).
"""

from .coordinator import MeshCoordinator, ModelSpec, spec_from_models
from .member import MeshMember
from .runtime import (InProcessMesh, SHARD_KEY_COLS, produce_sharded,
                      shard_ids)
from .scope import ClockSync, TraceLane, aggregate_traces, estimate_offset
from .server import (MemberStateServer, MeshCoordinatorServer,
                     RemoteCoordinator)

__all__ = [
    "MeshCoordinator", "MeshMember", "ModelSpec", "spec_from_models",
    "InProcessMesh", "SHARD_KEY_COLS", "produce_sharded", "shard_ids",
    "MeshCoordinatorServer", "RemoteCoordinator", "MemberStateServer",
    "ClockSync", "TraceLane", "aggregate_traces", "estimate_offset",
]
