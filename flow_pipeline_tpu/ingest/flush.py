"""Background flusher: closed-window extraction + sink writes off the
hot path.

The worker's flush work — building columnar rows from a closed window
store, extracting a sketch's top-K (a device sync), writing sinks — has
no ordering dependency on the NEXT batch's update; only on the state
captured at close time. So the worker captures that state under its lock
(cheap: dict pops and jax array references) and hands zero-arg jobs
here, where they run on one background thread in submission order.

Error contract: sink/extraction failures must FAIL THE STEP, not drop
rows silently (at-least-once semantics — an unwritten window must keep
its offsets uncommitted so a restart replays it). The first job
exception is latched and re-raised, wrapped in FlushError, from the next
submit()/drain() on the worker thread; drain() is called before every
offset commit, so no commit can cover rows whose write failed.
"""

from __future__ import annotations

# flowlint: lock-checked
# (shared attributes declare their lock / single-writer story below;
# `make lint` verifies write sites — see docs/STATIC_ANALYSIS.md)

import queue
import threading
from typing import Callable, Optional

from ..obs import REGISTRY, get_logger

log = get_logger("ingest.flush")


class FlushError(RuntimeError):
    """A background flush job failed; the wrapped cause is __cause__."""


class AsyncFlusher:
    """One background thread draining a bounded queue of flush jobs.

    max_queue bounds memory (each job pins one window's rows/state);
    submit() blocks when full — backpressure, never silent dropping.
    """

    def __init__(self, max_queue: int = 8):
        self.max_queue = max_queue
        self._jobs: queue.Queue = queue.Queue(maxsize=max_queue)
        self._error: Optional[BaseException] = None  # guarded-by: _cv
        # flowlint: unguarded -- the lock itself; bound once, never rebound
        self._cv = threading.Condition()
        self._inflight = 0  # queued + currently executing  # guarded-by: _cv
        self._stop = threading.Event()
        # flowlint: unguarded -- worker-thread lifecycle only (submit/stop run on the one owner thread)
        self._thread: Optional[threading.Thread] = None
        self.m_depth = REGISTRY.gauge(
            "ingest_queue_depth", "items queued per ingest stage")
        self.m_high = REGISTRY.gauge(
            "ingest_queue_highwater", "max queue depth seen per ingest stage")
        # flowlint: unguarded -- highwater cache written only by the worker thread (submit)
        self._high = 0

    # ---- worker-thread surface -------------------------------------------

    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue one zero-arg flush job. Raises FlushError if a previous
        job failed (the step that observes it must not commit)."""
        self._check()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="ingest-flush", daemon=True)
            self._thread.start()
        with self._cv:
            self._inflight += 1
        self._jobs.put(job)
        depth = self._jobs.qsize()
        self.m_depth.set(depth, stage="flush")
        if depth > self._high:
            self._high = depth
            self.m_high.set(depth, stage="flush")

    def drain(self, timeout: float = 60.0) -> None:
        """Block until every submitted job has finished; re-raise the
        first failure. Call before committing offsets."""
        with self._cv:
            done = self._cv.wait_for(
                lambda: self._inflight == 0 or self._error is not None,
                timeout)
        self._check()
        if not done:
            raise FlushError("flush queue did not drain in time")

    def stop(self, timeout: float = 30.0) -> None:
        """Drain and stop the thread. Safe to call twice."""
        if self._thread is None:
            return
        try:
            self.drain(timeout)
        finally:
            self._stop.set()
            self._jobs.put(None)  # wake the thread
            self._thread.join(timeout)
            if self._thread.is_alive():
                # wedged inside a job (e.g. a sink write with no socket
                # timeout): refuse to pretend it stopped — resetting
                # _thread here would let a later submit() start a SECOND
                # consumer of the same queue and run flush jobs out of
                # submission order
                raise TimeoutError(
                    "ingest flusher thread did not stop in time")
            self._thread = None
            self._stop.clear()

    def _check(self) -> None:
        with self._cv:
            err = self._error
            self._error = None
        if err is not None:
            raise FlushError(f"background flush failed: {err}") from err

    # ---- flusher thread ---------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            job = self._jobs.get()
            if job is None:
                continue
            try:
                job()
            except Exception as e:  # noqa: BLE001 — latched for the worker:
                # swallowing would break at-least-once (rows silently lost
                # under committed offsets)
                log.exception("flush job failed; surfacing to worker")
                with self._cv:
                    if self._error is None:
                        self._error = e
            finally:
                self.m_depth.set(self._jobs.qsize(), stage="flush")
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()
