"""Pipelined stage executor: overlap host grouping with the device step.

The feed side already double-buffers fetch+decode (engine.prefetch); this
adds the missing stage: a group thread pulls decoded batches off the
consumer and runs the pipeline's PREPARE half (pure host pre-aggregation,
no model state) into a bounded queue, so grouping of batch N+1 overlaps
the device step + window lifecycle of batch N on the worker thread.
Stage graph, each arrow a bounded queue:

    bus fetch+decode -> [prefetch q] -> group/prepare -> [prepared q]
        -> device step (worker thread) -> [flush q] -> flusher

Backpressure is the queues themselves: a slow device step fills the
prepared queue and the group thread waits; a slow flusher blocks
submit(). Nothing is dropped anywhere — the drain/stop protocol is that
``next()`` returns None only after a poll round STARTED AFTER the call
came back empty with the queue drained (the same freshness rule
engine.prefetch documents, one stage further downstream), so
stop_when_idle callers never abandon a tail in flight.

Errors from the feed or prepare stages latch and re-raise from next() —
a poison batch crashes the worker for the supervisor to restart, exactly
like the serial path.
"""

from __future__ import annotations

# flowlint: lock-checked
# (this stage is deliberately lock-free: one group thread produces, one
# worker thread consumes, and every shared field below is a single-writer
# latch or counter handed across the GIL / the bounded queue. The
# annotations make that story machine-checked — see docs/STATIC_ANALYSIS.md)

import queue
import threading
import time
from typing import Callable, Optional

from ..guard import register_guard_metrics
from ..obs import REGISTRY, get_logger
from ..obs.trace import TRACER

log = get_logger("ingest.executor")


class PipelinedExecutor:
    """Runs ``prepare`` over consumer batches on a dedicated thread.

    depth is the max prepared batches held ready (2 = double buffering:
    one applying, one ready, one in prepare).
    """

    def __init__(self, consumer, prepare: Callable, poll_max: int = 32768,
                 depth: int = 2, idle_sleep: float = 0.02):
        self.consumer = consumer
        self.prepare = prepare
        self.poll_max = poll_max
        self.depth = depth
        self.idle_sleep = idle_sleep
        self._out: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._idle = threading.Event()
        # freshness accounting (see engine.prefetch.PrefetchConsumer.poll)
        # flowlint: unguarded -- group thread is the sole writer; worker reads a monotonic int
        self._started = 0
        # flowlint: unguarded -- group thread is the sole writer; worker reads a monotonic int
        self._completed_start = 0
        # flowlint: unguarded -- group thread is the sole writer; worker reads the GIL-atomic latch (stop() clears it after join)
        self._error: Optional[BaseException] = None
        # flowlint: unguarded -- worker-thread lifecycle only (next()/stop() run on the one owner thread)
        self._thread: Optional[threading.Thread] = None
        self.m_depth = REGISTRY.gauge(
            "ingest_queue_depth", "items queued per ingest stage")
        self.m_high = REGISTRY.gauge(
            "ingest_queue_highwater", "max queue depth seen per ingest stage")
        # flowlint: unguarded -- group thread is the sole writer; readers tolerate staleness (gauge)
        self.high_water = 0
        # flowguard occupancy: live bytes resident in the prepared queue
        # (guard_buffer_bytes{stage="group"}) — the bound is depth
        # batches by construction; this makes the occupancy observable
        self.m_bytes = register_guard_metrics()["buffer_bytes"]
        # flowlint: unguarded -- the lock itself; bound once
        self._bytes_lock = threading.Lock()
        self._bytes = 0  # guarded-by: _bytes_lock

    # ---- worker surface ---------------------------------------------------

    def next(self):
        """Next prepared batch, or None when the stream is idle (fresh
        idle round + empty queue). Raises the first stage error."""
        if self._thread is None:
            self._start()
        started_before = self._started
        while True:
            if self._error is not None:
                raise self._error
            try:
                item, t_enq, chunk = self._out.get(timeout=self.idle_sleep)
                self.m_depth.set(self._out.qsize(), stage="group")
                self._track_bytes(-self._nbytes(item))
                # queue-wait: prepared-to-picked-up — the interval that
                # shows whether the device step or the group thread is
                # the bottleneck for THIS chunk
                TRACER.record("queue_wait", t_enq, time.time(),
                              chunk=chunk, stage="group")
                return item
            except queue.Empty:
                if not self._thread.is_alive():
                    if self._error is not None:
                        raise self._error
                    return None
                if self._idle.is_set() and \
                        self._completed_start > started_before:
                    return None

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the group thread. Prepared-but-unapplied batches are
        dropped — their offsets are uncommitted, so they replay."""
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("ingest group thread did not stop in time")
        self._thread = None
        self._stop.clear()
        # actually drop the retained batches (and any latched error): a
        # worker that restore()s and runs again would otherwise apply
        # these stale preparations AND re-poll their rewound offsets —
        # double counting — and until then they pin full FlowBatches
        while True:
            try:
                self._out.get_nowait()
            except queue.Empty:
                break
        self._idle.clear()
        self._error = None
        self.m_depth.set(0, stage="group")
        with self._bytes_lock:
            self._bytes = 0
        self.m_bytes.set(0, stage="group")

    # ---- occupancy accounting ---------------------------------------------

    @staticmethod
    def _nbytes(prep) -> int:
        batch = getattr(prep, "batch", None)
        return batch.nbytes() if batch is not None else 0

    def _track_bytes(self, delta: int) -> None:
        with self._bytes_lock:
            self._bytes += delta
            b = self._bytes
        self.m_bytes.set(b, stage="group")

    # ---- group thread -----------------------------------------------------

    def _start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="ingest-group", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._out.full():
                # device side is behind: the bounded queue IS the
                # backpressure — wait instead of spinning
                self._stop.wait(self.idle_sleep)
                continue
            self._started += 1
            round_no = self._started
            try:
                batch = self.consumer.poll(self.poll_max)
            except Exception as e:  # noqa: BLE001 — surface via next()
                log.exception("ingest poll failed; surfacing to worker")
                self._error = e
                break
            if batch is None or len(batch) == 0:
                self._idle.set()
                self._completed_start = round_no
                self._stop.wait(self.idle_sleep)
                continue
            chunk = getattr(batch, "chunk_id", -1)
            try:
                with TRACER.span("prepare", chunk=chunk, rows=len(batch)):
                    prep = self.prepare(batch)
            except Exception as e:  # noqa: BLE001 — surface via next()
                log.exception("ingest prepare failed; surfacing to worker")
                self._error = e
                break
            self._idle.clear()
            self._completed_start = round_no
            # space is guaranteed: this thread is the only producer and
            # it checked full() above; next() only ever removes items
            self._out.put((prep, time.time(), chunk))
            self._track_bytes(self._nbytes(prep))
            depth = self._out.qsize()
            self.m_depth.set(depth, stage="group")
            if depth > self.high_water:
                self.high_water = depth
                self.m_high.set(depth, stage="group")
