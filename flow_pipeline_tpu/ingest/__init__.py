"""Host dataplane runtime: everything between "decoded batch" and
"device step".

Five rounds of e2e budgets said the same thing (BENCH_r05: host_group
49.6% of wall, flushing 34.3%): the host side of the pipeline had no
runtime of its own — one thread did grouping, the device step, window
flushing and sink writes in strict sequence. This package gives it one,
shaped like the partitioned pre-aggregation front-ends of the streaming
top-K literature (PAPERS.md: arxiv 2511.16797, 2504.16896 — a sharded
pre-aggregation stage FEEDING the sketch, never a global sort on the
hot path):

- ingest.shard     sharded grouping: hash-partitioned per-shard
                   group/sum on a persistent thread pool (numpy releases
                   the GIL), plus the native radix-group kernel switch.
- ingest.executor  pipelined stage graph decode -> group -> device step
                   with bounded queues, double buffering, backpressure
                   and a drain/stop protocol.
- ingest.flush     background flusher: top-K extraction and sink writes
                   for closed windows run off the hot path, with errors
                   propagated back to the worker.

engine.worker wires these in behind --ingest.mode (serial keeps the old
single-threaded path for A/B); per-stage queue depths export through
obs.metrics as ingest_queue_depth / ingest_queue_highwater.
"""

from .executor import PipelinedExecutor
from .flush import AsyncFlusher, FlushError
from .shard import ShardPool, group_by_key_sharded

__all__ = [
    "AsyncFlusher",
    "FlushError",
    "PipelinedExecutor",
    "ShardPool",
    "group_by_key_sharded",
]
