"""Sharded host grouping: partition by hash prefix, group per shard.

ops.hostgroup's groupby is one serial chain (hash every row, argsort the
64-bit hash, verify, reduceat). Every link is numpy releasing the GIL,
and the hash space partitions perfectly: rows whose hashes share a top-
bit prefix can only group with each other, so P prefix shards group
independently and their outputs CONCATENATE into exactly the serial
result (shards ascend by prefix, hashes ascend within a shard — the
global hash order). That makes the sharded path bit-identical to
group_by_key, which tests/test_ingest.py pins down against the serial
oracle.

Exactness survives sharding for the same reason: two distinct key tuples
can only collide in the full 64-bit hash, which places them in the SAME
shard — the per-shard verify/lexsort fallback sees them.

The pool is a plain ThreadPoolExecutor kept alive across batches
(thread spin-up per batch would eat the win at ~1ms batch budgets).
"""

from __future__ import annotations

# flowlint: lock-checked
# (shared state declares its lock below; `make lint` verifies write
# sites — see docs/STATIC_ANALYSIS.md)

import os
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..ops import hostgroup

# Below this many rows the serial path keeps the job. The partition +
# dispatch overhead puts the measured break-even somewhere in the
# 4k-8k range on a 2-core box (noisy — the box is shared); 8192 is the
# deliberately conservative end of that range so sharding only engages
# where it is clearly profitable.
MIN_SHARD_ROWS = 8192


def default_workers() -> int:
    return max(1, min(8, os.cpu_count() or 1))


class ShardPool:
    """Persistent worker threads for GIL-releasing group work.

    One pool serves a whole pipeline (all key families + the executor's
    prepare stage); sizing past the physical cores just adds scheduler
    churn, so the default is cpu_count capped at 8.
    """

    def __init__(self, workers: int = 0):
        self.workers = workers or default_workers()
        self._ex = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="ingest-shard")

    def submit(self, fn, *args):
        return self._ex.submit(fn, *args)

    def map(self, fn, items) -> list:
        """Run fn over items on the pool, preserving order. Falls through
        to inline execution for a single item (no dispatch overhead)."""
        items = list(items)
        if len(items) <= 1:
            return [fn(x) for x in items]
        return list(self._ex.map(fn, items))

    def shutdown(self) -> None:
        self._ex.shutdown(wait=False, cancel_futures=True)

    def __enter__(self) -> "ShardPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


# One process-wide pool: pipelines are rebuilt freely (bench samples,
# supervisor restarts) and per-instance pools would strand idle threads.
_SHARED_LOCK = threading.Lock()
_SHARED: ShardPool | None = None  # guarded-by: _SHARED_LOCK


def shared_pool() -> ShardPool:
    """The process-wide pool, created once. Two pipelines built
    concurrently (supervisor restart racing a bench sample) must not
    each spin up a pool and strand one forever — hence the lock."""
    global _SHARED
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = ShardPool()
        return _SHARED


def _shard_bits(shards: int) -> int:
    bits = 1
    while (1 << bits) < shards:
        bits += 1
    return bits


def group_by_key_sharded(lanes: np.ndarray, planes: list[np.ndarray],
                         pool: ShardPool | None, shards: int = 0,
                         exact: bool = True, native: bool = False):
    """group_by_key over hash-prefix shards on ``pool``.

    Same contract and (by construction, see module docstring) same output
    as ops.hostgroup.group_by_key. Falls back to the serial path for
    small batches, a missing pool, or when the native kernel is requested
    (its single C pass already beats a partitioned numpy run).
    """
    n, w = lanes.shape
    if pool is None or n < MIN_SHARD_ROWS or pool.workers <= 1 or native:
        return hostgroup.group_by_key(lanes, planes, exact, native=native)
    shards = shards or pool.workers
    bits = _shard_bits(shards)

    # hash in parallel over contiguous row blocks (row-wise function)
    h = np.empty(n, np.uint64)
    nb = pool.workers
    step = -(-n // nb)
    blocks = [slice(i, min(i + step, n)) for i in range(0, n, step)]

    def do_hash(sl):
        h[sl] = hostgroup.hash_u64(lanes[sl])

    pool.map(do_hash, blocks)

    sid = (h >> np.uint64(64 - bits)).astype(np.int64)
    parts = [np.flatnonzero(sid == s) for s in range(1 << bits)]

    def do_group(idx):
        if idx.size == 0:
            return None
        sl = lanes[idx]
        perm, starts = hostgroup.grouping_perm(sl, exact, h=h[idx])
        return hostgroup.reduce_groups(
            sl, [p[idx] for p in planes], perm, starts)

    results = [r for r in pool.map(do_group, parts) if r is not None]
    if not results:
        return hostgroup._empty_groups(w, planes)
    uniq = np.concatenate([r[0] for r in results])
    counts = np.concatenate([r[2] for r in results])
    sums = [np.concatenate([r[1][j] for r in results])
            for j in range(len(planes))]
    return uniq, sums, counts
