"""NetFlow v5 / v9 / IPFIX datagram decoders.

Wire layouts per the protocol specs (RFC 3954 for v9, RFC 7011 for IPFIX;
v5 is the classic fixed 48-byte record). Field semantics follow the
reference pipeline's observed conventions: IPv4 addresses embed in the
trailing 4 bytes of the 16-byte address (ref: compose/clickhouse/create.sh
FixedString(16) + viz-ch.json extraction), timestamps are unix seconds,
and v9/IPFIX flow start/end sysuptime offsets convert against the export
header clock.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field
from typing import Optional

from ..schema.message import FlowMessage, FlowType


def _v4(addr4: bytes) -> bytes:
    """IPv4 -> 16-byte trailing embedding."""
    return b"\x00" * 12 + addr4


# ---------------------------------------------------------------------------
# NetFlow v5
# ---------------------------------------------------------------------------

_V5_HEADER = struct.Struct(">HHIIIIBBH")
_V5_RECORD = struct.Struct(">4s4s4sHHIIIIHHBBBBHHBBH")


def decode_v5(data: bytes, now: Optional[int] = None) -> list[FlowMessage]:
    if len(data) < _V5_HEADER.size:
        raise ValueError("short NetFlow v5 header")
    (_, count, sysuptime, unix_secs, _nsecs, seq, _etype, _eid,
     sampling) = _V5_HEADER.unpack_from(data, 0)
    sampling_rate = sampling & 0x3FFF  # top 2 bits are the sampling mode
    now = now or unix_secs  # caller's receive time wins over exporter clock
    msgs = []
    off = _V5_HEADER.size
    for i in range(count):
        if off + _V5_RECORD.size > len(data):
            raise ValueError(f"truncated v5 record {i}")
        (src, dst, _nexthop, in_if, out_if, pkts, octets, first, last,
         sport, dport, _pad, tcp_flags, proto, tos, src_as, dst_as,
         _smask, _dmask, _pad2) = _V5_RECORD.unpack_from(data, off)
        off += _V5_RECORD.size
        # First/Last are sysuptime millis; anchor them to the export clock
        start = unix_secs - max(0, (sysuptime - first)) // 1000
        end = unix_secs - max(0, (sysuptime - last)) // 1000
        msgs.append(
            FlowMessage(
                type=FlowType.NETFLOW_V5,
                time_received=now,
                time_flow_start=start,
                time_flow_end=end,
                sampling_rate=sampling_rate or 1,
                sequence_num=seq & 0xFFFFFFFF,
                src_addr=_v4(src),
                dst_addr=_v4(dst),
                bytes=octets,
                packets=pkts,
                src_port=sport,
                dst_port=dport,
                proto=proto,
                ip_tos=tos,
                tcp_flags=tcp_flags,
                in_if=in_if,
                out_if=out_if,
                src_as=src_as,
                dst_as=dst_as,
                etype=0x0800,
            )
        )
    return msgs


# ---------------------------------------------------------------------------
# NetFlow v9 / IPFIX (template-based)
# ---------------------------------------------------------------------------

# field type -> FlowMessage attribute handler. v9 and IPFIX share these IDs
# for the fields this pipeline carries.
_INT_FIELDS = {
    1: "bytes",  # IN_BYTES
    2: "packets",  # IN_PKTS
    4: "proto",  # PROTOCOL
    5: "ip_tos",  # SRC_TOS
    6: "tcp_flags",
    7: "src_port",
    10: "in_if",
    11: "dst_port",
    14: "out_if",
    16: "src_as",
    17: "dst_as",
    31: "ipv6_flow_label",
    32: "icmp_type",  # ICMP_TYPE: type*256 + code (split below)
    34: "sampling_rate",  # SAMPLING_INTERVAL
    61: "flow_direction",
    89: "forwarding_status",
    192: "ip_ttl",  # IPFIX ipTTL
}
_ADDR4_FIELDS = {8: "src_addr", 12: "dst_addr", 15: None}  # 15 = next hop, dropped
_ADDR6_FIELDS = {27: "src_addr", 28: "dst_addr"}
_TIME_FIELDS = {21: "last", 22: "first"}  # sysuptime ms (v9)
_TS_SEC_FIELDS = {150: "start_s", 151: "end_s"}  # IPFIX absolute seconds
_TS_MS_FIELDS = {152: "start_ms", 153: "end_ms"}  # IPFIX absolute millis


@dataclass
class TemplateCache:
    """(source, domain/source-id, template id) -> [(field type, length)].

    Templates arrive in-band; data sets that reference an unseen template
    are counted and skipped (the GoFlow behavior behind its
    flow_process_nf_errors_count metric). Options templates are tracked
    separately: their data records carry exporter-wide state — notably the
    sampling interval — which is cached per (source, domain) and applied to
    flow records that do not carry an inline sampling field."""

    templates: dict[tuple, list[tuple[int, int]]] = field(default_factory=dict)
    options: set = field(default_factory=set)  # keys that are options templates
    sampling: dict[tuple, int] = field(default_factory=dict)  # (src, dom) -> rate
    missing: int = 0
    # per-ROUTER template tally (source with the ephemeral port
    # stripped — the granularity of the exported `router` label),
    # maintained at put() time: count_for runs once per datagram on the
    # decode hot path, so it must not scan the whole cache (1000 routers
    # x 20 templates would be a 20k-tuple walk per packet), and tallying
    # the full ip:port would make one router's series flap between its
    # per-port counts instead of aggregating.
    by_router: dict[str, int] = field(default_factory=dict)

    @staticmethod
    def _router(source: str) -> str:
        host, _, _ = source.rpartition(":")
        return host if host else source

    def put(self, source: str, domain: int, tid: int,
            fields: list[tuple[int, int]], is_options: bool = False) -> None:
        key = (source, domain, tid)
        if key not in self.templates:  # refreshes don't re-count
            router = self._router(source)
            self.by_router[router] = self.by_router.get(router, 0) + 1
        self.templates[key] = fields
        if is_options:
            self.options.add(key)
        else:
            self.options.discard(key)

    def get(self, source: str, domain: int, tid: int):
        t = self.templates.get((source, domain, tid))
        if t is None:
            self.missing += 1
        return t

    def count_for(self, source: str) -> int:
        """Templates cached for one ROUTER (``source`` may carry the
        port; it is stripped to match the exported label) — the
        per-router flow_process_nf_templates_count series
        (collector.udp)."""
        return self.by_router.get(self._router(source), 0)

    def is_options(self, source: str, domain: int, tid: int) -> bool:
        return (source, domain, tid) in self.options

    def exporter_sampling(self, source: str, domain: int) -> int:
        return self.sampling.get((source, domain), 0)

    def __len__(self) -> int:
        return len(self.templates)


def _uint(b: bytes) -> int:
    return int.from_bytes(b, "big")


# RFC 7011 §7: a template field length of 0xFFFF marks a variable-length
# field whose actual size is a per-record 1-byte prefix (or 255 followed by
# a 2-byte length). NetFlow v9 has no such encoding, but treating 0xFFFF
# identically there is safe: no fixed v9 field is 65535 bytes wide.
VARLEN = 0xFFFF


def _varlen_slice(data: bytes, p: int, end: int) -> tuple[bytes, int]:
    """Read one variable-length field's content; returns (raw, new offset)."""
    if p >= end:
        raise ValueError("varlen field prefix overruns set")
    ln = data[p]
    p += 1
    if ln == 255:  # 3-byte form
        if p + 2 > end:
            raise ValueError("varlen field extended prefix overruns set")
        ln = struct.unpack_from(">H", data, p)[0]
        p += 2
    if p + ln > end:
        raise ValueError("varlen field content overruns set")
    return data[p : p + ln], p + ln


def _min_record_len(fields) -> int:
    """Lower bound on one data record's wire size: fixed widths plus at
    least one length-prefix byte per variable-length field."""
    return sum(1 if flen == VARLEN else flen for _, flen in fields)


def _record_from_fields(fields, data, off, flow_type, now, header_secs,
                        sysuptime, seq, end=None) -> tuple[FlowMessage, int, bool]:
    """Returns (msg, new offset, has_inline_sampling). The flag matters:
    sampling_rate defaults to 1, so 'field absent' and 'explicit inline 1'
    (unsampled flows from an otherwise-sampling exporter) are otherwise
    indistinguishable to the exporter-rate inheritance."""
    if end is None:
        end = len(data)
    msg = FlowMessage(type=flow_type, time_received=now, sequence_num=seq,
                      sampling_rate=1)
    times = {}
    etype = 0x0800
    has_sampling = False
    for ftype, flen in fields:
        if flen == VARLEN:
            # Variable-length content (RFC 7011 §7) is strings/opaque data;
            # every field this pipeline maps is fixed-width, so consume the
            # bytes and move on — the record stays decodable.
            _, off = _varlen_slice(data, off, end)
            continue
        # In a varlen-bearing template the outer loop's min-length check
        # cannot guarantee the fixed tail fits: a long varlen value can
        # leave fewer bytes than the remaining fixed fields, and slicing
        # past ``end`` would silently read the NEXT set's bytes as content.
        if off + flen > end:
            raise ValueError("record field overruns set")
        raw = data[off : off + flen]
        off += flen
        if ftype in _INT_FIELDS:
            if ftype in _SAMPLING_FIELDS:
                has_sampling = True
            setattr(msg, _INT_FIELDS[ftype], _uint(raw))
        elif ftype in _ADDR4_FIELDS:
            attr = _ADDR4_FIELDS[ftype]
            if attr:
                setattr(msg, attr, _v4(raw[:4]))
        elif ftype in _ADDR6_FIELDS:
            setattr(msg, _ADDR6_FIELDS[ftype], raw[:16])
            etype = 0x86DD
        elif ftype in _TIME_FIELDS:
            times[_TIME_FIELDS[ftype]] = _uint(raw)
        elif ftype in _TS_SEC_FIELDS:
            times[_TS_SEC_FIELDS[ftype]] = _uint(raw)
        elif ftype in _TS_MS_FIELDS:
            times[_TS_MS_FIELDS[ftype]] = _uint(raw)
        # unknown fields are skipped (length still consumed)
    if msg.icmp_type:
        msg.icmp_code = msg.icmp_type & 0xFF
        msg.icmp_type >>= 8
    msg.etype = etype
    if "first" in times:  # v9 sysuptime-relative millis
        msg.time_flow_start = header_secs - max(0, sysuptime - times["first"]) // 1000
    if "last" in times:
        msg.time_flow_end = header_secs - max(0, sysuptime - times["last"]) // 1000
    if "start_s" in times:
        msg.time_flow_start = times["start_s"]
    if "end_s" in times:
        msg.time_flow_end = times["end_s"]
    if "start_ms" in times:
        msg.time_flow_start = times["start_ms"] // 1000
    if "end_ms" in times:
        msg.time_flow_end = times["end_ms"] // 1000
    if not msg.time_flow_start:
        msg.time_flow_start = now
    if not msg.time_flow_end:
        msg.time_flow_end = msg.time_flow_start
    return msg, off, has_sampling


def _read_field_specs(data, off, end, count, enterprise: bool):
    """``enterprise`` is the IPFIX PEN rule (bit 15 => 4 extra bytes);
    NetFlow v9 has no such encoding, so its callers pass False — a v9
    vendor field type >= 0x8000 is just a type, not a length change."""
    fields = []
    for _ in range(count):
        # field specs must stay inside this flowset: an overstated count
        # would otherwise swallow the next set's bytes and cache a
        # corrupt template that mis-decodes every later record
        if off + 4 > end:
            raise ValueError("template field specs overrun flowset")
        ftype, flen = struct.unpack_from(">HH", data, off)
        off += 4
        if enterprise and ftype & 0x8000:  # IPFIX enterprise: skip the PEN
            if off + 4 > end:
                raise ValueError("enterprise field PEN overruns flowset")
            off += 4
            ftype = 0  # unknown -> skipped at decode
        fields.append((ftype, flen))
    return fields, off


def _decode_templates(data, off, end, source, domain, cache,
                      enterprise=False):
    while off + 4 <= end:
        tid, fcount = struct.unpack_from(">HH", data, off)
        off += 4
        fields, off = _read_field_specs(data, off, end, fcount, enterprise)
        cache.put(source, domain, tid, fields)
    return off


def _decode_options_templates_v9(data, off, end, source, domain, cache):
    """v9 options template: tid, scope length (bytes), options length
    (bytes), then scope + option field specs (RFC 3954 §6.1)."""
    while off + 6 <= end:
        tid, scope_len, opt_len = struct.unpack_from(">HHH", data, off)
        off += 6
        if tid == 0:  # padding
            break
        n_fields = (scope_len + opt_len) // 4
        fields, off = _read_field_specs(data, off, end, n_fields,
                                        enterprise=False)
        cache.put(source, domain, tid, fields, is_options=True)
    return off


def _decode_options_templates_ipfix(data, off, end, source, domain, cache):
    """IPFIX options template: tid, total field count, scope field count,
    then the field specs (RFC 7011 §3.4.2.2)."""
    while off + 6 <= end:
        tid, fcount, _scope_count = struct.unpack_from(">HHH", data, off)
        off += 6
        if tid == 0:  # padding
            break
        fields, off = _read_field_specs(data, off, end, fcount,
                                        enterprise=True)
        cache.put(source, domain, tid, fields, is_options=True)
    return off


# option-data field types carrying the exporter's sampling interval
_SAMPLING_FIELDS = {34, 305}  # SAMPLING_INTERVAL, samplingPacketInterval


def _decode_options_data(fields, data, off, end, source, domain, cache):
    """Scan option data records for a sampling interval; cache it
    exporter-wide."""
    rec_len = _min_record_len(fields)
    if rec_len <= 0:
        return
    while off + rec_len <= end:
        p = off
        for ftype, flen in fields:
            if flen == VARLEN:
                _, p = _varlen_slice(data, p, end)
                continue
            if p + flen > end:  # fixed tail after a long varlen value
                raise ValueError("options record field overruns set")
            if ftype in _SAMPLING_FIELDS:
                rate = _uint(data[p : p + flen])
                if rate:
                    cache.sampling[(source, domain)] = rate
            p += flen
        off = p  # varlen fields make records variable-width


def decode_v9(data: bytes, cache: TemplateCache, source: str = "",
              now: Optional[int] = None) -> list[FlowMessage]:
    if len(data) < 20:
        raise ValueError("short NetFlow v9 header")
    _, count, sysuptime, unix_secs, seq, source_id = struct.unpack_from(
        ">HHIIII", data, 0
    )
    now = now or unix_secs
    msgs = []
    inherit = []  # records lacking an inline sampling field
    off = 20
    while off + 4 <= len(data):
        set_id, set_len = struct.unpack_from(">HH", data, off)
        if set_len < 4 or off + set_len > len(data):
            raise ValueError("bad v9 flowset length")
        body_end = off + set_len
        body = off + 4
        if set_id == 0:  # template set
            _decode_templates(data, body, body_end, source, source_id, cache)
        elif set_id == 1:  # options template (sampling-rate carrier)
            try:
                _decode_options_templates_v9(data, body, body_end, source,
                                             source_id, cache)
            except ValueError:
                pass  # a malformed options set must not drop the datagram's flows
        elif set_id > 255:  # data set
            fields = cache.get(source, source_id, set_id)
            if fields is not None:
                if cache.is_options(source, source_id, set_id):
                    try:
                        _decode_options_data(fields, data, body, body_end,
                                             source, source_id, cache)
                    except ValueError:
                        pass  # a corrupt options record must not drop the datagram's flows
                else:
                    rec_len = _min_record_len(fields)
                    while body + rec_len <= body_end and rec_len > 0:
                        msg, body, has_sampling = _record_from_fields(
                            fields, data, body, FlowType.NETFLOW_V9, now,
                            unix_secs, sysuptime, seq, end=body_end,
                        )
                        msgs.append(msg)
                        if not has_sampling:
                            inherit.append(msg)
        off = body_end
    _apply_exporter_sampling(inherit, cache, source, source_id)
    return msgs


def decode_ipfix(data: bytes, cache: TemplateCache, source: str = "",
                 now: Optional[int] = None) -> list[FlowMessage]:
    if len(data) < 16:
        raise ValueError("short IPFIX header")
    _, length, export_secs, seq, domain = struct.unpack_from(">HHIII", data, 0)
    now = now or export_secs
    msgs = []
    inherit = []  # records lacking an inline sampling field
    off = 16
    end = min(len(data), length)
    while off + 4 <= end:
        set_id, set_len = struct.unpack_from(">HH", data, off)
        if set_len < 4 or off + set_len > end:
            raise ValueError("bad IPFIX set length")
        body_end = off + set_len
        body = off + 4
        if set_id == 2:  # template set
            _decode_templates(data, body, body_end, source, domain, cache,
                              enterprise=True)
        elif set_id == 3:  # options template (sampling-rate carrier)
            try:
                _decode_options_templates_ipfix(data, body, body_end, source,
                                                domain, cache)
            except ValueError:
                pass  # a malformed options set must not drop the datagram's flows
        elif set_id > 255:
            fields = cache.get(source, domain, set_id)
            if fields is not None:
                if cache.is_options(source, domain, set_id):
                    try:
                        _decode_options_data(fields, data, body, body_end,
                                             source, domain, cache)
                    except ValueError:
                        pass  # a corrupt options record must not drop the datagram's flows
                else:
                    rec_len = _min_record_len(fields)
                    while body + rec_len <= body_end and rec_len > 0:
                        msg, body, has_sampling = _record_from_fields(
                            fields, data, body, FlowType.IPFIX, now,
                            export_secs, 0, seq, end=body_end,
                        )
                        msgs.append(msg)
                        if not has_sampling:
                            inherit.append(msg)
        off = body_end
    _apply_exporter_sampling(inherit, cache, source, domain)
    return msgs


def _apply_exporter_sampling(msgs, cache: TemplateCache, source: str,
                             domain: int) -> None:
    """Flows WITHOUT an inline sampling field (callers pass only those)
    inherit the exporter-wide rate announced via options data; records stay
    at the default 1 when neither exists."""
    rate = cache.exporter_sampling(source, domain)
    if not rate:
        return
    for m in msgs:
        m.sampling_rate = rate


def decode_netflow(data: bytes, cache: TemplateCache, source: str = "",
                   now: Optional[int] = None) -> list[FlowMessage]:
    """Dispatch on the version word (v5 / v9 / IPFIX share UDP 2055)."""
    if len(data) < 2:
        raise ValueError("empty datagram")
    version = struct.unpack_from(">H", data, 0)[0]
    if version == 5:
        return decode_v5(data, now)
    if version == 9:
        return decode_v9(data, cache, source, now)
    if version == 10:
        return decode_ipfix(data, cache, source, now)
    raise ValueError(f"unsupported NetFlow version {version}")
