"""Collector service: UDP listeners -> decoders -> Producer.

Replaces the external GoFlow container (ref:
compose/docker-compose-clickhouse-collect.yml:47-62) with in-framework
listeners on the same ports (sFlow 6343, NetFlow/IPFIX 2055) and the same
observed metric surface (SURVEY.md §2-C12), so the reference's perfs
dashboard panels resolve against our /metrics:

    udp_traffic_bytes / udp_traffic_packets
    flow_traffic_bytes{type=...,remote_ip=...} / flow_traffic_packets{...}
    flow_process_nf_flowset_records_sum{router=...}
    flow_process_nf_errors_count{router=...}
    flow_process_nf_templates_count  (+ per-router series)
    flow_process_sf_samples_sum{type=FlowSample,agent=...}
    flow_process_sf_errors_count{agent=...}
    flow_summary_decoding_time_us{name=...}
    flow_decoder_count{worker=...}

The router/agent label carries the exporter's address (port stripped) so
the dashboards can break panels down per exporter, the way the
reference's perfs.json does with `by (router)` / `by (agent)`.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..obs import REGISTRY, MetricsRegistry, get_logger
from ..schema.message import FlowType
from .netflow import TemplateCache, decode_netflow
from .sflow import decode_sflow

log = get_logger("collector")

_TYPE_NAMES = {
    FlowType.SFLOW_5: "sFlow",
    FlowType.NETFLOW_V5: "NetFlow",
    FlowType.NETFLOW_V9: "NetFlow",
    FlowType.IPFIX: "NetFlow",
}


def _exporter_ip(source: str) -> str:
    """Exporter address without the ephemeral port: the `router`/`agent`
    label value (the reference's perfs dashboards break every per-flow
    panel down `by (router)` / `by (agent)` — an unlabeled counter
    cannot answer "which exporter went quiet")."""
    host, _, port = source.rpartition(":")
    return host if host else source


def _export_clock(data: bytes) -> int:
    """Exporter wall-clock from a NetFlow/IPFIX header (0 if unreadable).

    v5/v9 carry unix_secs at offset 8; IPFIX carries export time at
    offset 4 (RFC 7011 §3.1). Used for the delay summary only — flow
    timestamps come from the full decode.
    """
    try:
        version = struct.unpack_from(">H", data, 0)[0]
        if version in (5, 9):
            return struct.unpack_from(">I", data, 8)[0]
        if version == 10:
            return struct.unpack_from(">I", data, 4)[0]
    except struct.error:
        pass
    return 0


@dataclass(frozen=True)
class CollectorConfig:
    netflow_addr: Optional[tuple[str, int]] = ("0.0.0.0", 2055)
    sflow_addr: Optional[tuple[str, int]] = ("0.0.0.0", 6343)
    recv_buf: int = 1 << 20


class CollectorServer:
    """Threaded UDP listeners feeding a Producer (bus or Kafka adapter)."""

    def __init__(self, producer, config: CollectorConfig = CollectorConfig(),
                 registry: MetricsRegistry = REGISTRY):
        self.producer = producer
        self.config = config
        self.templates = TemplateCache()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._sockets: list[socket.socket] = []
        self.ports: dict[str, int] = {}

        self.m_udp_bytes = registry.counter("udp_traffic_bytes")
        self.m_udp_pkts = registry.counter("udp_traffic_packets")
        self.m_flow_bytes = registry.counter("flow_traffic_bytes")
        self.m_flow_pkts = registry.counter("flow_traffic_packets")
        self.m_nf_records = registry.counter("flow_process_nf_flowset_records_sum")
        self.m_nf_errors = registry.counter("flow_process_nf_errors_count")
        self.m_sf_errors = registry.counter("flow_process_sf_errors_count")
        self.m_nf_templates = registry.gauge("flow_process_nf_templates_count")
        self.m_sf_samples = registry.counter("flow_process_sf_samples_sum")
        self.m_decode_us = registry.summary("flow_summary_decoding_time_us")
        self.m_nf_delay = registry.summary(
            "flow_process_nf_delay_summary_seconds",
            "seconds between the exporter's header clock and processing",
        )
        self.m_workers = registry.gauge("flow_decoder_count")

    # ---- datagram handling (also the direct test surface) -----------------

    def handle_netflow(self, data: bytes, source: str = "") -> int:
        self.m_udp_bytes.inc(len(data))
        self.m_udp_pkts.inc()
        router = _exporter_ip(source)
        now = time.time()
        t0 = time.perf_counter()
        try:
            # Stamp receive time here (as the reference collector does) so a
            # skewed exporter clock cannot shift window assignment; the
            # exporter header clock remains the fallback only when now=None
            # (direct decode_netflow callers, e.g. tests).
            msgs = decode_netflow(data, self.templates, source,
                                  now=int(now))
        except (ValueError, struct.error) as e:
            # struct.error covers malformed datagrams that trip fixed-layout
            # unpacks before a bounds check — one spoofed packet must never
            # kill the listener
            self.m_nf_errors.inc(router=router)
            log.debug("netflow decode error from %s: %s", source, e)
            return 0
        finally:
            self.m_decode_us.observe((time.perf_counter() - t0) * 1e6,
                                     name="NetFlow")
        self.m_nf_templates.set(len(self.templates))
        self.m_nf_templates.set(self.templates.count_for(source),
                                router=router)
        self.m_nf_records.inc(len(msgs), router=router)
        # "time between flow and processing" (the reference perfs.json
        # NFDelaySummary panel): exporter header clock -> now, observed once
        # per record so busy exporters weight the quantiles like GoFlow's.
        export_clock = _export_clock(data)
        if export_clock:
            delay = max(0.0, now - export_clock)
            for _ in msgs:
                # labeled per exporter so the dashboards can chart delay
                # quantiles BY ROUTER (the reference perfs.json breaks
                # NFDelaySummary down the same way); the quantile-only
                # panels keep matching — they filter no other label
                self.m_nf_delay.observe(delay, router=router)
        return self._publish(msgs, router)

    def handle_sflow(self, data: bytes, source: str = "") -> int:
        self.m_udp_bytes.inc(len(data))
        self.m_udp_pkts.inc()
        agent = _exporter_ip(source)
        t0 = time.perf_counter()
        try:
            msgs = decode_sflow(data)
        except (ValueError, struct.error) as e:
            self.m_sf_errors.inc(agent=agent)
            log.debug("sflow decode error from %s: %s", source, e)
            return 0
        finally:
            self.m_decode_us.observe((time.perf_counter() - t0) * 1e6,
                                     name="sFlow")
        self.m_sf_samples.inc(len(msgs), type="FlowSample", agent=agent)
        return self._publish(msgs, agent)

    def _publish(self, msgs, remote_ip: str = "") -> int:
        for m in msgs:
            self.producer.send(m)
            name = _TYPE_NAMES.get(m.type, "unknown")
            self.m_flow_bytes.inc(m.bytes, type=name, remote_ip=remote_ip)
            self.m_flow_pkts.inc(m.packets, type=name, remote_ip=remote_ip)
        return len(msgs)

    # ---- service lifecycle ------------------------------------------------

    def start(self) -> "CollectorServer":
        listeners = []
        if self.config.netflow_addr:
            listeners.append(("netflow", self.config.netflow_addr,
                              self.handle_netflow))
        if self.config.sflow_addr:
            listeners.append(("sflow", self.config.sflow_addr,
                              self.handle_sflow))
        for name, addr, handler in listeners:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                            self.config.recv_buf)
            sock.bind(addr)
            sock.settimeout(0.2)
            self._sockets.append(sock)
            self.ports[name] = sock.getsockname()[1]
            t = threading.Thread(
                target=self._serve, args=(sock, handler, name),
                name=f"collector-{name}", daemon=True,
            )
            self._threads.append(t)
            t.start()
            log.info("listening %s on %s:%d", name, addr[0], self.ports[name])
        self.m_workers.set(len(self._threads), worker="udp")
        return self

    def _serve(self, sock, handler, name) -> None:
        while not self._stop.is_set():
            try:
                data, addr = sock.recvfrom(65535)
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                handler(data, f"{addr[0]}:{addr[1]}")
            except Exception:  # noqa: BLE001 — the listener must survive
                log.exception("unexpected %s handler failure", name)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        for s in self._sockets:
            s.close()
