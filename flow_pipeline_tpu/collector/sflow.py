"""sFlow v5 datagram decoder (flow samples with raw packet headers).

Layout per the sFlow v5 spec (sflow.org): XDR-encoded datagram carrying
samples; each flow sample carries flow records; record type 1 is the raw
sampled packet header, which we parse down the Ethernet / 802.1Q / IPv4 /
IPv6 / TCP / UDP / ICMP stack for the FlowMessage fields. Counter samples
are skipped (the pipeline carries flows, matching the collector role in
ref: README.md:15).
"""

from __future__ import annotations

import struct
import time
from typing import Optional

from ..schema.message import FlowMessage, FlowType

_FMT_FLOW_SAMPLE = 1
_FMT_FLOW_SAMPLE_EXPANDED = 3
_REC_RAW_PACKET = 1
_PROTO_ETHERNET = 1


def _parse_packet_header(hdr: bytes, msg: FlowMessage) -> bool:
    """Ethernet(+VLAN) -> IP -> L4. Returns False if not IP."""
    if len(hdr) < 14:
        return False
    etype = struct.unpack_from(">H", hdr, 12)[0]
    off = 14
    if etype == 0x8100 and len(hdr) >= 18:  # 802.1Q VLAN tag
        etype = struct.unpack_from(">H", hdr, 16)[0]
        off = 18
    msg.etype = etype
    if etype == 0x0800 and len(hdr) >= off + 20:  # IPv4
        ihl = (hdr[off] & 0x0F) * 4
        msg.ip_tos = hdr[off + 1]
        msg.ip_ttl = hdr[off + 8]
        msg.proto = hdr[off + 9]
        msg.src_addr = b"\x00" * 12 + hdr[off + 12 : off + 16]
        msg.dst_addr = b"\x00" * 12 + hdr[off + 16 : off + 20]
        l4 = off + ihl
    elif etype == 0x86DD and len(hdr) >= off + 40:  # IPv6
        vtc_fl = struct.unpack_from(">I", hdr, off)[0]
        msg.ipv6_flow_label = vtc_fl & 0xFFFFF
        msg.ip_tos = (vtc_fl >> 20) & 0xFF
        msg.proto = hdr[off + 6]
        msg.ip_ttl = hdr[off + 7]
        msg.src_addr = hdr[off + 8 : off + 24]
        msg.dst_addr = hdr[off + 24 : off + 40]
        l4 = off + 40
    else:
        return False
    if msg.proto in (6, 17) and len(hdr) >= l4 + 4:  # TCP/UDP ports
        msg.src_port, msg.dst_port = struct.unpack_from(">HH", hdr, l4)
        if msg.proto == 6 and len(hdr) >= l4 + 14:
            msg.tcp_flags = hdr[l4 + 13]
    elif msg.proto in (1, 58) and len(hdr) >= l4 + 2:  # ICMP(v6)
        msg.icmp_type, msg.icmp_code = hdr[l4], hdr[l4 + 1]
    return True


def decode_sflow(data: bytes, now: Optional[int] = None) -> list[FlowMessage]:
    if len(data) < 28:
        raise ValueError("short sFlow datagram")
    now = now or int(time.time())
    version, ip_ver = struct.unpack_from(">II", data, 0)
    if version != 5:
        raise ValueError(f"unsupported sFlow version {version}")
    off = 8
    agent_len = 4 if ip_ver == 1 else 16
    agent = data[off : off + agent_len]
    off += agent_len
    _sub_agent, seq, _uptime, n_samples = struct.unpack_from(">IIII", data, off)
    off += 16
    sampler = b"\x00" * 12 + agent if agent_len == 4 else agent

    msgs = []
    for _ in range(n_samples):
        if off + 8 > len(data):
            raise ValueError("truncated sFlow sample header")
        fmt, slen = struct.unpack_from(">II", data, off)
        off += 8
        s_end = off + slen
        if s_end > len(data):
            raise ValueError("truncated sFlow sample")
        fmt_type = fmt & 0xFFF  # low bits: format within enterprise 0
        if fmt_type in (_FMT_FLOW_SAMPLE, _FMT_FLOW_SAMPLE_EXPANDED):
            p = off
            if fmt_type == _FMT_FLOW_SAMPLE:
                (_sseq, _source, rate, _pool, _drops, in_if, out_if,
                 n_rec) = struct.unpack_from(">IIIIIIII", data, p)
                p += 32
            else:  # expanded: source/interface fields are (format, value)
                (_sseq, _sfmt, _sval, rate, _pool, _drops, in_fmt, in_val,
                 out_fmt, out_val, n_rec) = struct.unpack_from(
                    ">IIIIIIIIIII", data, p
                )
                in_if, out_if = in_val, out_val
                p += 44
            for _ in range(n_rec):
                # Bounds discipline matches the v9 flowset checks: a corrupt
                # rlen/n_rec must not read into the next sample's bytes and
                # silently mis-parse records.
                if p + 8 > s_end:
                    raise ValueError("truncated sFlow flow-record header")
                rfmt, rlen = struct.unpack_from(">II", data, p)
                p += 8
                r_end = p + rlen
                if r_end > s_end:
                    raise ValueError("sFlow flow record overruns sample")
                if (rfmt & 0xFFF) == _REC_RAW_PACKET and rlen >= 16:
                    proto, frame_len, _stripped, hdr_len = struct.unpack_from(
                        ">IIII", data, p
                    )
                    hdr = data[p + 16 : min(p + 16 + hdr_len, r_end)]
                    if proto == _PROTO_ETHERNET:
                        msg = FlowMessage(
                            type=FlowType.SFLOW_5,
                            time_received=now,
                            time_flow_start=now,
                            time_flow_end=now,
                            sampling_rate=rate or 1,
                            sequence_num=seq,
                            sampler_address=sampler,
                            bytes=frame_len,
                            packets=1,
                            in_if=in_if,
                            out_if=out_if,
                        )
                        if _parse_packet_header(hdr, msg):
                            msgs.append(msg)
                p = r_end
        off = s_end
    return msgs
