"""Flow collector: sFlow/NetFlow/IPFIX UDP -> FlowMessage -> bus.

The reference outsources collection to the external GoFlow image (run as
``cloudflare/goflow:latest`` with UDP 6343 sFlow + 2055 NetFlow/IPFIX and a
:8080 metrics port — ref: compose/docker-compose-clickhouse-collect.yml:47-62,
README.md:15). This package brings collection into the framework so no
external binary is required:

- ``netflow``: NetFlow v5 (fixed layout), NetFlow v9 and IPFIX
  (template-based) datagram decoders.
- ``sflow``: sFlow v5 flow-sample decoder, parsing the sampled raw packet
  headers (Ethernet / 802.1Q / IPv4 / IPv6 / TCP / UDP / ICMP).
- ``udp``: the listener service wiring decoders to a Producer, exposing the
  GoFlow-shaped metric surface (SURVEY.md §2-C12: flow_process_nf_*,
  flow_traffic_*, udp_traffic_*, flow_decoder_count, ...) so the reference's
  perfs dashboards keep working against our collector.

All decoders are pure functions bytes -> list[FlowMessage]; the reference's
observed semantics (16-byte addresses with IPv4 in the trailing bytes,
TimeReceived in seconds, sampling rate per flow) are preserved.
"""

from .netflow import decode_netflow, TemplateCache
from .sflow import decode_sflow
from .udp import CollectorServer, CollectorConfig

__all__ = [
    "decode_netflow",
    "TemplateCache",
    "decode_sflow",
    "CollectorServer",
    "CollectorConfig",
]
