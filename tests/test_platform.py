"""Platform selection + degrade diagnosis (utils.platform)."""

import os
import socket
import struct
import threading
from unittest import mock

from flow_pipeline_tpu.utils import platform as plat


class TestCpuRequested:
    def test_only_cpu_counts(self):
        with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "cpu"}):
            assert plat.cpu_requested()
        with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "tpu,cpu"}):
            assert not plat.cpu_requested()  # priority list != cpu request
        with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "axon"}):
            assert not plat.cpu_requested()


class TestResolvePlatformInfo:
    def test_cpu_request_short_circuits(self):
        with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "cpu"}):
            platform, reason = plat.resolve_platform_info()
        assert platform == "cpu" and reason is None

    def test_probe_failure_carries_child_stderr(self):
        import subprocess

        err = subprocess.CalledProcessError(
            1, ["python"], output="", stderr="Trace...\nRuntimeError: boom\n"
        )
        with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "axon"}), \
                mock.patch.object(plat.subprocess, "run", side_effect=err):
            platform, reason = plat.resolve_platform_info()
        assert platform == "cpu"
        assert reason == "backend init failed: RuntimeError: boom"

    def test_probe_timeout_carries_relay_diagnosis(self):
        import subprocess

        to = subprocess.TimeoutExpired(["python"], 1.0)
        with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "axon"}), \
                mock.patch.object(plat.subprocess, "run", side_effect=to), \
                mock.patch.object(plat, "_relay_diagnosis",
                                  return_value="relay dead"):
            platform, reason = plat.resolve_platform_info(probe_timeout=1.0)
        assert platform == "cpu"
        assert reason == "backend init timed out after 1s; relay dead"


class FakeRelay:
    """Minimal TCP server standing in for the axon relay."""

    def __init__(self, behavior):
        self.behavior = behavior  # "close" | "hold" | "banner"
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.port = self.sock.getsockname()[1]
        self._conns = []
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        try:
            conn, _ = self.sock.accept()
        except OSError:
            return
        if self.behavior == "close":
            conn.close()
        elif self.behavior == "banner":
            conn.sendall(b"hello")
            self._conns.append(conn)
        else:  # hold
            self._conns.append(conn)

    def close(self):
        for c in self._conns:
            c.close()
        self.sock.close()


class TestRelayDiagnosis:
    def diag(self, relay):
        env = {"PALLAS_AXON_POOL_IPS": "127.0.0.1",
               "AXON_POOL_SVC_OVERRIDE": "127.0.0.1"}
        real_connect = socket.create_connection

        def to_fake(addr, timeout):
            return real_connect(("127.0.0.1", relay.port), timeout)

        with mock.patch.dict(os.environ, env), \
                mock.patch.object(socket, "create_connection", to_fake):
            return plat._relay_diagnosis()

    def test_no_tunnel_configured(self):
        with mock.patch.dict(os.environ, {}, clear=False):
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)
            assert "no TPU tunnel" in plat._relay_diagnosis()

    def test_accept_then_close_means_upstream_down(self):
        relay = FakeRelay("close")
        try:
            assert "immediately closes" in self.diag(relay)
        finally:
            relay.close()

    def test_held_connection_means_grant_contention(self):
        relay = FakeRelay("hold")
        try:
            assert "held elsewhere" in self.diag(relay)
        finally:
            relay.close()

    def test_banner_means_init_stage_timeout(self):
        relay = FakeRelay("banner")
        try:
            assert "relay responded" in self.diag(relay)
        finally:
            relay.close()

    def test_reset_during_probe_is_a_diagnosis_not_a_crash(self):
        # an RST mid-probe must come back as a reason string — raising
        # would crash the exact degrade path this code exists to survive
        class RstRelay(FakeRelay):
            def _serve(self):
                try:
                    conn, _ = self.sock.accept()
                except OSError:
                    return
                # force an RST instead of FIN
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack("ii", 1, 0))
                conn.close()

        relay = RstRelay("rst")
        try:
            out = self.diag(relay)
            assert isinstance(out, str) and out
        finally:
            relay.close()

    def test_host_falls_back_to_pool_ip(self):
        env = {"PALLAS_AXON_POOL_IPS": "203.0.113.9,203.0.113.10"}
        seen = {}

        def spy(addr, timeout):
            seen["addr"] = addr
            raise OSError("refused")

        with mock.patch.dict(os.environ, env), \
                mock.patch.object(socket, "create_connection", spy):
            os.environ.pop("AXON_POOL_SVC_OVERRIDE", None)
            out = plat._relay_diagnosis()
        assert seen["addr"] == ("203.0.113.9", 2024)
        assert "unreachable" in out
