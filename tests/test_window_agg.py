"""WindowAggregator (device path) vs the numpy flows_5m oracle — the
BASELINE config #1 parity gate, exercised across many batches and window
boundaries, with watermark-driven flushing."""

import numpy as np

from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile, ZipfProfile
from flow_pipeline_tpu.models.oracle import flows_5m
from flow_pipeline_tpu.models.window_agg import WindowAggConfig, WindowAggregator
from flow_pipeline_tpu.schema.batch import FlowBatch


def run_pipeline(batches, config):
    agg = WindowAggregator(config)
    for b in batches:
        agg.update(b)
    return agg


def check_parity(flushed, batch):
    """flushed rows == oracle rows, exactly."""
    oracle = flows_5m(batch)
    assert len(flushed["timeslot"]) == len(oracle["timeslot"])
    got = {
        (int(t), int(s), int(d), int(e)): (int(b), int(p), int(c))
        for t, s, d, e, b, p, c in zip(
            flushed["timeslot"],
            flushed["src_as"],
            flushed["dst_as"],
            flushed["etype"],
            flushed["bytes"],
            flushed["packets"],
            flushed["count"],
        )
    }
    for i in range(len(oracle["timeslot"])):
        key = (
            int(oracle["timeslot"][i]),
            int(oracle["src_as"][i]),
            int(oracle["dst_as"][i]),
            int(oracle["etype"][i]),
        )
        assert got[key] == (
            int(oracle["bytes"][i]),
            int(oracle["packets"][i]),
            int(oracle["count"][i]),
        )


class TestWindowAggParity:
    def test_single_batch_parity(self):
        g = FlowGenerator(MockerProfile(), seed=21, rate=1000.0)
        batch = g.batch(4096)
        agg = run_pipeline([batch], WindowAggConfig(batch_size=4096))
        check_parity(agg.flush(force=True), batch)

    def test_multi_batch_windows_parity(self):
        # 20 batches spanning several 5-minute windows
        g = FlowGenerator(MockerProfile(), seed=22, rate=50.0)
        batches = [g.batch(500) for _ in range(20)]
        agg = run_pipeline(batches, WindowAggConfig(batch_size=512))
        check_parity(agg.flush(force=True), FlowBatch.concat(batches))

    def test_watermark_flushes_only_closed(self):
        g = FlowGenerator(MockerProfile(), seed=23, rate=10.0)  # 50s per batch
        agg = WindowAggregator(WindowAggConfig(batch_size=512))
        for _ in range(20):  # 1000 seconds -> at least 2 closed windows
            agg.update(g.batch(500))
        closed = agg.closed_slots()
        assert len(closed) >= 2
        flushed = agg.flush()
        assert set(int(t) for t in flushed["timeslot"]) == set(closed)
        # open window still buffered
        assert len(agg.windows) >= 1

    def test_flush_then_rest_covers_everything(self):
        g = FlowGenerator(MockerProfile(), seed=24, rate=10.0)
        batches = [g.batch(500) for _ in range(10)]
        agg = run_pipeline(batches, WindowAggConfig(batch_size=512))
        part1 = agg.flush()
        part2 = agg.flush(force=True)
        total = int(part1["count"].sum() + part2["count"].sum())
        assert total == 5000

    def test_zipf_high_cardinality_addr_keys(self):
        config = WindowAggConfig(
            key_cols=("src_addr", "dst_addr"), batch_size=2048
        )
        g = FlowGenerator(ZipfProfile(n_keys=300), seed=25, rate=10000.0)
        batch = g.batch(2048)
        agg = run_pipeline([batch], config)
        flushed = agg.flush(force=True)
        from flow_pipeline_tpu.models.oracle import exact_groupby

        oracle = exact_groupby(batch, ["src_addr", "dst_addr"], timeslot=True)
        assert len(flushed["timeslot"]) == len(oracle["timeslot"])
        assert flushed["bytes"].sum() == oracle["bytes"].sum()
        assert flushed["count"].sum() == 2048

    def test_empty_batch_noop(self):
        agg = WindowAggregator(WindowAggConfig(batch_size=64))
        agg.update(FlowBatch.empty(0))
        out = agg.flush(force=True)
        assert len(out["timeslot"]) == 0


class TestHashCollisionFallback:
    """The hash-grouped fast path must keep flows_5m bit-exact even when
    the 64-bit grouping hash collides: the drain re-runs the chunk
    through the lexicographic path."""

    def test_forced_collision_uses_exact_fallback(self, monkeypatch):
        import jax.numpy as jnp

        from flow_pipeline_tpu.models import window_agg as wa
        from flow_pipeline_tpu.ops import segment

        # A degenerate hash that maps EVERY row to one value guarantees a
        # collision whenever two distinct keys coexist. Unique cache keys
        # (window_seconds=77) keep the stubbed trace out of the shared
        # lru_cache entries other tests use.
        def degenerate(keys):
            n = keys.shape[0]
            one = jnp.ones(n, jnp.uint32)
            return one, one

        monkeypatch.setattr(segment, "hash_lanes", degenerate)
        config = WindowAggConfig(window_seconds=77, batch_size=64)
        gen = FlowGenerator(MockerProfile(), seed=5)
        batch = gen.batch(180)
        agg = WindowAggregator(config)
        agg.update(batch)
        agg._drain()

        # independent exact reference: same config, un-stubbed hash
        monkeypatch.undo()
        wa._cached_update.cache_clear()
        wa._cached_update_exact.cache_clear()
        ref = WindowAggregator(config)
        ref.update(batch)
        ref._drain()
        assert agg.windows.keys() == ref.windows.keys()
        for slot in ref.windows:
            assert agg.windows[slot].keys() == ref.windows[slot].keys()
            for k in ref.windows[slot]:
                np.testing.assert_array_equal(
                    agg.windows[slot][k], ref.windows[slot][k])

    def test_fallback_required_when_missing(self):
        import jax.numpy as jnp
        import pytest

        from flow_pipeline_tpu.models.window_agg import WindowAggregator

        agg = WindowAggregator(WindowAggConfig(batch_size=64))
        fake = (jnp.zeros((4, 4), jnp.uint32), jnp.zeros((4, 4), jnp.int32),
                jnp.zeros(4, jnp.int32), jnp.asarray(0),
                jnp.asarray(True))  # collided, no fallback
        agg.add_partial(fake, fallback=None)
        with pytest.raises(RuntimeError, match="no exact"):
            agg._drain()
