"""flowcensus runtime contracts: the SketchFamily registry the
dispatch layers iterate (flow_pipeline_tpu/families/registry.py).

The static side — completeness of every registration, both-ways kind
coverage — is the family-citizenship lint rule's job
(tests/test_flowlint.py). Here: the runtime API the refactored
dispatch sites actually call."""

import os
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

from flow_pipeline_tpu.families import registry  # noqa: E402


class TestRegistryShape:
    def test_registration_order_is_deterministic(self):
        # dispatch loops built on families() must stay bit-stable
        assert [f.kind for f in registry.families()] == \
            ["hh", "wagg", "dense", "spread"]

    def test_unknown_kind_raises_helpfully(self):
        with pytest.raises(KeyError, match="registered:"):
            registry.family("hll")

    def test_snapshot_kind_index(self):
        assert registry.family_for_snapshot("windowed_hh").kind == "hh"
        assert registry.family_for_snapshot("windowed_spread").kind \
            == "spread"
        assert registry.family_for_snapshot("no_such_kind") is None
        # wagg has no snapshot kind: windows are exact stores, captured
        # by the member's isinstance branch, never via snapshot_kind
        assert registry.family("wagg").snapshot_kind is None

    def test_checkpoint_kind_index(self):
        assert registry.family_for_checkpoint("window_agg").kind == "wagg"
        assert registry.family_for_checkpoint("windowed_dense").kind \
            == "dense"
        assert registry.family_for_checkpoint("ddos") is None

    def test_payload_kind_index_covers_invertible(self):
        # both wire tags of the hh family route to one descriptor
        assert registry.family_for_payload("hh").kind == "hh"
        assert registry.family_for_payload("hh_inv").kind == "hh"
        assert registry.family_for_payload("spread").kind == "spread"


class TestHooks:
    def test_every_registered_hook_resolves(self):
        # the lint checks this statically (parse, no imports); the
        # runtime twin actually imports every target once
        hook_fields = ("payload", "merge", "top_rows", "serve_capture",
                       "serve_capture_merged", "checkpoint_save",
                       "checkpoint_restore", "audit_class")
        for fam in registry.families():
            for field in hook_fields:
                ref = getattr(fam, field)
                if ref:
                    assert callable(registry.resolve(ref)), \
                        (fam.kind, field)

    def test_hook_returns_none_for_absent_surface(self):
        wagg = registry.family("wagg")
        assert registry.hook(wagg, "serve_capture") is None

    def test_merge_hooks_share_one_signature(self):
        # the coordinator calls every merge hook as (payloads, config)
        from flow_pipeline_tpu.mesh import merge as merge_ops

        assert registry.hook(registry.family("hh"), "merge") \
            is merge_ops.merge_hh
        assert registry.hook(registry.family("wagg"), "merge") \
            is merge_ops.merge_wagg
        assert merge_ops.merge_wagg([], config=None) == {}

    def test_resolve_caches(self):
        ref = registry.family("spread").merge
        assert registry.resolve(ref) is registry.resolve(ref)


class TestFacts:
    def test_audit_attrs_iterates_shadowed_families(self):
        # the guard pause and serve merge loops iterate this instead of
        # naming `audit` / `spread_audit` one by one
        assert registry.audit_attrs() == (("hh", "audit"),
                                          ("spread", "spread_audit"))

    def test_delta_planes_by_payload_kind(self):
        assert registry.delta_planes("hh") == (("cms", False),)
        assert registry.delta_planes("hh_inv") == (("cms", False),)
        assert registry.delta_planes("spread") == (("regs", True),)
        assert registry.delta_planes("wagg") == ()
        assert registry.delta_planes("never_registered") == ()

    def test_merge_monoids_match_the_algebra(self):
        monoids = {f.kind: f.merge_monoid for f in registry.families()}
        assert monoids == {"hh": "u64-sum", "wagg": "u64-sum",
                           "dense": "i64-sum", "spread": "max"}

    def test_double_registration_rejected(self):
        with pytest.raises(ValueError, match="registered twice"):
            registry.register(registry.SketchFamily(kind="hh"))
