"""flowhistory: durable snapshot archive + time-travel query surface.

The acceptance gates this file carries:

- **Record-and-replay parity**: during a live run, every ``/query/*``
  answer is recorded at the version it was served; afterwards the same
  query with ``?version=`` (and ``?at=``) against the archive must
  answer BYTE-IDENTICAL — for table and invertible sketches, spread
  families, and the mesh publisher (slow leg), including chains that
  cross keyframe boundaries and survive a retention compaction.
- **Damage gate**: torn tails, CRC-corrupted keyframes, CRC-corrupted
  mid-chain deltas, and eviction mid-read all skip to the next intact
  keyframe — zero damaged snapshots served, gaps answer 404 with
  nearest-version hints, and a writer crash mid-append leaves a
  recoverable archive.
- **-serve.feed_bytes** (satellite): the promoted feed byte budget is
  enforced at the configured value.
- **Gateway range retention** (satellite): a gateway given
  ``-history.dir`` answers ``/query/range`` for slots older than the
  live window, bit-exact vs the rows the live path served when those
  slots were current.

The slow mesh leg runs in ``make history-parity`` / CI.
"""

import json
import os
import tempfile
import urllib.error
import urllib.request

import numpy as np
import pytest

from flow_pipeline_tpu.engine import (StreamWorker, WindowedHeavyHitter,
                                      WorkerConfig)
from flow_pipeline_tpu.gateway import SnapshotGateway
from flow_pipeline_tpu.gateway.delta import (encode_delta, snapshot_state,
                                             state_to_snapshot)
from flow_pipeline_tpu.gateway.feed import SnapshotFeed
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.history import (ArchiveReader, ArchiveWriter,
                                       HistoryGapError, HistoryServer)
from flow_pipeline_tpu.models import (HeavyHitterConfig, WindowAggConfig,
                                      WindowAggregator)
from flow_pipeline_tpu.serve import ServeServer, SnapshotStore
from flow_pipeline_tpu.serve.publisher import WorkerServePublisher
from flow_pipeline_tpu.sink import MemorySink
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer

T0 = 1_699_999_800  # window-aligned stream start


def _get_raw(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10).read()


def _get(port, path):
    return json.loads(_get_raw(port, path))


def _fetch(port, path):
    """(status, body) — errors are answers too; a 400 the live path
    served must replay as the same 400."""
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10)
        return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _fill_bus(batches=8, per=500, rate=5.0, seed=91,
              spread_fraction=0.0):
    bus = InProcessBus()
    bus.create_topic("flows", 1)
    profile = ZipfProfile(n_keys=100, alpha=1.3,
                          **({"spread_fraction": spread_fraction}
                             if spread_fraction else {}))
    gen = FlowGenerator(profile, seed=seed, t0=T0, rate=rate)
    prod = Producer(bus, fixedlen=True)
    for _ in range(batches):
        prod.send_many(gen.batch(per).to_messages())
    return bus


def _models(hh_sketch="table"):
    return {
        "flows_5m": WindowAggregator(WindowAggConfig(batch_size=512)),
        "top_talkers": WindowedHeavyHitter(
            HeavyHitterConfig(batch_size=512, width=1 << 12, capacity=64,
                              hh_sketch=hh_sketch),
            k=10),
    }


def _quiesce(worker):
    """Stop the pipeline threads once the bus is drained (leaked
    daemon pollers pollute FAULTS counters suite-wide)."""
    if worker.executor is not None:
        worker.executor.stop()
    if worker.flusher is not None:
        worker.flusher.stop()
    stop_feed = getattr(worker.consumer, "stop", None)
    if stop_feed is not None:
        stop_feed()


# ---- synthetic canonical states (the delta-codec test shape) ---------------


def _mk_state(version, *, width=8, bump=0):
    """One hh family (+u64 CMS planes), one dense family, one range
    table whose slot set slides with ``bump`` — the delta-codec test
    state, reused so the archive inherits its edge coverage."""
    rng = np.random.default_rng(7)
    cms = rng.integers(0, 1000, size=(3, 2, width)).astype(np.uint64)
    if bump:
        cms[0, 1, bump % width] += np.uint64(bump)
    rows = {
        "src_addr": np.arange(4, dtype=np.uint32) + np.uint32(bump),
        "bytes": np.asarray([9.0, 5.0, 3.0, 1.0], np.float32),
        "valid": np.asarray([True, True, True, False]),
    }
    return {
        "version": int(version), "created": 100.0 + version,
        "watermark": float(T0 + 300 * version), "flows_seen": 10 * version,
        "source": "worker",
        "families": {
            "hh": {"kind": "hh", "window_start": T0, "depth": 4,
                   "key_lanes": 2, "value_cols": ["bytes"],
                   "rows": rows, "cms": cms},
            "dense": {"kind": "dense", "window_start": T0, "depth": 4,
                      "key_lanes": 1, "value_cols": [],
                      "rows": {"port": np.arange(4, dtype=np.uint32)},
                      "cms": None},
        },
        "ranges": {"flows_5m": [
            [T0, {"timeslot": np.asarray([T0, T0], np.int64),
                  "bytes": np.asarray([1, 2], np.uint64)}],
            [T0 + 300 * max(1, bump),
             {"timeslot": np.asarray([T0 + 300], np.int64),
              "bytes": np.asarray([3 + bump], np.uint64)}],
        ]},
        "audit": {"hh": {"cms_err": 0.0, "windows": version}},
    }


def _assert_states_equal(a, b):
    assert a["version"] == b["version"]
    assert a["created"] == b["created"]
    assert a["watermark"] == b["watermark"]
    assert a["flows_seen"] == b["flows_seen"]
    assert set(a["families"]) == set(b["families"])
    for name, f in a["families"].items():
        g = b["families"][name]
        for k in ("kind", "window_start", "depth", "key_lanes"):
            assert f[k] == g[k], (name, k)
        assert list(f["value_cols"]) == list(g["value_cols"])
        assert set(f["rows"]) == set(g["rows"])
        for c in f["rows"]:
            x, y = np.asarray(f["rows"][c]), np.asarray(g["rows"][c])
            assert x.dtype == y.dtype and np.array_equal(x, y), (name, c)
        if f["cms"] is None:
            assert g["cms"] is None
        else:
            assert g["cms"] is not None
            assert f["cms"].dtype == g["cms"].dtype
            assert np.array_equal(f["cms"], g["cms"])
    assert set(a["ranges"]) == set(b["ranges"])
    for t, slots in a["ranges"].items():
        gslots = b["ranges"][t]
        assert [int(s) for s, _ in slots] == [int(s) for s, _ in gslots]
        for (_, rows), (_, grows) in zip(slots, gslots):
            assert set(rows) == set(grows)
            for c in rows:
                assert np.array_equal(np.asarray(rows[c]),
                                      np.asarray(grows[c]))
    assert a["audit"] == b["audit"]


def _archive_states(dir_, states, keyframe_every=3, **kw):
    w = ArchiveWriter(dir_, keyframe_every=keyframe_every, **kw)
    prev = None
    for s in states:
        w.record(prev, s)
        prev = s
    w.commit()
    w.close()
    return w


def _rec_index(dir_):
    """[(segment path, [record dicts])] — test access to the scan for
    computing corruption offsets."""
    r = ArchiveReader(dir_)
    with r._lock:
        return [(p, list(recs)) for p, recs in r._scan_locked()]


def _flip_byte(path, off):
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


# ---- archive round trip (unit, synthetic states) ---------------------------


class TestArchiveRoundTrip:
    def test_every_version_reconstructs_exactly(self, tmp_path):
        states = [_mk_state(i + 1, bump=i) for i in range(10)]
        _archive_states(str(tmp_path), states, keyframe_every=3)
        r = ArchiveReader(str(tmp_path))
        assert r.versions() == list(range(1, 11))
        # chains of up to 3 deltas: versions 2-4, 6-8, 10 replay
        # through apply_delta; 1, 5, 9 are keyframe hits
        for s in states:
            _assert_states_equal(r.reconstruct(s["version"]), s)

    def test_segments_rotate_on_keyframe(self, tmp_path):
        states = [_mk_state(i + 1, bump=i) for i in range(7)]
        _archive_states(str(tmp_path), states, keyframe_every=3)
        segs = sorted(p for p in os.listdir(str(tmp_path))
                      if p.endswith(".fharc"))
        # keyframes at v1, v5 (after 3 deltas), each its own segment
        assert segs == ["seg-%020d.fharc" % 1, "seg-%020d.fharc" % 5]

    def test_restart_starts_a_new_keyframe_segment(self, tmp_path):
        states = [_mk_state(i + 1, bump=i) for i in range(8)]
        _archive_states(str(tmp_path), states[:5], keyframe_every=100)
        w = ArchiveWriter(str(tmp_path), keyframe_every=100)
        assert w.last_version == 5
        prev = states[4]
        for s in states[5:]:
            w.record(prev, s)
            prev = s
        w.commit()
        w.close()
        r = ArchiveReader(str(tmp_path))
        assert r.versions() == list(range(1, 9))
        # the post-restart chain anchors a NEW segment at v6 even
        # though the cadence would have allowed a delta
        assert os.path.exists(
            os.path.join(str(tmp_path), "seg-%020d.fharc" % 6))
        for s in states:
            _assert_states_equal(r.reconstruct(s["version"]), s)

    def test_backwards_version_is_refused(self, tmp_path):
        w = ArchiveWriter(str(tmp_path))
        w.record(None, _mk_state(5))
        assert w.record(None, _mk_state(3)) == "skip"
        assert w.record(None, _mk_state(5)) == "skip"
        w.commit()
        w.close()
        assert ArchiveReader(str(tmp_path)).versions() == [5]

    def test_retention_evicts_whole_oldest_segments(self, tmp_path):
        states = [_mk_state(i + 1, bump=i) for i in range(9)]
        _archive_states(str(tmp_path), states, keyframe_every=2)
        total = sum(os.path.getsize(os.path.join(str(tmp_path), p))
                    for p in os.listdir(str(tmp_path)))
        # re-open with a budget that forces out the oldest segment(s)
        w = ArchiveWriter(str(tmp_path), retain_bytes=total // 2)
        w.commit()
        w.close()
        r = ArchiveReader(str(tmp_path))
        kept = r.versions()
        assert kept and kept[-1] == 9
        assert len(kept) < 9
        # kept versions: still exact; evicted: honest gap with hints
        for s in states:
            if s["version"] in kept:
                _assert_states_equal(r.reconstruct(s["version"]), s)
            else:
                with pytest.raises(HistoryGapError) as ei:
                    r.reconstruct(s["version"])
                assert ei.value.before is None  # whole prefix evicted
                assert ei.value.after == kept[0]

    def test_retention_never_evicts_the_last_segment(self, tmp_path):
        states = [_mk_state(i + 1, bump=i) for i in range(4)]
        _archive_states(str(tmp_path), states, keyframe_every=2,
                        retain_bytes=1)  # absurd bound: 1 byte
        r = ArchiveReader(str(tmp_path))
        # the newest segment survives any bound
        assert r.versions() == [4]

    def test_version_at_resolves_newest_at_or_before(self, tmp_path):
        states = [_mk_state(v) for v in (1, 2, 3)]  # created = 101..103
        _archive_states(str(tmp_path), states)
        r = ArchiveReader(str(tmp_path))
        assert r.version_at(100.5) is None  # predates the archive
        assert r.version_at(101.0) == 1
        assert r.version_at(102.7) == 2
        assert r.version_at(1e12) == 3

    def test_slot_index_maps_slots_to_newest_holder(self, tmp_path):
        # bump slides the second range slot: older slots stay indexed
        # at the newest version that still held them
        states = [_mk_state(i + 1, bump=i) for i in range(4)]
        _archive_states(str(tmp_path), states)
        idx = ArchiveReader(str(tmp_path)).slot_index()["flows_5m"]
        assert idx[T0] == 4            # held by every version
        assert idx[T0 + 300] == 2      # bump=1 (v2) held slot T0+300
        assert idx[T0 + 900] == 4      # bump=3 (v4)


# ---- damage gate -----------------------------------------------------------


class TestArchiveDamage:
    def _states(self, n=9):
        return [_mk_state(i + 1, bump=i) for i in range(n)]

    def test_torn_tail_drops_only_the_tail(self, tmp_path):
        states = self._states()
        _archive_states(str(tmp_path), states, keyframe_every=3)
        segs = _rec_index(str(tmp_path))
        last_seg = segs[-1][0]
        os.truncate(last_seg, os.path.getsize(last_seg) - 5)
        r = ArchiveReader(str(tmp_path))
        assert r.versions() == list(range(1, 9))  # v9 torn away
        for s in states[:8]:
            _assert_states_equal(r.reconstruct(s["version"]), s)
        with pytest.raises(HistoryGapError) as ei:
            r.reconstruct(9)
        assert ei.value.before == 8 and ei.value.after is None

    def test_writer_crash_mid_append_is_recoverable(self, tmp_path):
        """The journal torn-tail discipline: a crash mid-append leaves
        a torn last record; a restarted writer never touches the torn
        segment and anchors a fresh keyframe segment."""
        states = self._states(6)
        _archive_states(str(tmp_path), states[:5], keyframe_every=100)
        segs = _rec_index(str(tmp_path))
        os.truncate(segs[-1][0], os.path.getsize(segs[-1][0]) - 3)
        w = ArchiveWriter(str(tmp_path), keyframe_every=100)
        assert w.last_version == 4  # the torn v5 is not resumable
        assert w.record(states[4], states[5]) == "key"
        w.commit()
        w.close()
        r = ArchiveReader(str(tmp_path))
        assert r.versions() == [1, 2, 3, 4, 6]
        _assert_states_equal(r.reconstruct(6), states[5])
        with pytest.raises(HistoryGapError) as ei:
            r.reconstruct(5)
        assert (ei.value.before, ei.value.after) == (4, 6)

    def test_corrupt_keyframe_gaps_the_whole_segment(self, tmp_path):
        states = self._states()
        _archive_states(str(tmp_path), states, keyframe_every=2)
        segs = _rec_index(str(tmp_path))
        assert len(segs) == 3  # keyframes at 1, 4, 7
        mid_path, mid_recs = segs[1]
        assert mid_recs[0]["t"] == "key"
        _flip_byte(mid_path, mid_recs[0]["off"])
        r = ArchiveReader(str(tmp_path))
        # the middle segment (v4-6) is unusable; neighbors still serve
        assert r.versions() == [1, 2, 3, 7, 8, 9]
        for v in (4, 5, 6):
            with pytest.raises(HistoryGapError) as ei:
                r.reconstruct(v)
            assert (ei.value.before, ei.value.after) == (3, 7)
        for s in states:
            if s["version"] not in (4, 5, 6):
                _assert_states_equal(r.reconstruct(s["version"]), s)

    def test_corrupt_delta_mid_chain_gaps_the_rest(self, tmp_path):
        states = self._states(6)
        _archive_states(str(tmp_path), states, keyframe_every=100)
        (path, recs), = _rec_index(str(tmp_path))
        assert recs[3]["t"] == "dlt"  # v4
        _flip_byte(path, recs[3]["off"])
        r = ArchiveReader(str(tmp_path))
        # keyframe + intact prefix serve; v4 onward is gapped (deltas
        # past the damage have no anchor)
        assert r.versions() == [1, 2, 3]
        for s in states[:3]:
            _assert_states_equal(r.reconstruct(s["version"]), s)
        for v in (4, 5, 6):
            with pytest.raises(HistoryGapError) as ei:
                r.reconstruct(v)
            assert (ei.value.before, ei.value.after) == (3, None)

    def test_eviction_mid_read_answers_a_gap(self, tmp_path, monkeypatch):
        """The file vanishing between index and read (retention racing
        a query) must answer a gap with FRESH hints — never a crash,
        never a partial snapshot."""
        states = self._states()
        _archive_states(str(tmp_path), states, keyframe_every=3)
        r = ArchiveReader(str(tmp_path))
        with r._lock:
            stale = [(p, list(recs)) for p, recs in r._scan_locked()]
        os.remove(stale[0][0])  # evict the segment holding v1-4
        real = r._scan_locked
        calls = {"n": 0}

        def flaky_scan():
            calls["n"] += 1
            return stale if calls["n"] == 1 else real()

        monkeypatch.setattr(r, "_scan_locked", flaky_scan)
        with pytest.raises(HistoryGapError) as ei:
            r.reconstruct(2)
        assert ei.value.before is None and ei.value.after == 5

    def test_damage_is_counted(self, tmp_path):
        from flow_pipeline_tpu.history import register_history_metrics

        m = register_history_metrics()
        before = m["damage"].value()
        states = self._states(4)
        _archive_states(str(tmp_path), states, keyframe_every=100)
        (path, recs), = _rec_index(str(tmp_path))
        _flip_byte(path, recs[1]["off"])
        ArchiveReader(str(tmp_path)).versions()
        assert m["damage"].value() > before


try:  # property test where hypothesis exists (repo convention)
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @settings(max_examples=10, deadline=None)
    @given(st.lists(st.integers(0, 1000), min_size=2, max_size=8),
           st.integers(1, 4))
    def test_archive_round_trip_property(bumps, keyframe_every):
        """Any state sequence archives and reconstructs exactly at any
        keyframe cadence — arrays bit-identical, dtypes preserved."""
        with tempfile.TemporaryDirectory() as d:
            states = [_mk_state(i + 1, bump=b)
                      for i, b in enumerate(bumps)]
            _archive_states(d, states, keyframe_every=keyframe_every)
            r = ArchiveReader(d)
            for s in states:
                _assert_states_equal(r.reconstruct(s["version"]), s)
except ImportError:  # pragma: no cover
    pass


# ---- record-and-replay parity (worker publisher) ---------------------------


PARITY_PATHS = (
    "/query/topk", "/query/topk?k=0", "/query/topk?k=5",
    "/query/topk?model=top_talkers&k=10",
    "/query/topk?model=flows_5m&k=3",
    "/query/range", "/query/range?model=flows_5m",
    "/query/audit",
)


def _record_and_archive(tmp_path, hh_sketch="table", keyframe_every=2,
                        **worker_kw):
    """Drive a worker publishing per batch; record every live answer at
    the version it was served while a gateway with an embedded
    ArchiveWriter mirrors the stream into the archive. Returns
    (recorded {(version, path): bytes}, live store, gateway)."""
    worker = StreamWorker(
        Consumer(_fill_bus(), fixedlen=True), _models(hh_sketch),
        [MemorySink()],
        WorkerConfig(snapshot_every=0, poll_max=512, **worker_kw))
    pub = WorkerServePublisher(refresh=0.0).attach(worker)
    serve = ServeServer(pub.store, port=0).start()
    writer = ArchiveWriter(str(tmp_path), keyframe_every=keyframe_every)
    gw = SnapshotGateway([pub.store], poll=60, archive=writer)
    recorded = {}
    paths = None
    try:
        while True:
            more = worker.run_once()
            with worker.lock:
                pub.publish(worker)
            gw.sync_once()
            if paths is None:
                fam = pub.store.current.families["top_talkers"]
                key = ",".join("7" for _ in range(fam.key_lanes))
                paths = PARITY_PATHS + (
                    f"/query/estimate?model=top_talkers&key={key}",)
            version = pub.store.current.version
            for path in paths:
                if (version, path) not in recorded:
                    recorded[(version, path)] = \
                        _fetch(serve.port, path)
            if not more:
                break
    finally:
        serve.stop()
        writer.close()
        _quiesce(worker)
    return recorded, pub.store, gw


def _assert_replay_parity(tmp_path, recorded, store, gw):
    reader = ArchiveReader(str(tmp_path))
    archived = set(reader.versions())
    versions = {v for v, _ in recorded}
    assert len(versions) >= 4, "need a multi-version run"
    assert versions <= archived, "every served version is archived"
    hs = HistoryServer(reader, store=gw.store, port=0).start()
    try:
        replayed = 0
        for (version, path), live in sorted(recorded.items()):
            sep = "&" if "?" in path else "?"
            got = _fetch(hs.port, f"{path}{sep}version={version}")
            assert got == live, (version, path)
            replayed += 1
        assert replayed == len(recorded)
        # ?at= resolves through created stamps to the same bytes
        for version in sorted(versions):
            snap = reader.snapshot(version)
            got = _fetch(hs.port,
                         f"/query/topk?at={snap.created!r}")
            assert got == recorded[(version, "/query/topk")]
    finally:
        hs.stop()
    return reader


class TestRecordAndReplayParity:
    """Acceptance: archive answers == live answers, byte for byte."""

    @pytest.fixture(scope="class", params=["table", "invertible"])
    def run(self, request, tmp_path_factory):
        kw = {}
        if request.param == "invertible":
            kw = dict(sketch_backend="host", host_assist="on")
        tmp = tmp_path_factory.mktemp(f"hist-{request.param}")
        recorded, store, gw = _record_and_archive(
            tmp, hh_sketch=request.param, **kw)
        return tmp, recorded, store, gw

    def test_replay_is_byte_identical(self, run):
        tmp, recorded, store, gw = run
        reader = _assert_replay_parity(tmp, recorded, store, gw)
        # keyframe_every=2 guarantees reconstructions replayed deltas
        # across keyframe boundaries, not just keyframe hits
        assert len(reader.versions()) > 2

    def test_replay_survives_compaction(self, run):
        """Evict the oldest segment(s), then replay the survivors —
        still byte-identical; the evicted versions answer 404 with
        nearest-version hints."""
        tmp, recorded, store, gw = run
        total = sum(os.path.getsize(os.path.join(str(tmp), p))
                    for p in os.listdir(str(tmp))
                    if p.endswith(".fharc"))
        w = ArchiveWriter(str(tmp), retain_bytes=int(total * 0.6))
        w.commit()
        w.close()
        reader = ArchiveReader(str(tmp))
        kept = set(reader.versions())
        versions = {v for v, _ in recorded}
        assert kept < versions, "compaction evicted something"
        hs = HistoryServer(reader, store=gw.store, port=0).start()
        try:
            for (version, path), live in sorted(recorded.items()):
                sep = "&" if "?" in path else "?"
                code, raw = _fetch(hs.port,
                                   f"{path}{sep}version={version}")
                if version in kept:
                    assert (code, raw) == live
                else:
                    assert code == 404
                    assert json.loads(raw)["nearest_after"] == \
                        min(kept)
        finally:
            hs.stop()

    def test_gap_and_index_endpoints(self, run):
        tmp, recorded, store, gw = run
        reader = ArchiveReader(str(tmp))
        hs = HistoryServer(reader, store=gw.store, port=0).start()
        try:
            newest = max(v for v, _ in recorded)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_raw(hs.port, f"/query/topk?version={newest + 50}")
            assert ei.value.code == 404
            body = json.loads(ei.value.read())
            assert body["nearest_before"] == max(reader.versions())
            assert body["nearest_after"] is None
            # at= predating the archive: honest 404 with the first
            # archived version as the way forward
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_raw(hs.port, "/query/topk?at=1.5")
            assert ei.value.code == 404
            assert json.loads(ei.value.read())["nearest_after"] == \
                min(reader.versions())
            idx = _get(hs.port, "/history/index")
            assert idx["versions"] == len(reader.versions())
            assert idx["newest"] == max(reader.versions())
            assert idx["live_version"] == gw.store.current.version
        finally:
            hs.stop()


class TestSpreadReplayParity:
    """The spread-family leg of the acceptance gate."""

    def test_spread_answers_replay_byte_identical(self, tmp_path):
        from flow_pipeline_tpu.models.superspreader import (
            SUPERSPREADER_MODEL, superspreader_config,
            superspreader_model)

        worker = StreamWorker(
            Consumer(_fill_bus(spread_fraction=0.25, seed=7),
                     fixedlen=True),
            {SUPERSPREADER_MODEL: superspreader_model(
                superspreader_config(capacity=128), k=16)},
            [MemorySink()],
            WorkerConfig(snapshot_every=0, poll_max=512))
        pub = WorkerServePublisher(refresh=0.0).attach(worker)
        serve = ServeServer(pub.store, port=0).start()
        writer = ArchiveWriter(str(tmp_path), keyframe_every=2)
        gw = SnapshotGateway([pub.store], poll=60, archive=writer)
        paths = (f"/query/spread?model={SUPERSPREADER_MODEL}&k=5",
                 f"/query/topk?model={SUPERSPREADER_MODEL}&k=8",
                 "/query/spread")
        recorded = {}
        try:
            while True:
                more = worker.run_once()
                with worker.lock:
                    pub.publish(worker)
                gw.sync_once()
                version = pub.store.current.version
                fam = pub.store.current.families[SUPERSPREADER_MODEL]
                key = ",".join(
                    str(int(x)) for x in
                    np.atleast_1d(fam.rows["src_addr"][0]))
                for path in paths + (
                        f"/query/spread?model={SUPERSPREADER_MODEL}"
                        f"&key={key}",):
                    recorded.setdefault((version, path),
                                        _get_raw(serve.port, path))
                if not more:
                    break
        finally:
            serve.stop()
            writer.close()
            _quiesce(worker)
        reader = ArchiveReader(str(tmp_path))
        hs = HistoryServer(reader, store=gw.store, port=0).start()
        try:
            assert set(v for v, _ in recorded) <= set(reader.versions())
            for (version, path), live in sorted(recorded.items()):
                sep = "&" if "?" in path else "?"
                got = _get_raw(hs.port, f"{path}{sep}version={version}")
                assert got == live, (version, path)
        finally:
            hs.stop()


@pytest.mark.slow  # mesh ingest; gated by `make history-parity` / CI
class TestMeshReplayParity:
    """The mesh-publisher leg: the coordinator's merged snapshot
    stream archives and replays byte-identical."""

    def test_mesh_stream_replays_byte_identical(self, tmp_path):
        from flow_pipeline_tpu.mesh import InProcessMesh, produce_sharded
        from flow_pipeline_tpu.serve import attach_mesh

        def mesh_models():
            return {
                "flows_5m": WindowAggregator(
                    WindowAggConfig(batch_size=512)),
                "top_talkers": WindowedHeavyHitter(
                    HeavyHitterConfig(
                        key_cols=("src_addr", "dst_addr", "src_port",
                                  "dst_port", "proto"),
                        batch_size=512, width=1 << 12, capacity=128),
                    k=10),
            }

        bus = InProcessBus()
        bus.create_topic("flows", 4)
        gen = FlowGenerator(ZipfProfile(n_keys=200, alpha=1.3), seed=7,
                            t0=1_700_000_000, rate=40.0)
        done = 0
        while done < 8000:
            done += produce_sharded(bus, "flows", gen.batch(2048), 4)
        mesh = InProcessMesh(
            bus, "flows", 2, model_factory=mesh_models,
            config=WorkerConfig(poll_max=2048, snapshot_every=0),
            sinks=[MemorySink()])
        pub = attach_mesh(mesh.coordinator, refresh=0.2, start=False)
        mesh.start()
        serve = ServeServer(pub.store, port=0).start()
        writer = ArchiveWriter(str(tmp_path), keyframe_every=2)
        gw = SnapshotGateway([pub.store], poll=60, archive=writer)
        paths = ("/query/topk", "/query/topk?model=top_talkers&k=10",
                 "/query/range?model=flows_5m", "/query/audit")
        recorded = {}
        try:
            mesh.wait_idle()
            for _ in range(4):  # several published versions
                snap = pub.publish_now()
                gw.sync_once()
                for path in paths:
                    recorded.setdefault((snap.version, path),
                                        _fetch(serve.port, path))
            assert snap.source == "mesh"
        finally:
            serve.stop()
            writer.close()
            mesh.finalize()
        reader = ArchiveReader(str(tmp_path))
        hs = HistoryServer(reader, store=gw.store, port=0).start()
        try:
            assert set(v for v, _ in recorded) <= set(reader.versions())
            for (version, path), live in sorted(recorded.items()):
                sep = "&" if "?" in path else "?"
                got = _fetch(hs.port, f"{path}{sep}version={version}")
                assert got == live, (version, path)
                if got[0] == 200:
                    assert json.loads(got[1])["version"] == version
        finally:
            hs.stop()


# ---- gateway range retention (satellite) -----------------------------------


class TestGatewayRangeRetention:
    """A gateway with -history.dir answers /query/range for slots older
    than the live window, bit-exact vs the rows the live path served
    when those slots were current."""

    def test_archived_slots_serve_the_recorded_rows(self, tmp_path):
        worker = StreamWorker(
            Consumer(_fill_bus(batches=10, per=400, rate=2.0),
                     fixedlen=True),
            _models(), [MemorySink()],
            WorkerConfig(snapshot_every=0, poll_max=512))
        # keep only the 2 newest closed slots live: older slots exist
        # ONLY in the archive
        pub = WorkerServePublisher(refresh=0.0, range_slots=2) \
            .attach(worker)
        serve = ServeServer(pub.store, port=0).start()
        writer = ArchiveWriter(str(tmp_path), keyframe_every=4)
        gw = SnapshotGateway([pub.store], poll=60, archive=writer)
        recorded = {}  # slot -> the rows the live path served
        try:
            while True:
                more = worker.run_once()
                with worker.lock:
                    pub.publish(worker)
                gw.sync_once()
                snap = pub.store.current
                for slot, _ in snap.ranges.get("flows_5m", ()):
                    if slot not in recorded:
                        body = _get(serve.port,
                                    f"/query/range?model=flows_5m"
                                    f"&from={slot}&to={slot + 300}")
                        recorded[slot] = body["rows"]
                if not more:
                    break
        finally:
            serve.stop()
            writer.close()
            _quiesce(worker)
        live_slots = [s for s, _ in
                      pub.store.current.ranges.get("flows_5m", ())]
        old_slots = sorted(set(recorded) - set(live_slots))
        assert old_slots, "need slots that left the live window"
        reader = ArchiveReader(str(tmp_path))
        hs = HistoryServer(reader, store=gw.store, port=0).start()
        try:
            for slot in old_slots:
                body = _get(hs.port, f"/query/range?model=flows_5m"
                                     f"&from={slot}&to={slot + 300}")
                assert body["slots"] == [slot]
                assert body["archived_slots"] == [slot]
                assert body["rows"] == recorded[slot], slot
            # the unbounded range answers every slot ever closed, in
            # ascending order: archive + live seamlessly
            body = _get(hs.port, "/query/range?model=flows_5m")
            assert body["slots"] == sorted(recorded)
            assert body["archived_slots"] == old_slots
            flat = [r for s in sorted(recorded) for r in recorded[s]]
            assert body["rows"] == flat
        finally:
            hs.stop()


# ---- -serve.feed_bytes (satellite) -----------------------------------------


class TestFeedBytesFlag:
    def test_flag_registered_and_parsed(self):
        from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet

        assert "serve.feed_bytes" in KNOWN_FLAGS
        fs = FlagSet("t")
        fs.integer("serve.feed_bytes", 0, "h")
        assert fs.parse(["-serve.feed_bytes", "1048576"]) == \
            {"serve.feed_bytes": 1 << 20}

    def test_server_threads_the_budget_into_the_feed(self):
        store = SnapshotStore()
        store.publish_snapshot(state_to_snapshot(_mk_state(1)))
        serve = ServeServer(store, port=0, feed_bytes=12345).start()
        try:
            _get_raw(serve.port, "/sub/snapshot?since=0")
            assert serve._feed.history_bytes == 12345
        finally:
            serve.stop()
        # 0 keeps the library default
        from flow_pipeline_tpu.gateway.feed import FEED_HISTORY_BYTES

        serve = ServeServer(store, port=0).start()
        try:
            _get_raw(serve.port, "/sub/snapshot?since=0")
            assert serve._feed.history_bytes == FEED_HISTORY_BYTES
        finally:
            serve.stop()

    def test_bound_is_enforced_at_the_configured_value(self):
        """The budget actually bites: the retained delta bytes never
        exceed it, and a subscriber older than the trimmed chain takes
        a full resync."""
        states = [_mk_state(i + 1, bump=i) for i in range(7)]
        store = SnapshotStore()
        store.publish_snapshot(state_to_snapshot(states[0]))
        # a budget that holds roughly ONE delta frame
        budget = int(len(encode_delta(snapshot_state(
            state_to_snapshot(states[0])), states[1])) * 1.5)
        feed = SnapshotFeed(store, history_bytes=budget)
        feed.frame_since(0)
        for s in states[1:]:
            store.publish_snapshot(state_to_snapshot(s))
            feed.frame_since(s["version"] - 1)
            assert feed._delta_bytes_held <= budget
        # v1 fell off the trimmed chain: full resync, not a gap
        kind, cur, _ = feed.frame_since(1)
        assert (kind, cur) == ("full", 7)
        # the newest transition still ships as a delta
        assert feed.frame_since(6)[0] == "delta"


# ---- flags / cli wiring ----------------------------------------------------


def test_history_flags_registered_and_parsed():
    from flow_pipeline_tpu.utils.flags import KNOWN_FLAGS, FlagSet

    assert {"history.dir", "history.keyframe", "history.retain",
            "history.upstream", "history.listen",
            "history.poll"} <= KNOWN_FLAGS
    fs = FlagSet("t")
    fs.string("history.dir", "", "h")
    fs.integer("history.keyframe", 64, "h")
    fs.integer("history.retain", 1 << 30, "h")
    vals = fs.parse(["-history.dir", "/tmp/a",
                     "-history.keyframe", "8",
                     "-history.retain", "1000000"])
    assert vals == {"history.dir": "/tmp/a", "history.keyframe": 8,
                    "history.retain": 1000000}


def test_history_subcommand_wired():
    from flow_pipeline_tpu import cli

    assert cli._COMMANDS["history"] is cli.history_main
    assert callable(cli.history_entry)
    # refuses to start without an upstream (exit code 2, no traceback)
    assert cli.history_main(["-history.dir", "/tmp/x"]) == 2


def test_history_tier_end_to_end_over_http(tmp_path):
    """The flowhistory tier the cli wires: subscribe over real HTTP,
    archive, serve the live head AND the past."""
    states = [_mk_state(i + 1, bump=i) for i in range(5)]
    store = SnapshotStore()
    store.publish_snapshot(state_to_snapshot(states[0]))
    upstream = ServeServer(store, port=0).start()
    hs = HistoryServer(ArchiveReader(str(tmp_path)), port=0).start()
    writer = ArchiveWriter(str(tmp_path), keyframe_every=2,
                           upstream=f"127.0.0.1:{upstream.port}",
                           store=hs.store)
    try:
        assert writer.sync_once() == "full"
        for s in states[1:]:
            store.publish_snapshot(state_to_snapshot(s))
            assert writer.sync_once() == "delta"
        assert writer.sync_once() == "none"
        # live head mirrored like a gateway replica
        assert hs.store.current.version == 5
        assert _get(hs.port, "/query/version")["version"] == 5
        # the past reconstructs through the same HTTP surface
        body = _get(hs.port, "/query/topk?model=hh&version=2")
        assert body["version"] == 2
        # v2 was built with bump=1: src_addr = 1..4, last row invalid
        assert [r["src_addr"] for r in body["rows"]] == [1, 2, 3]
        assert ArchiveReader(str(tmp_path)).versions() == \
            [1, 2, 3, 4, 5]
    finally:
        writer.stop()
        hs.stop()
        upstream.stop()
