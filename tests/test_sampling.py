"""Sampling-rate-correct serving (VERDICT r3 #2).

The reference's traffic panels multiply by the exporter sampling rate at
query time over raw rows — sum(bytes*sampling_rate*8) on Postgres
(ref: compose/grafana/dashboards/viz.json:62) and sum(Bytes*SamplingRate)
on ClickHouse (ref: viz-ch.json) — so a framework that serves from
pre-aggregated tables must bake the rate in at ingest or the
information is unrecoverable. These tests gate that path end to end:

- flows_5m carries exact uint64 ``bytes_scaled``/``packets_scaled``
  columns (rate rides as a grouping lane; raw sums stay bit-identical
  to the unscaled rollup) on the single-chip, fused, host-grouped AND
  mesh-sharded paths;
- sketch/dense models rank and report rate-scaled values (a 1:1000
  exporter's flows count 1000x), dense ports exactly, sketches within
  the usual gates;
- rate 0 ("unknown", what GoFlow emits before an options template
  arrives) scales by 1, never 0.
"""

from __future__ import annotations

import numpy as np
import pytest

from flow_pipeline_tpu.engine import WindowedHeavyHitter
from flow_pipeline_tpu.engine.fused import FusedPipeline
from flow_pipeline_tpu.engine.hostfused import HostGroupPipeline
from flow_pipeline_tpu.gen import FlowGenerator, ZipfProfile
from flow_pipeline_tpu.models import (
    DenseTopConfig,
    DenseTopKModel,
    HeavyHitterConfig,
    WindowAggConfig,
    WindowAggregator,
)
from flow_pipeline_tpu.models.oracle import exact_groupby
from flow_pipeline_tpu.schema.batch import FlowBatch

RATES = (0, 1, 100, 1000)


def rated_batches(n_batches=4, n=3000, seed=11):
    rng = np.random.default_rng(3)
    gen = FlowGenerator(ZipfProfile(n_keys=2000, alpha=1.2), seed=seed)
    batches = []
    for _ in range(n_batches):
        b = gen.batch(n)
        b.columns["sampling_rate"] = rng.choice(
            RATES, size=n).astype(np.uint64)
        batches.append(b)
    merged = FlowBatch({
        k: np.concatenate([b.columns[k] for b in batches])
        for k in batches[0].columns
    })
    return batches, merged


def rows_by_key(rows, key_names, val_names):
    n = len(rows[key_names[0]])
    return {
        tuple(int(rows[k][i]) for k in key_names):
        tuple(int(rows[v][i]) for v in val_names)
        for i in range(n)
    }


KEYS = ["timeslot", "src_as", "dst_as", "etype"]
VALS = ["bytes", "packets", "count", "bytes_scaled", "packets_scaled"]


class TestFlows5mScaled:
    def test_single_chip_exact_vs_oracle(self):
        batches, merged = rated_batches()
        agg = WindowAggregator(WindowAggConfig(batch_size=1024))
        for b in batches:
            agg.update(b)
        got = rows_by_key(agg.flush(force=True), KEYS, VALS)
        want = rows_by_key(
            exact_groupby(merged, ["src_as", "dst_as", "etype"],
                          scale_col="sampling_rate"), KEYS, VALS)
        assert got == want

    def test_raw_sums_unchanged_by_scaling(self):
        """The rate-as-group-lane design must not perturb raw flows_5m
        parity: bytes/packets/count match a scale_col=None aggregator."""
        batches, _ = rated_batches()
        on = WindowAggregator(WindowAggConfig(batch_size=1024))
        off = WindowAggregator(WindowAggConfig(batch_size=1024,
                                               scale_col=None))
        for b in batches:
            on.update(b)
            off.update(b)
        raw = ["bytes", "packets", "count"]
        assert rows_by_key(on.flush(True), KEYS, raw) == \
            rows_by_key(off.flush(True), KEYS, raw)

    def test_rate_zero_counts_as_one(self):
        b = FlowBatch.empty(4)
        b.columns["time_received"][:] = 6000
        b.columns["src_as"][:] = 65000
        b.columns["bytes"][:] = 10
        b.columns["packets"][:] = 2
        b.columns["sampling_rate"][:] = [0, 1, 0, 1]
        agg = WindowAggregator(WindowAggConfig(batch_size=4))
        agg.update(b)
        rows = agg.flush(force=True)
        assert rows["bytes"].tolist() == [40]
        assert rows["bytes_scaled"].tolist() == [40]  # 0 -> x1, not x0

    @pytest.mark.parametrize("pipeline_cls",
                             [FusedPipeline, HostGroupPipeline])
    def test_pipelines_match_oracle(self, pipeline_cls):
        batches, merged = rated_batches()
        models = {"flows_5m": WindowAggregator(
            WindowAggConfig(batch_size=1024))}
        pipe = pipeline_cls(models)
        for b in batches:
            pipe.update(b)
        got = rows_by_key(models["flows_5m"].flush(force=True), KEYS, VALS)
        want = rows_by_key(
            exact_groupby(merged, ["src_as", "dst_as", "etype"],
                          scale_col="sampling_rate"), KEYS, VALS)
        assert got == want

    def test_sharded_matches_oracle(self):
        from flow_pipeline_tpu.parallel import make_mesh
        from flow_pipeline_tpu.parallel.sharded import (
            ShardedWindowAggregator,
        )

        batches, merged = rated_batches()
        agg = ShardedWindowAggregator(
            WindowAggConfig(batch_size=512), make_mesh(4))
        for b in batches:
            agg.update(b)
        got = rows_by_key(agg.flush(force=True), KEYS, VALS)
        want = rows_by_key(
            exact_groupby(merged, ["src_as", "dst_as", "etype"],
                          scale_col="sampling_rate"), KEYS, VALS)
        assert got == want


class TestSketchScaled:
    def test_dense_ports_exact(self):
        batches, merged = rated_batches()
        dm = DenseTopKModel(DenseTopConfig(key_col="src_port",
                                           batch_size=1024))
        for b in batches:
            dm.update(b)
        top = dm.top(1 << 16)
        want = exact_groupby(merged, ["src_port"], timeslot=False,
                             scale_col="sampling_rate")
        wm = {int(k): int(v) for k, v in
              zip(want["src_port"], want["bytes_scaled"])}
        gm = {int(p): int(v) for p, v, ok in
              zip(top["src_port"], top["bytes"], top["valid"]) if ok}
        assert gm == wm

    def test_hh_ranks_scaled_traffic(self):
        """One src sends few SAMPLED flows at rate 1000; another sends
        more flows at rate 1. Scaled ranking must put the sampled
        exporter first (raw ranking would invert it)."""
        n = 1024
        b = FlowBatch.empty(n)
        b.columns["time_received"][:] = 6000
        b.columns["bytes"][:] = 100
        src = np.zeros((n, 4), np.uint32)
        src[:, 3] = 2  # busy-looking unsampled source
        src[: n // 8, 3] = 1  # 1/8 of rows: the 1:1000-sampled source
        b.columns["src_addr"] = src
        rate = np.ones(n, np.uint64)
        rate[: n // 8] = 1000
        b.columns["sampling_rate"] = rate
        m = WindowedHeavyHitter(
            HeavyHitterConfig(key_cols=("src_addr",), batch_size=n,
                              width=1 << 10, capacity=128), k=2)
        m.update(b)
        rows = m.flush(force=True)[0]
        assert int(rows["src_addr"][0][3]) == 1  # sampled source ranks 1st
        assert int(rows["bytes"][0]) == (n // 8) * 100 * 1000
        assert int(rows["bytes"][1]) == (n - n // 8) * 100

    def test_scale_col_none_restores_raw(self):
        batches, merged = rated_batches()
        m = DenseTopKModel(DenseTopConfig(key_col="src_port",
                                          batch_size=1024,
                                          scale_col=None))
        for b in batches:
            m.update(b)
        top = m.top(1 << 16)
        want = exact_groupby(merged, ["src_port"], timeslot=False)
        wm = {int(k): int(v) for k, v in
              zip(want["src_port"], want["bytes"])}
        gm = {int(p): int(v) for p, v, ok in
              zip(top["src_port"], top["bytes"], top["valid"]) if ok}
        assert gm == wm
