"""Service-integration tests against REAL Kafka / Postgres / ClickHouse.

These prove the wire paths the in-process doubles stand in for elsewhere:
the Kafka adapters' at-least-once commit semantics against a real broker
(the role of the reference's compose topologies,
ref: compose/docker-compose-postgres-mock.yml), and real sink writes.

They run in CI's services job (.github/workflows/ci.yml), where the three
backends are Actions service containers addressed via env vars:

    FLOWTPU_KAFKA=localhost:9092
    FLOWTPU_POSTGRES="host=localhost user=flows password=flows dbname=flows"
    FLOWTPU_CLICKHOUSE=http://localhost:8123

Locally they skip unless those env vars are exported.
"""

import json
import os
import time
import urllib.parse
import urllib.request
import uuid

import numpy as np
import pytest

from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile
from flow_pipeline_tpu.models import WindowAggConfig, WindowAggregator
from flow_pipeline_tpu.models.oracle import flows_5m
from flow_pipeline_tpu.schema.batch import FlowBatch

KAFKA = os.environ.get("FLOWTPU_KAFKA")
PG = os.environ.get("FLOWTPU_POSTGRES")
CH = os.environ.get("FLOWTPU_CLICKHOUSE")

needs_kafka = pytest.mark.skipif(not KAFKA, reason="FLOWTPU_KAFKA not set")
needs_pg = pytest.mark.skipif(not PG, reason="FLOWTPU_POSTGRES not set")
needs_ch = pytest.mark.skipif(not CH, reason="FLOWTPU_CLICKHOUSE not set")
# path to the BUILT go feed client binary (deploy/go-feed-client); CI's
# services job builds it with setup-go — there is no Go toolchain in the
# dev image, so the Go side of the seam is proven in CI
GO_FEED = os.environ.get("FLOWTPU_GO_FEED")
needs_go = pytest.mark.skipif(not GO_FEED, reason="FLOWTPU_GO_FEED not set")


def gen_batch(n, seed=7):
    return FlowGenerator(MockerProfile(), seed=seed, t0=1_700_000_000,
                         rate=50.0).batch(n)


def drain(consumer, want_msgs, timeout_s=60):
    """Poll until `want_msgs` flows arrive (or fail). Returns batches."""
    batches, got = [], 0
    deadline = time.time() + timeout_s
    while got < want_msgs:
        assert time.time() < deadline, f"only {got}/{want_msgs} arrived"
        b = consumer.poll(8192)
        if b is None or len(b) == 0:
            time.sleep(0.2)
            continue
        batches.append(b)
        got += len(b)
    return batches


@needs_kafka
class TestKafkaAdapters:
    def make(self, topic, group="g1", fixedlen=True):
        from flow_pipeline_tpu.transport.kafka import (
            KafkaConsumerAdapter,
            KafkaProducerAdapter,
        )

        prod = KafkaProducerAdapter(KAFKA, topic, fixedlen=fixedlen)
        cons = KafkaConsumerAdapter(KAFKA, topic, group=group,
                                    fixedlen=fixedlen)
        return prod, cons

    def test_produce_consume_roundtrip(self):
        topic = f"flows-it-{uuid.uuid4().hex[:8]}"
        prod, cons = self.make(topic)
        batch = gen_batch(500)
        for m in batch.to_messages():
            prod.send(m)
        prod.flush()
        got = FlowBatch.concat(drain(cons, 500))
        assert len(got) == 500
        # content fidelity through the broker (order may interleave
        # across partitions; compare as multisets of sequence numbers)
        assert (np.sort(got.columns["sequence_num"])
                == np.sort(batch.columns["sequence_num"])).all()
        assert got.columns["bytes"].sum() == batch.columns["bytes"].sum()

    def test_commit_then_resume_skips_only_committed(self):
        # THE at-least-once contract: a restarted consumer re-reads
        # everything after the last commit — no more, no less
        topic = f"flows-it-{uuid.uuid4().hex[:8]}"
        group = f"g-{uuid.uuid4().hex[:8]}"
        prod, cons = self.make(topic, group=group)
        batch = gen_batch(600)
        for m in batch.to_messages():
            prod.send(m)
        prod.flush()
        batches = drain(cons, 600)
        first = batches[0]
        cons.commit(first.partition, first.last_offset + 1)
        committed_seqs = set(first.columns["sequence_num"].tolist())
        cons._consumer.close()

        from flow_pipeline_tpu.transport.kafka import KafkaConsumerAdapter

        cons2 = KafkaConsumerAdapter(KAFKA, topic, group=group,
                                     fixedlen=True)
        want = 600 - len(first)
        replayed = FlowBatch.concat(drain(cons2, want))
        replayed_seqs = set(replayed.columns["sequence_num"].tolist())
        all_seqs = set(batch.columns["sequence_num"].tolist())
        assert replayed_seqs == all_seqs - committed_seqs
        cons2._consumer.close()

    def test_worker_over_real_broker_exact_parity(self):
        # bus -> worker -> exact aggregation over a real broker must match
        # the oracle, and commit only after processing
        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.sink import MemorySink
        from flow_pipeline_tpu.transport.kafka import (
            KafkaConsumerAdapter,
            KafkaProducerAdapter,
        )

        topic = f"flows-it-{uuid.uuid4().hex[:8]}"
        prod = KafkaProducerAdapter(KAFKA, topic, fixedlen=True)
        batch = gen_batch(2000)
        for m in batch.to_messages():
            prod.send(m)
        prod.flush()

        cons = KafkaConsumerAdapter(KAFKA, topic, group="worker-it",
                                    fixedlen=True)
        sink = MemorySink()
        worker = StreamWorker(
            cons,
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=1024))},
            [sink],
            WorkerConfig(poll_max=1024, snapshot_every=1),
        )
        deadline = time.time() + 60
        while worker.flows_seen < 2000:
            assert time.time() < deadline, worker.flows_seen
            if not worker.run_once():
                time.sleep(0.2)
        worker.finalize()
        oracle = flows_5m(batch)
        rows = sink.tables["flows_5m"]
        agg = {}
        for r in rows:
            key = (r["timeslot"], r["src_as"], r["dst_as"], r["etype"])
            agg[key] = agg.get(key, 0) + r["count"]
        assert sum(agg.values()) == 2000
        assert len(agg) == len(oracle["timeslot"])
        cons._consumer.close()


@needs_pg
class TestPostgresSink:
    def test_real_writes_roundtrip(self):
        from flow_pipeline_tpu.sink.postgres import PostgresSink, available

        if not available():
            pytest.skip("psycopg2 not installed")
        sink = PostgresSink(PG)
        rows = {
            "timeslot": np.array([300, 300, 600], np.uint64),
            "src_as": np.array([65000, 65001, 65000], np.uint64),
            "dst_as": np.array([65001, 65000, 65002], np.uint64),
            "etype": np.array([0x86DD] * 3, np.uint32),
            "bytes": np.array([100, 200, 300], np.uint64),
            "packets": np.array([1, 2, 3], np.uint64),
            "count": np.array([1, 1, 1], np.uint64),
        }
        sink.write("flows_5m", rows)
        with sink._conn, sink._conn.cursor() as cur:
            cur.execute("SELECT sum(bytes), sum(count) FROM flows_5m "
                        "WHERE timeslot IN (300, 600)")
            total_bytes, total_count = cur.fetchone()
        assert total_bytes >= 600 and total_count >= 3
        sink.close()

    def test_ranked_port_table(self):
        from flow_pipeline_tpu.sink.postgres import PostgresSink, available

        if not available():
            pytest.skip("psycopg2 not installed")
        sink = PostgresSink(PG)
        slot = int(time.time())  # unique-ish timeslot per run
        rows = {
            "timeslot": np.full(3, slot, np.uint64),
            "src_port": np.array([443, 53, 80], np.uint32),
            "bytes": np.array([900, 500, 100], np.uint64),
            "packets": np.array([9, 5, 1], np.uint64),
            "count": np.array([3, 2, 1], np.uint64),
        }
        sink.write("top_src_ports", rows)
        with sink._conn, sink._conn.cursor() as cur:
            cur.execute("SELECT rank, src_port FROM top_src_ports "
                        "WHERE timeslot = %s ORDER BY rank", (slot,))
            got = cur.fetchall()
        assert got == [(0, 443), (1, 53), (2, 80)]
        sink.close()


@needs_ch
class TestClickHouseSink:
    def query(self, sql, database="default"):
        req = urllib.request.Request(
            f"{CH}/?database={database}&query=" + urllib.parse.quote(sql),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.read().decode().strip()

    def test_flows_5m_and_summing_merge(self):
        from flow_pipeline_tpu.sink.clickhouse import ClickHouseSink

        sink = ClickHouseSink(CH)
        assert sink.ping()
        slot = int(time.time()) // 300 * 300
        rows = {
            "timeslot": np.array([slot, slot], np.uint64),
            "src_as": np.array([65000, 65000], np.uint64),
            "dst_as": np.array([65001, 65001], np.uint64),
            "etype": np.array([0x86DD] * 2, np.uint32),
            "bytes": np.array([100, 250], np.uint64),
            "packets": np.array([1, 2], np.uint64),
            "count": np.array([1, 1], np.uint64),
        }
        sink.write("flows_5m", rows)  # two partial rows, same key
        total = self.query(
            "SELECT sum(Bytes), sum(Count) FROM flows_5m "
            f"WHERE Timeslot = toDateTime({slot}) AND SrcAS = 65000"
        )
        b, c = (int(x) for x in total.split("\t"))
        assert b >= 350 and c >= 2  # merge-time summation semantics

    def test_archive_raw_roundtrip_and_ipv6_fidelity(self):
        from flow_pipeline_tpu.sink.clickhouse import ClickHouseSink

        sink = ClickHouseSink(CH)
        sink.check_raw_schema()  # fresh table must pass
        batch = gen_batch(300, seed=11)
        assert sink.archive_raw(batch) == 300
        n = int(self.query("SELECT count() FROM flows_raw"))
        assert n >= 300
        # address bytes round-trip through the IPv6 domain + Date derives
        one = self.query(
            "SELECT IPv6NumToString(DstAddr), Date, TimeReceived "
            "FROM flows_raw ORDER BY TimeReceived LIMIT 1 FORMAT TSV"
        ).split("\t")
        import datetime
        import ipaddress

        assert ipaddress.ip_address(one[0]).version == 6
        day = datetime.datetime.fromtimestamp(
            int(one[2]), datetime.timezone.utc).strftime("%Y-%m-%d")
        assert one[1] == day

    def test_stale_fixedstring_schema_fails_fast(self):
        from flow_pipeline_tpu.sink.clickhouse import ClickHouseSink

        db = f"it_{uuid.uuid4().hex[:8]}"
        self.query(f"CREATE DATABASE {db}")
        try:
            self.query(
                "CREATE TABLE flows_raw (TimeReceived UInt64, "
                "SrcAddr FixedString(16), DstAddr FixedString(16)) "
                "ENGINE = MergeTree() ORDER BY TimeReceived",
                database=db,
            )
            sink = ClickHouseSink(CH, database=db, create_tables=False)
            with pytest.raises(RuntimeError, match="IPv6"):
                sink.check_raw_schema()
        finally:
            self.query(f"DROP DATABASE {db}")

    def test_worker_end_to_end_against_clickhouse(self):
        # bus (in-process) -> worker -> REAL ClickHouse, raw archive on
        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.sink.clickhouse import ClickHouseSink
        from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer

        bus = InProcessBus()
        bus.create_topic("flows", 2)
        batch = gen_batch(1500, seed=13)
        Producer(bus, fixedlen=True).send_many(batch.to_messages())
        sink = ClickHouseSink(CH)
        before = int(self.query("SELECT count() FROM flows_raw"))
        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=1024))},
            [sink],
            WorkerConfig(poll_max=1024, archive_raw=True),
        )
        worker.run(stop_when_idle=True)
        after = int(self.query("SELECT count() FROM flows_raw"))
        assert after - before == 1500


@needs_go
class TestGoFeedClient:
    """The Go side of the processor seam (ref: README.md:44-47 reserves
    the processor slot): the built deploy/go-feed-client binary publishes
    hand-encoded FlowMessage frames over the raw-codec gRPC contract, and
    the normal FeedServer -> bus -> worker -> sink loop must account for
    every flow with the mocker-shaped values intact."""

    def test_go_publish_through_worker_to_sink(self, tmp_path):
        import sqlite3
        import subprocess

        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.models import HeavyHitterConfig
        from flow_pipeline_tpu.engine.windowed import WindowedHeavyHitter
        from flow_pipeline_tpu.sink import SQLiteSink
        from flow_pipeline_tpu.transport import Consumer, InProcessBus
        from flow_pipeline_tpu.transport.feed import FeedServer, available

        if not available():
            pytest.skip("grpcio not importable")
        bus = InProcessBus()
        server = FeedServer(bus, address="127.0.0.1:0").start()
        try:
            n = 20000
            out = subprocess.run(
                [GO_FEED, "-addr", f"127.0.0.1:{server.port}",
                 "-count", str(n), "-batch", "4096"],
                capture_output=True, text=True, timeout=120,
            )
            assert out.returncode == 0, out.stderr
            assert f"accepted={n}" in out.stdout
        finally:
            server.stop()

        db = str(tmp_path / "go.db")
        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=4096)),
             "top_talkers": WindowedHeavyHitter(HeavyHitterConfig(
                 key_cols=("src_addr", "dst_addr", "src_port", "dst_port",
                           "proto"), batch_size=4096, width=1 << 12,
                 capacity=256), k=50)},
            [SQLiteSink(db)],
            WorkerConfig(poll_max=4096, snapshot_every=0),
        )
        worker.run(stop_when_idle=True)
        assert worker.flows_seen == n

        con = sqlite3.connect(db)
        total = con.execute("SELECT SUM(count) FROM flows_5m").fetchone()[0]
        assert total == n  # every Go-published flow accounted exactly once
        ases = {r[0] for r in con.execute(
            "SELECT DISTINCT src_as FROM flows_5m")}
        assert ases == {65000, 65001}  # mocker-parity values survived
        etypes = {r[0] for r in con.execute(
            "SELECT DISTINCT etype FROM flows_5m")}
        assert etypes == {0x86DD}
        talkers = con.execute(
            "SELECT COUNT(*) FROM top_talkers").fetchone()[0]
        assert talkers > 0
