"""Transport tests: bus partition/offset semantics, producer framing,
consumer decode + commit-after-flush resume."""

import pytest

from flow_pipeline_tpu.schema import FlowMessage, decode_frames, decode_message
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer


def msg(i):
    return FlowMessage(bytes=i + 1, packets=1, src_as=65000 + i % 3)


class TestBus:
    def test_round_robin_partitions(self):
        bus = InProcessBus()
        bus.create_topic("flows", 2)
        for i in range(10):
            bus.produce("flows", bytes([i]))
        assert bus.end_offset("flows", 0) == 5
        assert bus.end_offset("flows", 1) == 5

    def test_fetch_by_offset(self):
        bus = InProcessBus()
        bus.create_topic("t", 1)
        for i in range(20):
            bus.produce("t", bytes([i]), partition=0)
        msgs = bus.fetch("t", 0, 5, max_messages=3)
        assert [m.offset for m in msgs] == [5, 6, 7]
        assert msgs[0].value == bytes([5])

    def test_commits_never_regress(self):
        bus = InProcessBus()
        bus.create_topic("t", 1)
        bus.commit("g", "t", 0, 10)
        bus.commit("g", "t", 0, 5)
        assert bus.committed("g", "t", 0) == 10

    def test_lag(self):
        bus = InProcessBus()
        bus.create_topic("t", 2)
        for i in range(6):
            bus.produce("t", b"x")
        assert bus.lag("g", "t") == 6
        bus.commit("g", "t", 0, 3)
        assert bus.lag("g", "t") == 3


class TestProducerConsumer:
    def test_roundtrip_unframed(self):
        bus = InProcessBus()
        bus.create_topic("flows", 2)
        prod = Producer(bus, fixedlen=False)
        prod.send_many([msg(i) for i in range(10)])
        cons = Consumer(bus, fixedlen=False)
        seen = 0
        while (batch := cons.poll()) is not None:
            seen += len(batch)
            assert batch.first_offset == 0
        assert seen == 10

    def test_roundtrip_framed(self):
        bus = InProcessBus()
        bus.create_topic("flows", 1)
        Producer(bus, fixedlen=True).send_many([msg(i) for i in range(5)])
        batch = Consumer(bus, fixedlen=True).poll()
        assert len(batch) == 5
        assert batch.columns["bytes"].tolist() == [1, 2, 3, 4, 5]

    def test_batch_carries_offsets(self):
        bus = InProcessBus()
        bus.create_topic("flows", 1)
        Producer(bus, fixedlen=True).send_many([msg(i) for i in range(7)])
        cons = Consumer(bus, fixedlen=True)
        batch = cons.poll(max_messages=4)
        assert (batch.partition, batch.first_offset, batch.last_offset) == (0, 0, 3)
        batch = cons.poll(max_messages=4)
        assert (batch.first_offset, batch.last_offset) == (4, 6)

    def test_resume_from_commit_not_position(self):
        # consumer restart resumes from the COMMITTED offset: uncommitted
        # polls are re-delivered (at-least-once)
        bus = InProcessBus()
        bus.create_topic("flows", 1)
        Producer(bus, fixedlen=True).send_many([msg(i) for i in range(10)])
        c1 = Consumer(bus, fixedlen=True, group="g")
        b1 = c1.poll(max_messages=6)
        c1.commit(0, 4)  # only 4 durably processed
        del c1
        c2 = Consumer(bus, fixedlen=True, group="g")
        b2 = c2.poll(max_messages=10)
        assert b2.first_offset == 4  # offsets 4..5 re-delivered

    def test_multi_partition_rotation(self):
        bus = InProcessBus()
        bus.create_topic("flows", 2)
        prod = Producer(bus, fixedlen=True)
        prod.send_many([msg(i) for i in range(8)])
        cons = Consumer(bus, fixedlen=True)
        parts = set()
        while (b := cons.poll(max_messages=2)) is not None:
            parts.add(b.partition)
        assert parts == {0, 1}

    def test_poll_empty_returns_none(self):
        bus = InProcessBus()
        bus.create_topic("flows", 2)
        assert Consumer(bus).poll() is None
