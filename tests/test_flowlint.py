"""flowlint rule tests: each rule against known-good / known-bad fixture
snippets, plus the regression gate that the repo itself lints clean
(what `make lint` / CI enforce)."""

# flowlint: skip-file
# (the fixture strings below deliberately contain findings)

import os
import sys
import textwrap

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

from tools.flowlint.runner import run_lint  # noqa: E402


def _lint(tmp_path, source: str, name: str = "fix.py", rules=None):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_lint(str(tmp_path), [name], rules)


def _rules(findings):
    return [f.rule for f in findings]


class TestJitPurity:
    def test_direct_impurity_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            import time, jax

            @jax.jit
            def step(x):
                print("tracing")
                return x + time.time()
        """)
        msgs = [f.message for f in out]
        assert any("print" in m for m in msgs)
        assert any("time.time" in m for m in msgs)

    def test_transitive_reachability(self, tmp_path):
        out = _lint(tmp_path, """
            import jax

            def helper(y):
                import random
                return random.random() + y

            @jax.jit
            def step(y):
                return helper(y)
        """)
        assert any("random.random" in f.message for f in out)

    def test_partial_decorator_and_shard_map_forms(self, tmp_path):
        out = _lint(tmp_path, """
            import jax
            from functools import partial
            from jax.experimental.shard_map import shard_map

            @partial(jax.jit, static_argnames=("k",))
            def step(x, *, k):
                open("/tmp/x")
                return x

            def per_chip(x):
                import time
                return x + time.time()

            fn = jax.jit(shard_map(per_chip, mesh=None, in_specs=None,
                                   out_specs=None))
        """)
        msgs = " ".join(f.message for f in out)
        assert "open" in msgs and "time.time" in msgs

    def test_metric_mutation_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            import jax
            from flow_pipeline_tpu.obs import REGISTRY

            m = REGISTRY.counter("c", "help")

            @jax.jit
            def step(x):
                m.inc()
                return x
        """)
        assert any(".inc" in f.message for f in out)

    def test_global_write_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            import jax
            _CACHE = None

            @jax.jit
            def step(x):
                global _CACHE
                _CACHE = x
                return x
        """)
        assert any("module-global write" in f.message for f in out)

    def test_pure_jit_and_host_side_effects_clean(self, tmp_path):
        out = _lint(tmp_path, """
            import jax
            import jax.numpy as jnp
            from flow_pipeline_tpu.obs import REGISTRY

            m = REGISTRY.counter("c", "help")

            @jax.jit
            def step(x):
                return jnp.sum(x) * 2

            def host_loop(x):
                m.inc()          # fine: NOT reachable from a jit body
                print("host")
                return step(x)
        """)
        assert _rules(out) == []


class TestUint64Discipline:
    def test_unmarked_module_not_checked(self, tmp_path):
        out = _lint(tmp_path, """
            import numpy as np
            def f(x):
                return x.astype(np.int64) + np.array([1])
        """)
        assert _rules(out) == []

    def test_marked_module_flags_casts_and_dtypeless(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np
            import jax.numpy as jnp

            def f(x):
                a = x.astype(np.int64)
                b = jnp.asarray(x).astype(jnp.int32)
                c = np.array([1, 2])
                d = np.zeros(4)
                e = np.int64(7) + x
                ok = np.asarray(x)            # dtype-preserving: allowed
                ok2 = np.zeros(4, np.uint64)  # explicit dtype: allowed
                return a, b, c, d, e, ok, ok2
        """)
        assert _rules(out) == ["uint64-discipline"] * 5

    def test_suppression_with_reason(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(x):
                # flowlint: disable=uint64-discipline -- indices < 2^31, not counters
                return x.astype(np.int32)
        """)
        assert _rules(out) == []

    def test_suppression_without_reason_is_a_finding(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(x):
                return x.astype(np.int32)  # flowlint: disable=uint64-discipline
        """)
        assert "suppression" in _rules(out)

    def test_trailing_suppression_does_not_mask_next_line(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(x):
                a = x.astype(np.int32)  # flowlint: disable=uint64-discipline -- bounded
                b = x.astype(np.int64)
                return a, b
        """)
        assert _rules(out) == ["uint64-discipline"]  # only line b


class TestLockDiscipline:
    def test_guarded_write_enforced(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def good(self):
                    with self._lock:
                        self._n += 1

                def bad(self):
                    self._n += 1
        """)
        assert _rules(out) == ["lock-discipline"]
        assert "outside" in out[0].message

    def test_guarded_write_in_match_case_enforced(self, tmp_path):
        # `match` case bodies are walked like `if` branches
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def bad(self, mode):
                    match mode:
                        case "bump":
                            self._n += 1
        """)
        assert _rules(out) == ["lock-discipline"]
        assert "outside" in out[0].message

    def test_undeclared_attribute_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            class Box:
                def __init__(self):
                    self._m = 0

                def touch(self):
                    self._m = 5
        """)
        assert any("undeclared attribute" in f.message for f in out)

    def test_tuple_unpack_write_seen(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._cv = threading.Condition()
                    self._err = None  # guarded-by: _cv

                def take(self):
                    err, self._err = self._err, None
                    return err
        """)
        assert _rules(out) == ["lock-discipline"]

    def test_blocking_call_under_lock(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading, time

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def slow(self):
                    with self._lock:
                        self._n += 1
                        time.sleep(1)
        """)
        assert any("blocking" in f.message for f in out)

    def test_cv_wait_on_held_lock_allowed(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._cv = threading.Condition()
                    self._n = 0  # guarded-by: _cv

                def drain(self):
                    with self._cv:
                        self._cv.wait_for(lambda: self._n == 0, 5)
        """)
        assert _rules(out) == []

    def test_module_global_guard(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            _LOCK = threading.Lock()
            _POOL = None  # guarded-by: _LOCK

            def good():
                global _POOL
                with _LOCK:
                    if _POOL is None:
                        _POOL = object()
                return _POOL

            def bad():
                global _POOL
                _POOL = None
        """)
        assert _rules(out) == ["lock-discipline"]
        assert "_POOL" in out[0].message


class TestLockRuleExprScan:
    def test_no_duplicate_findings_in_nested_statements(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading, time

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def slow(self):
                    with self._lock:
                        if self._n > 0:
                            time.sleep(1)
        """)
        assert len([f for f in out if "blocking" in f.message]) == 1

    def test_nested_cv_wait_under_outer_lock_allowed(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    # flowlint: unguarded -- the lock itself
                    self._cv = threading.Condition()
                    self._n = 0  # guarded-by: _cv

                def drain(self):
                    with self._lock:
                        with self._cv:
                            self._cv.wait_for(lambda: self._n == 0, 5)
        """)
        assert _rules(out) == []


class TestSuppressionHygiene:
    def test_unknown_rule_in_disable_reported(self, tmp_path):
        out = _lint(tmp_path, """
            def f():
                # flowlint: disable=lock-dicipline -- typo'd rule name
                return 1
        """)
        assert any("unknown rule" in f.message for f in out)

    def test_unused_suppression_reported_on_full_run(self, tmp_path):
        out = _lint(tmp_path, """
            def f():
                # flowlint: disable=jit-purity -- nothing here triggers it
                return 1
        """)
        assert any("no longer matches" in f.message for f in out)

    def test_unused_not_reported_when_rules_narrowed(self, tmp_path):
        out = _lint(tmp_path, """
            def f():
                # flowlint: disable=jit-purity -- nothing here triggers it
                return 1
        """, rules=("uint64-discipline",))
        assert _rules(out) == []


class TestNativeLoaderOverride:
    def test_missing_override_raises_every_call(self, monkeypatch):
        import importlib

        import flow_pipeline_tpu.native as native

        monkeypatch.setenv("FLOWDECODE_LIB", "/nonexistent/libx.so")
        importlib.reload(native)
        import pytest as _pytest
        with _pytest.raises(RuntimeError, match="FLOWDECODE_LIB"):
            native.available()
        # the strict override must NOT latch: a caller that swallowed the
        # first error must not silently get the no-native fallback
        with _pytest.raises(RuntimeError, match="FLOWDECODE_LIB"):
            native.available()
        monkeypatch.delenv("FLOWDECODE_LIB")
        importlib.reload(native)  # restore normal loader state


class TestFlagRegistry:
    def _write_registry(self, tmp_path, names):
        util = tmp_path / "utils"
        util.mkdir()
        (util / "flags.py").write_text(
            "KNOWN_FLAGS = frozenset({" +
            ", ".join(repr(n) for n in names) + "})\n")
        return "utils/flags.py"

    def test_undeclared_token_and_declaration(self, tmp_path):
        reg = self._write_registry(tmp_path, ["kafka.topic"])
        (tmp_path / "README.md").write_text("uses -kafka.topic\n")
        (tmp_path / "app.py").write_text(textwrap.dedent("""
            def build(fs):
                fs.string("kafka.topic", "flows", "topic")
                fs.string("kafka.brokerz", "x", "typo'd declaration")
                argv = ["-kafka.topic", "t", "-no.such.flag=1"]
                return argv
        """))
        out = run_lint(str(tmp_path), [reg, "app.py"])
        msgs = " ".join(f.message for f in out)
        assert "kafka.brokerz" in msgs
        assert "-no.such.flag=1" in msgs
        assert "kafka.topic" not in " ".join(
            m for m in msgs.splitlines() if "not mentioned" in m)

    def test_undocumented_flag_flagged(self, tmp_path):
        reg = self._write_registry(tmp_path, ["secret.knob"])
        (tmp_path / "README.md").write_text("no flags here\n")
        out = run_lint(str(tmp_path), [reg])
        assert any("secret.knob" in f.message and "not mentioned" in f.message
                   for f in out)


class TestDtypeFlow:
    """v2 uint64-discipline: the flow-sensitive dtype interpreter."""

    def test_uint64_pyint_promotion_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                c = np.zeros(4, np.uint64)
                total = c.sum()
                return total + 1
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "promote to float64" in out[0].message
        assert "np.uint64(" in out[0].message
        # the finding carries the inferred dtype chain as evidence
        assert "dtype chain" in out[0].message
        assert "np.zeros" in out[0].message or "total" in out[0].message

    def test_wrapped_constant_clean(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                c = np.zeros(4, np.uint64)
                shifted = (c >> np.uint64(16)) | (c << np.uint64(48))
                return c.sum() + np.uint64(1) + shifted[0]
        """, rules=("uint64-discipline",))
        assert _rules(out) == []

    def test_true_division_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                c = np.zeros(4, np.uint64)
                return c / np.uint64(2)
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "division" in out[0].message

    def test_float_mixing_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                c = np.zeros(4, np.uint64)
                scale = np.float32(0.5)
                return c * scale
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "promotion out of the unsigned envelope" in out[0].message

    def test_uint32_pyint_leaves_wraparound_envelope(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                h = np.full(8, 7, np.uint32)
                return h * 5
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "wraparound envelope" in out[0].message

    def test_ops_scope_checked_without_marker(self, tmp_path):
        # ops/ and hostsketch/ modules get promotion checks even
        # unmarked — but NOT the strict dtype-less-constructor checks
        out = _lint(tmp_path, """
            import numpy as np

            def f():
                lax = np.zeros(4)          # dtype-less: ok here
                c = np.zeros(4, np.uint64)
                return c + 1, lax
        """, name="flow_pipeline_tpu/ops/fix.py",
            rules=("uint64-discipline",))
        assert len(out) == 1
        assert "promote to float64" in out[0].message

    def test_jnp_weak_typing_exempt(self, tmp_path):
        # JAX keeps the array dtype for python-int operands (weak
        # typing); only numpy's scalar rules promote
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import jax.numpy as jnp

            def f(x):
                h = x.astype(jnp.uint32)
                return h ^ (h >> 16)
        """, rules=("uint64-discipline",))
        assert _rules(out) == []

    def test_param_shadowing_module_global_not_guessed(self, tmp_path):
        # a parameter shadows a module-level uint64 constant: callers
        # may pass anything, so the interpreter must not inherit the
        # global's dtype — under-approximate, never guess
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            MASK = np.uint64(0xFF)

            def f(MASK):
                return MASK + 1
        """, rules=("uint64-discipline",))
        assert _rules(out) == []

    def test_class_level_dtypeless_constructor_flagged(self, tmp_path):
        # class-body statements execute at definition time; a platform-
        # default-dtype table at class scope is still a finding
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            class C:
                TABLE = np.array([1, 2, 3])
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "without an explicit dtype" in out[0].message

    def test_yield_fstring_and_subscript_index_scanned(self, tmp_path):
        # expressions the statement driver reaches only through yield,
        # f-strings, or an assignment target's index are still scanned
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def gen():
                yield np.zeros(3)

            def fmt():
                return f"{np.zeros(4)}"

            def store(d, v):
                d[np.int64(v)] = 0
        """, rules=("uint64-discipline",))
        assert len(out) == 3
        msgs = " ".join(f.message for f in out)
        assert "without an explicit dtype" in msgs
        assert "signed scalar constructor" in msgs

    def test_walrus_assignment_tracked(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                c = np.zeros(4, np.uint64)
                if (total := c.sum() + 1) > 0:
                    return total
                return None
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "uint64 +" in out[0].message

    def test_match_case_bodies_interpreted(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(mode):
                c = np.zeros(4, np.uint64)
                match mode:
                    case "bump":
                        return c + 1
                    case _:
                        return c
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "uint64 +" in out[0].message

    def test_decorator_expressions_scanned(self, tmp_path):
        # decorators evaluate at definition time in the enclosing scope
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def deco(table):
                def wrap(fn):
                    return fn
                return wrap

            @deco(np.zeros(3))
            def f():
                return 0
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "without an explicit dtype" in out[0].message

    def test_propagation_through_branches_and_calls(self, tmp_path):
        # dtype survives if/else when both branches agree; np.where and
        # astype propagate; the flag fires far from the construction
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(cond, raw):
                if cond:
                    c = np.asarray(raw, dtype=np.uint64)
                else:
                    c = np.zeros(3, np.uint64)
                picked = np.where(cond, c, np.uint64(0))
                return picked - 1
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert out[0].line == 11  # the `return picked - 1` line
        assert "np.asarray" in out[0].message  # chain reaches back

    def test_comprehension_lambda_and_default_bodies_scanned(self, tmp_path):
        # the v1 ast.walk checks must survive the move to an
        # interpreter: constructors inside comprehensions, lambdas, and
        # default-arg expressions are still findings
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(vals, fill=np.zeros(2)):
                planes = [np.zeros(4) for _ in range(3)]
                sig = [np.int64(v) for v in vals]
                g = lambda v: np.array([v])
                return planes, sig, g, fill
        """, rules=("uint64-discipline",))
        assert _rules(out) == ["uint64-discipline"] * 4

    def test_suppression_still_works(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                c = np.zeros(4, np.uint64)
                # flowlint: disable=uint64-discipline -- bounded by caller, exact below 2^53
                return c.sum() + 1
        """, rules=("uint64-discipline",))
        assert _rules(out) == []


class TestLockOrder:
    def test_two_lock_cycle_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
        """, rules=("lock-order",))
        assert len(out) == 1
        assert "lock-order cycle" in out[0].message
        assert "Box._a" in out[0].message and "Box._b" in out[0].message

    def test_multi_item_with_cycle_flagged(self, tmp_path):
        # `with a, b:` acquires left to right — the same deadlock as
        # nested withs, and the same finding
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a, self._b:
                        pass

                def two(self):
                    with self._b, self._a:
                        pass
        """, rules=("lock-order",))
        assert len(out) == 1
        assert "lock-order cycle" in out[0].message

    def test_consistent_order_clean(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
        """, rules=("lock-order",))
        assert _rules(out) == []

    def test_nested_def_not_attributed_to_encloser(self, tmp_path):
        # defining a callback is not running it: schedule() never
        # sleeps, so calling it under a lock is not blocking-while-
        # holding (same for lambda bodies)
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import time
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def schedule(self):
                    def cb():
                        time.sleep(1)
                    slow = lambda: time.sleep(2)
                    return cb, slow

                def outer(self):
                    with self._lock:
                        return self.schedule()
        """, rules=("lock-order", "lock-discipline"))
        assert _rules(out) == []

    def test_same_named_classes_not_unified(self, tmp_path):
        # two unrelated classes that happen to share a name must not
        # have their locks merged into a phantom deadlock cycle
        m1 = textwrap.dedent("""
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def go(self):
                    with self._a:
                        with self._b:
                            pass
        """)
        m2 = m1.replace("with self._a:", "with self._X:").replace(
            "with self._b:", "with self._a:").replace(
            "with self._X:", "with self._b:")
        (tmp_path / "m1.py").write_text(m1)
        (tmp_path / "m2.py").write_text(m2)
        out = run_lint(str(tmp_path), ["m1.py", "m2.py"],
                       rules=("lock-order",))
        assert out == []

    def test_cycle_witness_reports_only_real_edges(self, tmp_path):
        # a<->b and b<->c form one SCC, but there is NO c -> a edge:
        # the reported witness path must not fabricate one (it would
        # send the maintainer to reorder an acquisition no code does)
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._c = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def ba(self):
                    with self._b:
                        with self._a:
                            pass

                def bc(self):
                    with self._b:
                        with self._c:
                            pass

                def cb(self):
                    with self._c:
                        with self._b:
                            pass
        """, rules=("lock-order",))
        assert len(out) == 1
        assert "fix.Box._a -> fix.Box._b -> fix.Box._a" in out[0].message
        assert "_c -> fix.Box._a" not in out[0].message

    def test_match_case_bodies_walked(self, tmp_path):
        # acquisitions and blocking calls inside `match` case bodies
        # must be as visible as inside `if` branches
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import time
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self, mode):
                    with self._a:
                        match mode:
                            case "x":
                                with self._b:
                                    self.slow()

                def slow(self):
                    time.sleep(1)

                def two(self):
                    with self._b:
                        with self._a:
                            pass
        """, rules=("lock-order",))
        msgs = " ".join(f.message for f in out)
        assert "lock-order cycle" in msgs
        assert "slow()" in msgs and "time.sleep" in msgs

    def test_interprocedural_cycle_through_calls(self, tmp_path):
        # the cycle only exists composed with the call graph: each
        # method nests ONE with, the second lock comes from the callee
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        self.grab_b()

                def grab_b(self):
                    with self._b:
                        pass

                def two(self):
                    with self._b:
                        self.grab_a()

                def grab_a(self):
                    with self._a:
                        pass
        """, rules=("lock-order",))
        assert any("lock-order cycle" in f.message for f in out)

    def test_interprocedural_blocking_while_holding(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading, time

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def helper(self):
                    time.sleep(1)

                def outer(self):
                    with self._lock:
                        self.helper()
        """, rules=("lock-order",))
        assert len(out) == 1
        assert "eventually blocks" in out[0].message
        assert "time.sleep" in out[0].message

    def test_cv_wait_exemption_kept(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._cv = threading.Condition()

                def drain(self):
                    with self._cv:
                        self._cv.wait_for(lambda: True, 5)

                def caller(self):
                    self.drain()
        """, rules=("lock-order",))
        assert _rules(out) == []

    def test_plain_lock_self_deadlock_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._m = threading.Lock()

                def a(self):
                    with self._m:
                        self.b()

                def b(self):
                    with self._m:
                        pass
        """, rules=("lock-order",))
        assert len(out) == 1
        assert "fix.Box._m -> fix.Box._m" in out[0].message

    def test_reentrant_lock_self_reentry_allowed(self, tmp_path):
        # bus.InProcessBus.produce -> create_topic under the same RLock
        # is the sanctioned pattern; Condition wraps an RLock too
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._m = threading.RLock()

                def a(self):
                    with self._m:
                        self.b()

                def b(self):
                    with self._m:
                        pass
        """, rules=("lock-order",))
        assert _rules(out) == []

    def test_cross_class_edge_via_constructed_attr(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading, time

            class Inner:
                def __init__(self):
                    self._il = threading.Lock()

                def poke(self):
                    with self._il:
                        time.sleep(0.1)

            class Outer:
                def __init__(self):
                    self._ol = threading.Lock()
                    self._inner = Inner()

                def a(self):
                    with self._ol:
                        self._inner.poke()
        """, rules=("lock-order",))
        # no cycle — but the blocking call inside Inner.poke is seen
        # from Outer.a through the constructor-typed attribute
        assert len(out) == 1
        assert "eventually blocks" in out[0].message


class TestLockDisciplineSubscript:
    def test_subscript_store_needs_annotation(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            class Box:
                def __init__(self):
                    self._states = [None]

                def reset(self, i):
                    self._states[i] = object()
        """, rules=("lock-discipline",))
        assert len(out) == 1
        assert "undeclared attribute" in out[0].message

    def test_annotated_subscript_store_passes(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            class Box:
                def __init__(self):
                    # flowlint: unguarded -- worker thread only
                    self._states = [None]

                def reset(self, i):
                    self._states[i] = object()
        """, rules=("lock-discipline",))
        assert _rules(out) == []

    def test_guarded_subscript_store_enforced(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    self._commits = {}  # guarded-by: _lock

                def good(self, k, v):
                    with self._lock:
                        self._commits[k] = v

                def bad(self, k, v):
                    self._commits[k] = v
        """, rules=("lock-discipline",))
        assert _rules(out) == ["lock-discipline"]
        assert "outside" in out[0].message


_ABI_CC = """
#include <stdint.h>

extern "C" {

// sums n uint32s, scaled
long long fd_sum(const uint32_t* data, long long n, int scale) {
  long long out = 0;
  for (long long i = 0; i < n; ++i) { out += data[i] * scale; }
  return out;
}

long long fd_scan(const uint8_t* buf, long long n, float* out) {
  if (n > 0) { out[0] = 1.0f; }
  return n;
}

}  // extern "C"
"""

_ABI_BINDER_OK = """
import ctypes
import numpy as np


def _c_arr(a):
    return a.ctypes.data_as(ctypes.c_void_p)


def _bind(lib):
    lib.fd_sum.restype = ctypes.c_longlong
    lib.fd_sum.argtypes = [
        ctypes.c_void_p,
        ctypes.c_longlong,
        ctypes.c_int,
    ]
    lib.fd_scan.restype = ctypes.c_longlong
    lib.fd_scan.argtypes = [
        ctypes.c_char_p,
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_float),
    ]
    return lib


def call(lib, xs):
    xs = np.ascontiguousarray(xs, dtype=np.uint32)
    return lib.fd_sum(_c_arr(xs), len(xs), 1)
"""


class TestAbiContract:
    def _setup(self, tmp_path, cc=_ABI_CC, binder=_ABI_BINDER_OK):
        (tmp_path / "native").mkdir(exist_ok=True)
        (tmp_path / "native" / "fake.cc").write_text(cc)
        (tmp_path / "binder.py").write_text(textwrap.dedent(binder))
        return run_lint(str(tmp_path), ["binder.py"],
                        rules=("abi-contract",))

    def test_matching_binder_clean(self, tmp_path):
        assert self._setup(tmp_path) == []

    def test_arity_mismatch_flagged(self, tmp_path):
        out = self._setup(tmp_path, binder=_ABI_BINDER_OK.replace(
            "        ctypes.c_int,\n", ""))
        assert len(out) == 1
        assert "declares 2 parameter(s)" in out[0].message
        assert "fd_sum" in out[0].message

    def test_ctype_mapping_mismatch_flagged(self, tmp_path):
        out = self._setup(tmp_path, binder=_ABI_BINDER_OK.replace(
            "        ctypes.c_longlong,\n        ctypes.c_int,",
            "        ctypes.c_int,\n        ctypes.c_int,"))
        assert len(out) == 1
        assert "argtypes[1]" in out[0].message
        assert "long long" in out[0].message

    def test_unbound_export_flagged_and_allowlisted(self, tmp_path):
        binder_partial = _ABI_BINDER_OK.replace(
            "    lib.fd_scan.restype = ctypes.c_longlong\n"
            "    lib.fd_scan.argtypes = [\n"
            "        ctypes.c_char_p,\n"
            "        ctypes.c_longlong,\n"
            "        ctypes.POINTER(ctypes.c_float),\n"
            "    ]\n", "")
        out = self._setup(tmp_path, binder=binder_partial)
        assert len(out) == 1
        assert "fd_scan" in out[0].message and "no ctypes binding" \
            in out[0].message
        assert out[0].path.endswith("fake.cc")
        # the explicit allowlist silences it
        out = self._setup(tmp_path, binder=binder_partial +
                          "\n# flowlint: abi-unbound: fd_scan -- "
                          "bound lazily by the stress driver only\n")
        assert out == []

    def test_binding_nonexistent_symbol_flagged(self, tmp_path):
        out = self._setup(tmp_path, binder=_ABI_BINDER_OK.replace(
            "fd_scan", "fd_scam"))
        msgs = " ".join(f.message for f in out)
        assert "fd_scam" in msgs and "no extern" in msgs
        # and fd_scan is now unbound on the C side
        assert "fd_scan" in msgs

    def test_missing_restype_flagged(self, tmp_path):
        out = self._setup(tmp_path, binder=_ABI_BINDER_OK.replace(
            "    lib.fd_scan.restype = ctypes.c_longlong\n", ""))
        assert len(out) == 1
        assert "no restype" in out[0].message

    def test_ctypes_alias_treated_as_unknown(self, tmp_path):
        # a local alias (`_LL = ctypes.c_longlong`) is opaque to the
        # parser: skip the comparison, don't report the alias's
        # spelling as an ABI mismatch
        binder = _ABI_BINDER_OK.replace(
            "import ctypes\n",
            "import ctypes\n\n_LL = ctypes.c_longlong\n").replace(
            "    lib.fd_sum.restype = ctypes.c_longlong\n",
            "    lib.fd_sum.restype = _LL\n").replace(
            "        ctypes.c_longlong,\n        ctypes.c_int,\n",
            "        _LL,\n        ctypes.c_int,\n")
        assert "_LL = ctypes.c_longlong" in binder
        out = self._setup(tmp_path, binder=binder)
        assert out == []

    def test_argtypes_via_shared_name_not_misreported(self, tmp_path):
        # argtypes assigned a module-level name is unparseable for the
        # rule: treat it as unknown and skip the arity/type checks —
        # never claim the argtypes assignment is missing
        binder = _ABI_BINDER_OK.replace(
            "    lib.fd_scan.argtypes = [\n"
            "        ctypes.c_char_p,\n"
            "        ctypes.c_longlong,\n"
            "        ctypes.POINTER(ctypes.c_float),\n"
            "    ]\n",
            "    lib.fd_scan.argtypes = _SCAN_ARGS\n")
        assert binder != _ABI_BINDER_OK
        out = self._setup(tmp_path, binder=binder)
        assert out == []

    def test_callsite_dtype_mismatch_flagged(self, tmp_path):
        out = self._setup(tmp_path, binder=_ABI_BINDER_OK.replace(
            "dtype=np.uint32", "dtype=np.float32"))
        assert len(out) == 1
        assert "float32 buffer" in out[0].message
        assert "uint32_t*" in out[0].message

    def test_callsite_dtype_via_assert_and_empty(self, tmp_path):
        binder = _ABI_BINDER_OK + textwrap.dedent("""
            def scan(lib, buf):
                assert buf.dtype == np.uint8
                out = np.empty(4, np.float64)
                return lib.fd_scan(buf, len(buf), _c_arr(out))
        """)
        out = self._setup(tmp_path, binder=binder)
        assert len(out) == 1
        assert "float64 buffer" in out[0].message
        assert "'out'" in out[0].message

    def test_rule_skipped_without_binder_in_scope(self, tmp_path):
        (tmp_path / "native").mkdir()
        (tmp_path / "native" / "fake.cc").write_text(_ABI_CC)
        (tmp_path / "other.py").write_text("x = 1\n")
        out = run_lint(str(tmp_path), ["other.py"],
                       rules=("abi-contract",))
        assert out == []

    def test_repo_abi_covers_all_native_symbols(self):
        # the acceptance criterion: the rule parses and checks every
        # bound symbol of the real library (17 as of r21 — decode/count/
        # encode/hash_group + the threaded hash_group_mt twin + the 4
        # hs_* sketch kernels + the hs_spread_update register scatter-max
        # + the 2 hs_inv_* invertible kernels + the 3 ff_* fused-dataplane
        # kernels + the 2 ff_build_* lane builders). The fused kernels'
        # cross-file calls INTO hs_* are declarations (semicolon-
        # terminated), which the parser must not double-count as exports.
        from tools.flowlint import rules_abi

        exports = [f.name for f in rules_abi.parse_exports(REPO)]
        assert sorted(exports) == sorted(set(exports)), \
            "extern-C declarations double-counted as exports"
        assert set(exports) == {
            "flow_decode_stream", "flow_count_frames",
            "flow_encode_stream", "flow_hash_group",
            "flow_hash_group_mt",
            "hs_cms_update", "hs_cms_query", "hs_hh_prefilter",
            "hs_topk_merge", "hs_spread_update",
            "hs_inv_update", "hs_inv_decode",
            "ff_group_sum", "ff_group_sum_mt", "ff_fused_update",
            "ff_build_lanes", "ff_build_planes",
        }
        bound = rules_abi.parse_bound_symbols(os.path.join(
            REPO, "flow_pipeline_tpu", "native", "__init__.py"))
        assert bound == set(exports)


class TestJsonOutput:
    def test_json_findings_machine_readable(self, tmp_path, capsys):
        import json

        from tools.flowlint.runner import main

        (tmp_path / "fix.py").write_text(textwrap.dedent("""
            # flowlint: uint64-exact
            import numpy as np

            def f():
                return np.zeros(3)
        """))
        rc = main(["--root", str(tmp_path), "--json", "fix.py"])
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        assert data["count"] == 1
        (f,) = data["findings"]
        assert f["file"] == "fix.py" and f["rule"] == "uint64-discipline"
        assert isinstance(f["line"], int) and f["message"]

    def test_json_clean_run(self, tmp_path, capsys):
        import json

        from tools.flowlint.runner import main

        (tmp_path / "ok.py").write_text("x = 1\n")
        rc = main(["--root", str(tmp_path), "--json", "ok.py"])
        assert rc == 0
        data = json.loads(capsys.readouterr().out)
        assert data["count"] == 0 and data["findings"] == []


class TestDtypeSignedMix:
    """unsigned op signed — the headline promotion, both dtypes inferred."""

    def test_uint64_int64_promotion_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                a = np.zeros(3, dtype=np.uint64)
                b = np.ones(3, dtype=np.int64)
                return a + b
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "promotes to float64" in out[0].message
        assert "dtype chain" in out[0].message

    def test_smaller_unsigned_signed_mix_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f():
                a = np.zeros(3, dtype=np.uint32)
                b = np.ones(3, dtype=np.int32)
                return a ^ b
        """, rules=("uint64-discipline",))
        assert len(out) == 1
        assert "wraparound envelope" in out[0].message

    def test_starred_unpack_clears_tracked_dtype(self, tmp_path):
        # `a, *rest = vals` rebinds rest to a plain list — a stale
        # tracked uint64 here was a false positive on correct code
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(vals):
                rest = np.zeros(3, dtype=np.uint64)
                a, *rest = vals
                return rest + [1]
        """, rules=("uint64-discipline",))
        assert _rules(out) == []

    def test_class_bases_and_keywords_scanned(self, tmp_path):
        # base/metaclass expressions run at class-definition time just
        # like decorators; v1 (ast.walk) saw them, so must v2
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            class C(make_base(np.zeros(3)), metaclass=pick(np.array([1]))):
                pass
        """, rules=("uint64-discipline",))
        assert len(out) == 2
        assert all("without an explicit dtype" in f.message for f in out)


class TestAsyncCoverage:
    """async def / async with / async for bodies get the same analysis
    as their sync twins in every rule."""

    def test_dtype_interpreter_enters_async_with(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            async def g(lock, it):
                async with lock:
                    bad = np.zeros(3)
                async for _ in it:
                    d = np.zeros(3, dtype=np.uint64)
                    return d / 2
        """, rules=("uint64-discipline",))
        assert len(out) == 2
        assert any("without an explicit dtype" in f.message for f in out)
        assert any("true division" in f.message for f in out)

    def test_lock_discipline_covers_async_methods(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading
            import time

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock

                async def ok(self):
                    async with self._lock:
                        self.n = 1

                async def bad(self):
                    self.n = 2

                async def blocky(self):
                    async with self._lock:
                        time.sleep(1)
        """, rules=("lock-discipline",))
        msgs = sorted(f.message for f in out)
        assert len(out) == 2
        assert any("outside `with self._lock:`" in m for m in msgs)
        assert any("blocking call" in m for m in msgs)

    def test_lock_order_cycle_through_async_with(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                async def ab(self):
                    async with self._a:
                        async with self._b:
                            pass

                async def ba(self):
                    async with self._b:
                        async with self._a:
                            pass
        """, rules=("lock-order",))
        assert len(out) == 1
        assert "cycle" in out[0].message


class TestCrossFileLockCycle:
    def _write_pkg(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "a_mod.py").write_text(textwrap.dedent("""
            # flowlint: lock-checked
            import threading
            from pkg.z_mod import Worker

            class A:
                def __init__(self):
                    self.l1 = threading.Lock()
                    self.w = Worker()

                def go(self):
                    with self.l1:
                        self.w.go()

                def reenter(self):
                    with self.l1:
                        pass
        """))
        (pkg / "z_mod.py").write_text(textwrap.dedent("""
            # flowlint: lock-checked
            import threading
            from pkg.a_mod import A

            class Worker:
                def __init__(self):
                    self.l2 = threading.Lock()
                    self.back = A()

                def go(self):
                    with self.l2:
                        self.back.reenter()
        """))

    def test_cycle_found_in_both_file_orders(self, tmp_path):
        # constructor-typed attrs must resolve against classes indexed
        # LATER in the file list too — a one-pass index dropped
        # whichever direction of the cycle was scanned first
        self._write_pkg(tmp_path)
        for order in (["pkg/a_mod.py", "pkg/z_mod.py"],
                      ["pkg/z_mod.py", "pkg/a_mod.py"]):
            out = run_lint(str(tmp_path), order, ("lock-order",))
            assert any("pkg.a_mod.A.l1 -> pkg.z_mod.Worker.l2" in f.message
                       for f in out), order


class TestDtypePositionalCast:
    def test_asarray_positional_dtype_retypes(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(x):
                y = np.asarray(x, np.uint64)
                return y + 1
        """, rules=("uint64-discipline",))
        # the cast target (uint64), not the input's dtype, flows on
        assert len(out) == 1
        assert "uint64 + python int" in out[0].message

    def test_sort_positional_axis_not_a_dtype(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def g():
                a = np.zeros(3, dtype=np.uint32)
                s = np.sort(a, 0)
                return s + np.uint32(1)
        """, rules=("uint64-discipline",))
        assert _rules(out) == []


class TestJsonRuleNarrowing:
    def test_json_rules_reflect_selection(self, tmp_path, capsys):
        import json

        from tools.flowlint.runner import main

        (tmp_path / "ok.py").write_text("x = 1\n")
        rc = main(["--root", str(tmp_path), "--json",
                   "--rule", "lock-order", "ok.py"])
        assert rc == 0
        data = json.loads(capsys.readouterr().out)
        # a narrowed run must not claim all six rules ran
        assert data["rules"] == ["lock-order"]


class TestNetTimeout:
    """r17 satellite: every urlopen/socket/requests call in net-checked
    modules must carry an explicit timeout (the r13 mesh trace fan-out
    bug was exactly this class)."""

    def test_unmarked_module_not_checked(self, tmp_path):
        out = _lint(tmp_path, """
            import urllib.request
            def f(url):
                return urllib.request.urlopen(url).read()
            """, rules=("net-timeout",))
        assert out == []

    def test_urlopen_without_timeout_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: net-checked
            import urllib.request
            def f(url):
                return urllib.request.urlopen(url).read()
            """, rules=("net-timeout",))
        assert _rules(out) == ["net-timeout"]
        assert "urlopen" in out[0].message

    def test_aliased_urlopen_still_matched(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: net-checked
            import urllib.request as _rq
            def f(url):
                return _rq.urlopen(url).read()
            """, rules=("net-timeout",))
        assert _rules(out) == ["net-timeout"]

    def test_timeout_kw_and_positional_accepted(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: net-checked
            import socket
            import urllib.request
            def f(url, addr):
                a = urllib.request.urlopen(url, timeout=5).read()
                b = urllib.request.urlopen(url, None, 5).read()
                c = socket.create_connection(addr, 2.0)
                d = socket.create_connection(addr, timeout=2.0)
                return a, b, c, d
            """, rules=("net-timeout",))
        assert out == []

    def test_http_connection_and_requests_checked(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: net-checked
            import http.client
            import requests
            import socket
            def f(host, addr, url):
                c1 = http.client.HTTPConnection(host, 80)
                c2 = http.client.HTTPConnection(host, 80, timeout=3)
                r1 = requests.get(url)
                r2 = requests.get(url, timeout=3)
                s1 = socket.create_connection(addr)
                return c1, c2, r1, r2, s1
            """, rules=("net-timeout",))
        assert _rules(out) == ["net-timeout"] * 3
        lines = sorted(f.line for f in out)
        assert lines == [7, 9, 11]

    def test_suppression_with_reason_accepted(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: net-checked
            import urllib.request
            def f(url):
                # deliberate: blocks until the endless stream closes
                return urllib.request.urlopen(url).read()  # flowlint: disable=net-timeout -- endless tail follow, bounded by caller's thread lifetime
            """, rules=("net-timeout",))
        assert out == []

    def test_repo_net_modules_are_marked(self):
        """The modules that actually open cross-process sockets must
        stay opted in — deleting a marker would silently de-fang the
        rule exactly where it matters."""
        from tools.flowlint.core import load_files

        rels = ["flow_pipeline_tpu/mesh/server.py",
                "flow_pipeline_tpu/serve/loadgen.py",
                "flow_pipeline_tpu/sink/clickhouse.py",
                "flow_pipeline_tpu/cli.py"]
        for sf in load_files(REPO, rels):
            assert "net-checked" in sf.markers, sf.rel


_FAM_HOOKS = """
def payload(state): return {}
def merge(payloads, config=None): return {}
def top_rows(merged, config, k, slot): return {}
def capture(m): return (None, 1, None)
def capture_merged(spec, slot, payloads): return None
def save(model): return {}
def restore(model, ms, name): return None
"""

_FAM_REGISTRY = """
register(SketchFamily(
    kind="hh",
    snapshot_kind="windowed_hh",
    checkpoint_kind="windowed_hh",
    payload_kinds=("hh",),
    merge_monoid="u64-sum",
    ranked=True,
    state_attr="state",
    payload="hooks:payload",
    merge="hooks:merge",
    top_rows="hooks:top_rows",
    serve_capture="hooks:capture",
    serve_capture_merged="hooks:capture_merged",
    checkpoint_save="hooks:save",
    checkpoint_restore="hooks:restore",
    flag_namespace="hh.",
    endpoint="/query/topk",
    parity_target="hh-parity",
    doc_token="`hh`",
    obs_token="hh_recall",
))
"""


class TestFamilyCitizenship:
    """family-citizenship fixture battery: the registry parser, the
    per-surface completeness checks, the reverse kind-literal check,
    and the suppression/skip-file behavior every other rule has."""

    def _run(self, tmp_path, registry=_FAM_REGISTRY, extra=()):
        files = {"families/registry.py": registry,
                 "hooks.py": _FAM_HOOKS}
        files.update(extra)
        for rel, src in files.items():
            path = tmp_path / rel
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(textwrap.dedent(src))
        return run_lint(str(tmp_path), sorted(files),
                        rules=("family-citizenship",))

    def test_complete_registry_clean(self, tmp_path):
        assert self._run(tmp_path) == []

    def test_rule_skipped_without_registry_in_scope(self, tmp_path):
        (tmp_path / "app.py").write_text('kind = x["kind"] == "mystery"\n')
        out = run_lint(str(tmp_path), ["app.py"],
                       rules=("family-citizenship",))
        assert out == []

    def test_missing_surface_named_exactly_once(self, tmp_path):
        out = self._run(tmp_path, registry=_FAM_REGISTRY.replace(
            '    merge="hooks:merge",\n', ""))
        assert len(out) == 1
        assert "family `hh` is missing surface `merge`" in out[0].message

    def test_ranked_surfaces_only_owed_when_ranked(self, tmp_path):
        dropped = _FAM_REGISTRY.replace(
            '    serve_capture="hooks:capture",\n', "")
        out = self._run(tmp_path, registry=dropped)
        assert len(out) == 1
        assert "missing surface `serve_capture`" in out[0].message
        # an unranked family (exact rows, wagg-style) legitimately
        # leaves the top-K capture surfaces unset
        unranked = dropped.replace("    ranked=True,", "    ranked=False,") \
            .replace('    serve_capture_merged="hooks:capture_merged",\n',
                     "").replace('    snapshot_kind="windowed_hh",\n', "") \
            .replace('    state_attr="state",\n', "")
        assert self._run(tmp_path, registry=unranked) == []

    def test_unresolvable_hook_flagged(self, tmp_path):
        out = self._run(tmp_path, registry=_FAM_REGISTRY.replace(
            "hooks:merge", "hooks:no_such_fn"))
        assert len(out) == 1
        assert "does not resolve" in out[0].message
        assert "no_such_fn" in out[0].message

    def test_hook_module_outside_scope_flagged(self, tmp_path):
        out = self._run(tmp_path, registry=_FAM_REGISTRY.replace(
            "hooks:merge", "phantom_mod:merge"))
        assert len(out) == 1
        assert "phantom_mod" in out[0].message
        assert "not in the lint scope" in out[0].message

    def test_computed_field_is_a_finding(self, tmp_path):
        out = self._run(tmp_path, registry=_FAM_REGISTRY.replace(
            'merge="hooks:merge",', 'merge="hooks:" + MERGE_FN,'))
        assert any("must be a literal" in f.message for f in out)

    def test_unregistered_kind_literal_flagged(self, tmp_path):
        out = self._run(tmp_path, extra={"mesh/codec.py": """
            def capture(payload):
                if payload["kind"] == "mystery":
                    return None
                if payload["kind"] == "hh":
                    return payload
        """})
        assert len(out) == 1
        assert 'kind tag "mystery"' in out[0].message
        assert out[0].path == "mesh/codec.py"

    def test_snapshot_and_get_kind_forms_checked(self, tmp_path):
        out = self._run(tmp_path, extra={"serve/publisher.py": """
            def pick(m, payload):
                a = m.snapshot_kind == "windowed_hh"       # registered
                b = payload.get("kind") in ("hh", "rogue")
                return a, b
        """})
        assert len(out) == 1
        assert 'kind tag "rogue"' in out[0].message

    def test_bare_kind_local_not_a_signal(self, tmp_path):
        # journal records / delta ships reuse a local named `kind`;
        # those tagged unions are not family dispatch
        out = self._run(tmp_path, extra={"mesh/coordinator.py": """
            def replay(records):
                for kind, blob in records:
                    if kind == "chk":
                        return blob
        """})
        assert out == []

    def test_non_family_kind_allowed_then_stale_flagged(self, tmp_path):
        allow = "NON_FAMILY_KINDS = (\"ddos\",)\n" + _FAM_REGISTRY
        out = self._run(tmp_path, registry=allow, extra={
            "engine/worker.py": """
                def restore(ms):
                    if ms["kind"] == "ddos":
                        return None
            """})
        assert out == []
        # the same entry with no dispatch surface mentioning it is
        # itself a finding (stale allowlist discipline)
        out = self._run(tmp_path, registry=allow, extra={
            "engine/worker.py": """
                def restore(ms):
                    return ms
            """})
        assert len(out) == 1
        assert '"ddos" appears at no dispatch surface' in out[0].message

    def test_empty_registry_flagged(self, tmp_path):
        out = self._run(tmp_path, registry="FAMILIES = {}\n")
        assert len(out) == 1
        assert "registers no SketchFamily" in out[0].message

    def test_suppression_with_reason_accepted(self, tmp_path):
        out = self._run(tmp_path, registry=_FAM_REGISTRY.replace(
            "register(SketchFamily(",
            "register(SketchFamily(  # flowlint: disable=family-citizenship -- half-registered on purpose: fixture").replace(
            '    merge="hooks:merge",\n', ""))
        assert out == []

    def test_skip_file_opts_registry_out(self, tmp_path):
        out = self._run(
            tmp_path,
            registry="# flowlint: skip-file\n" + _FAM_REGISTRY.replace(
                '    merge="hooks:merge",\n', ""))
        assert out == []

    def test_repo_registry_parses_with_four_families(self):
        # the real registry must stay statically readable: the same
        # parser the lint uses sees all four families and both
        # NON_FAMILY_KINDS entries
        from tools.flowlint import rules_family
        from tools.flowlint.core import load_files

        (reg,) = load_files(
            REPO, ["flow_pipeline_tpu/families/registry.py"])
        fams, non_family, _line, findings = \
            rules_family._parse_registry(reg)
        assert findings == []
        assert [kw["kind"] for kw, _ in fams] == \
            ["hh", "wagg", "dense", "spread"]
        assert non_family == ["ddos", "flowguard"]


class TestAnnotate:
    def test_json_round_trips_to_error_lines(self, tmp_path, capsys):
        import json

        from tools.flowlint import annotate
        from tools.flowlint.runner import main

        (tmp_path / "fix.py").write_text(textwrap.dedent("""
            # flowlint: uint64-exact
            import numpy as np

            def f():
                return np.zeros(3)
        """))
        rc = main(["--root", str(tmp_path), "--json", "fix.py"])
        assert rc == 1
        doc = json.loads(capsys.readouterr().out)
        json_path = tmp_path / "findings.json"
        json_path.write_text(json.dumps(doc))
        assert annotate.main([str(json_path)]) == 0
        lines = capsys.readouterr().out.splitlines()
        (f,) = doc["findings"]
        assert lines == [
            f"::error file=fix.py,line={f['line']},"
            f"title=flowlint uint64-discipline::{f['message']}",
            "flowlint: 1 finding(s)",
        ]

    def test_clean_document_emits_count_only(self, capsys):
        from tools.flowlint import annotate

        assert annotate.annotations({"findings": [], "count": 0}) == \
            ["flowlint: 0 finding(s)"]


# common fixture prologue — indented to match the fixture literals so
# textwrap.dedent in _lint sees one uniform block
_DUR = """
            # flowlint: durable-checked
            from flow_pipeline_tpu.utils import fsutil
"""


class TestDurabilityProtocol:
    """durability-protocol fixture battery: the per-function protocol
    model (open/write/fsync/replace/dir-fsync ordering), the raw-op and
    bare-open fences, the group-commit seam, and the verified
    `# durable:` annotation grammar."""

    def test_unmarked_module_not_checked(self, tmp_path):
        out = _lint(tmp_path, """
            def f(path):
                with open(path, "w") as fh:
                    fh.write("x")
        """, rules=("durability-protocol",))
        assert out == []

    def test_bare_write_open_flagged(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def f(path):
                with open(path, "w") as fh:
                    fh.write("x")
        """, rules=("durability-protocol",))
        assert len(out) == 1
        assert "bare open" in out[0].message
        assert "open_durable" in out[0].message

    def test_nonliteral_mode_flagged_read_modes_ignored(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def f(path, m):
                a = open(path)            # default read: fine
                b = open(path, "r")
                c = open(path, "rb")
                d = open(path, m)         # unclassifiable
                return a, b, c, d
        """, rules=("durability-protocol",))
        assert len(out) == 1
        assert "non-literal mode" in out[0].message

    def test_raw_os_ops_flagged(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            import os, shutil

            def f(a, b):
                os.replace(a, b)
                shutil.rmtree(a)
        """, rules=("durability-protocol",))
        msgs = " ".join(f.message for f in out)
        assert len(out) == 2
        assert "raw os.replace()" in msgs
        assert "raw shutil.rmtree()" in msgs
        assert "utils/fsutil" in msgs

    def test_raw_ops_exempt_in_core_fsutil(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: durable-checked
            import os

            def fsync_file(f):
                f.flush()
                os.fsync(f.fileno())
        """, name="flow_pipeline_tpu/utils/fsutil.py",
            rules=("durability-protocol",))
        assert out == []

    def test_full_publish_protocol_clean(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def publish(path, data):
                tmp = path + ".tmp"
                with fsutil.open_durable(tmp, "wb") as f:
                    f.write(data)
                    fsutil.fsync_file(f)
                fsutil.replace(tmp, path)
                fsutil.fsync_dir(".")
        """, rules=("durability-protocol",))
        assert out == []

    def test_write_bytes_durable_is_the_whole_sentence(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def spill(path, data):
                fsutil.write_bytes_durable(path, data)
        """, rules=("durability-protocol",))
        assert out == []

    def test_unsynced_handle_write_flagged(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def f(path):
                fh = fsutil.open_durable(path, "ab")
                fh.write(b"rec")
                fh.close()
                fsutil.fsync_dir(".")
        """, rules=("durability-protocol",))
        assert len(out) == 1
        assert "no later fsutil.fsync_file(fh)" in out[0].message

    def test_replace_of_unsynced_temp_flagged(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def f(path):
                tmp = path + ".tmp"
                with fsutil.open_durable(tmp, "wb") as f:
                    f.write(b"payload")
                fsutil.replace(tmp, path)
                fsutil.fsync_file(f)   # too late: after the publish
                fsutil.fsync_dir(".")
        """, rules=("durability-protocol",))
        assert len(out) == 1
        assert "never fsynced" in out[0].message
        assert "torn" in out[0].message

    def test_unpublished_staging_file_flagged(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def f(path):
                tmp = path + ".tmp"
                with fsutil.open_durable(tmp, "wb") as f:
                    f.write(b"x")
                    fsutil.fsync_file(f)
                fsutil.fsync_dir(".")
        """, rules=("durability-protocol",))
        assert len(out) == 1
        assert "never" in out[0].message and "published" in out[0].message

    def test_missing_dir_fsync_flagged(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def f(a, b):
                fsutil.replace(a, b)
        """, rules=("durability-protocol",))
        assert len(out) == 1
        assert "no later fsutil.fsync_dir" in out[0].message

    def test_unacked_seam_append_flagged(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            class Coord:
                def ok(self, rec):
                    self._j.append(rec)
                    self._j.sync()

                def bad(self, rec):
                    self._j.append(rec)

                def flush(self):
                    self._j.sync()
        """, rules=("durability-protocol",))
        assert len(out) == 1
        assert "self._j.append" in out[0].message
        assert "not durable when the caller acks" in out[0].message

    def test_plain_list_append_is_not_a_seam(self, tmp_path):
        # .append on an attr the module never .sync()s is a list, not a
        # buffered journal — and list-method names like .remove must
        # never be read as fsutil name ops
        out = _lint(tmp_path, _DUR + """
            class Box:
                def add(self, v):
                    self._items.append(v)

                def drop(self, v):
                    self._items.remove(v)
        """, rules=("durability-protocol",))
        assert out == []

    def test_group_commit_annotation_excuses_deferred_sync(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            class Coord:
                def deferred(self, rec):
                    # durable: group-commit=flush -- every public caller flushes before its ack
                    self._j.append(rec)

                def flush(self):
                    self._j.sync()
        """, rules=("durability-protocol",))
        assert out == []

    def test_annotation_without_reason_is_a_finding(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            class Coord:
                def deferred(self, rec):
                    # durable: group-commit=flush
                    self._j.append(rec)

                def flush(self):
                    self._j.sync()
        """, rules=("durability-protocol",))
        msgs = " ".join(f.message for f in out)
        assert "without a justification" in msgs
        # and the unexcused append is still reported
        assert "not durable when the caller acks" in msgs

    def test_annotation_naming_barrierless_method_is_a_finding(
            self, tmp_path):
        # the static half of the mutation gate: delete the fsync out of
        # the promised method and the annotation itself turns red
        out = _lint(tmp_path, _DUR + """
            def rotate(old, new):
                # durable: dir-fsync=commit -- commit fsyncs the dir before any ack
                fsutil.rename(old, new)

            def commit():
                pass
        """, rules=("durability-protocol",))
        msgs = " ".join(f.message for f in out)
        assert "does not contain the promised barrier" in msgs

    def test_dir_fsync_annotation_excuses_deferred_barrier(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            def rotate(old, new):
                # durable: dir-fsync=commit -- commit fsyncs the dir before any ack
                fsutil.rename(old, new)

            def commit():
                fsutil.fsync_dir(".")
        """, rules=("durability-protocol",))
        assert out == []

    def test_suppression_with_reason_accepted(self, tmp_path):
        out = _lint(tmp_path, _DUR + """
            import os

            def f(a, b):
                # flowlint: disable=durability-protocol -- migration shim, deleted with r22
                os.replace(a, b)
        """, rules=("durability-protocol",))
        assert out == []

    def test_repo_durable_modules_are_marked(self):
        """Every module that owns crash-critical state must stay opted
        in — deleting a marker would silently de-fang the rule exactly
        where it matters (same contract as the net-checked list)."""
        from tools.flowlint.core import load_files

        rels = ["flow_pipeline_tpu/mesh/journal.py",
                "flow_pipeline_tpu/mesh/coordinator.py",
                "flow_pipeline_tpu/sink/resilient.py",
                "flow_pipeline_tpu/history/archive.py",
                "flow_pipeline_tpu/engine/checkpoint.py",
                "flow_pipeline_tpu/utils/fsutil.py"]
        for sf in load_files(REPO, rels):
            assert "durable-checked" in sf.markers, sf.rel


class TestDurabilityMutationGate:
    """The static half of the two-prong durability mutation gate:
    deleting any single load-bearing fsync / dir-fsync / replace from a
    durable surface must produce a durability-protocol finding when the
    mutated module is linted standalone. (The dynamic half lives in
    tests/test_crashpoints.py::TestBarrierMutations, where the same
    deletions — via fsutil.suppressed — surface as crash-state
    invariant violations.)"""

    # (repo-relative module, line regex, 0-based occurrence). Barrier
    # lines NOT listed are excluded deliberately:
    # - journal.py compact's fsync of the OLD handle (occurrence 2 of
    #   fsync_file(self._f)) protects only never-acked buffered appends
    #   — not load-bearing for acked data;
    # - archive.py's rotation-time fsync of the outgoing segment
    #   (occurrence 0 of fsync_file(self._fh)) is an interprocedural
    #   barrier the lexical rule cannot see; the crash-point checker
    #   covers it (the archive scenario commits across a rotation);
    # - coordinator.py syncs other than fence/submit are per-caller
    #   copies of the annotated group-commit seam (deleting one leaves
    #   other callers' barriers intact — redundancy, not protocol).
    MUTATIONS = [
        ("flow_pipeline_tpu/mesh/journal.py",
         r"fsutil\.fsync_file\(self\._f\)", 0),
        ("flow_pipeline_tpu/mesh/journal.py",
         r"fsutil\.fsync_file\(self\._f\)", 1),
        ("flow_pipeline_tpu/mesh/journal.py",
         r"fsutil\.fsync_file\(f\)", 0),
        ("flow_pipeline_tpu/mesh/journal.py",
         r"fsutil\.fsync_dir\(dir_\)", 0),
        ("flow_pipeline_tpu/mesh/journal.py",
         r"fsutil\.fsync_dir\(self\.dir\)", 0),
        ("flow_pipeline_tpu/mesh/journal.py",
         r"fsutil\.replace\(tmp, self\.path\)", 0),
        ("flow_pipeline_tpu/history/archive.py",
         r"fsutil\.fsync_file\(self\._fh\)", 1),
        ("flow_pipeline_tpu/history/archive.py",
         r"fsutil\.fsync_dir\(self\.dir\)", 0),
        ("flow_pipeline_tpu/history/archive.py",
         r"fsutil\.fsync_dir\(self\.dir\)", 1),
        ("flow_pipeline_tpu/engine/checkpoint.py",
         r"fsutil\.fsync_dir\(parent\)", 0),
        ("flow_pipeline_tpu/mesh/coordinator.py",
         r"self\._journal\.sync\(\)", 3),   # fence()'s ack barrier
        ("flow_pipeline_tpu/mesh/coordinator.py",
         r"self\._journal\.sync\(\)", 5),   # submit()'s ack barrier
        # the dead-letter spill is one write_bytes_durable call; its
        # three barriers live in fsutil's own protocol sentence
        ("flow_pipeline_tpu/utils/fsutil.py",
         r"^        fsync_file\(f\)", 0),
        ("flow_pipeline_tpu/utils/fsutil.py",
         r"^    replace\(tmp, path\)", 0),
        ("flow_pipeline_tpu/utils/fsutil.py",
         r"^    fsync_dir\(os\.path", 0),
    ]

    @staticmethod
    def _mutate(src: str, pattern: str, occurrence: int) -> str:
        import re
        lines = src.splitlines(keepends=True)
        hits = [i for i, ln in enumerate(lines) if re.search(pattern, ln)]
        assert len(hits) > occurrence, \
            f"{pattern!r}: {len(hits)} hit(s), wanted > {occurrence} — " \
            f"the mutation list is stale against the source"
        i = hits[occurrence]
        indent = lines[i][:len(lines[i]) - len(lines[i].lstrip())]
        lines[i] = indent + "pass  # mutated\n"
        return "".join(lines)

    def test_unmutated_modules_lint_clean_standalone(self, tmp_path):
        for rel in sorted({rel for rel, _p, _o in self.MUTATIONS}):
            with open(os.path.join(REPO, rel)) as fh:
                src = fh.read()
            dst = tmp_path / "base" / rel
            dst.parent.mkdir(parents=True, exist_ok=True)
            dst.write_text(src)
            out = run_lint(str(tmp_path / "base"), [rel],
                           rules=("durability-protocol",))
            assert out == [], (rel, [f.render() for f in out])

    def test_every_dropped_barrier_is_a_finding(self, tmp_path):
        for n, (rel, pattern, occ) in enumerate(self.MUTATIONS):
            with open(os.path.join(REPO, rel)) as fh:
                src = fh.read()
            root = tmp_path / str(n)
            dst = root / rel
            dst.parent.mkdir(parents=True, exist_ok=True)
            dst.write_text(self._mutate(src, pattern, occ))
            out = run_lint(str(root), [rel],
                           rules=("durability-protocol",))
            dur = [f for f in out if f.rule == "durability-protocol"]
            assert dur, (
                f"deleting {pattern!r} occurrence {occ} from {rel} "
                f"produced no durability-protocol finding — the static "
                f"mutation gate lost its teeth")


class TestAnnotateRobustness:
    def test_output_byte_identical_across_runs(self, tmp_path, capsys):
        import json

        from tools.flowlint import annotate
        from tools.flowlint.runner import main

        (tmp_path / "fix.py").write_text(textwrap.dedent("""
            # flowlint: uint64-exact
            import numpy as np

            def f():
                a = np.zeros(3)
                b = np.int64(1)
                return a, b
        """))
        rc = main(["--root", str(tmp_path), "--json", "fix.py"])
        assert rc == 1
        json_path = tmp_path / "findings.json"
        json_path.write_text(capsys.readouterr().out)
        assert annotate.main([str(json_path)]) == 0
        first = capsys.readouterr().out
        assert annotate.main([str(json_path)]) == 0
        second = capsys.readouterr().out
        assert first == second
        assert first.encode() == second.encode()

    def test_missing_keys_degrade_gracefully(self):
        # a hand-built or version-skewed document must never crash the
        # presenter — CI would lose the real findings behind a KeyError
        from tools.flowlint import annotate

        lines = annotate.annotations({"findings": [{}]})
        assert lines[0].startswith("::error file=<unknown>,line=1,")
        assert lines[-1] == "flowlint: 1 finding(s)"

    def test_count_falls_back_to_findings_length(self):
        from tools.flowlint import annotate

        lines = annotate.annotations(
            {"findings": [{"file": "a.py", "line": 3, "rule": "r",
                           "message": "m"}]})
        assert lines[-1] == "flowlint: 1 finding(s)"


class TestLintWallClock:
    def test_full_repo_run_within_budget(self):
        """make lint is a pre-commit gate: a rule that regresses the
        full-scope run past interactive latency is a bug even when its
        findings are right (observed ~3s on CI-class hardware; the
        ceiling leaves 20x headroom before failing)."""
        import time

        t0 = time.monotonic()
        run_lint(REPO)
        assert time.monotonic() - t0 < 60.0


class TestRepoRegression:
    def test_repo_lints_clean(self):
        findings = run_lint(REPO)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_repo_has_jit_roots_covered(self):
        # the purity rule must actually be traversing this codebase: the
        # fused engine step and the hh update are jit roots, so a planted
        # impurity in models/ must be reachable (guards against the rule
        # silently finding zero roots after a refactor)
        import ast

        from tools.flowlint import rules_purity
        from tools.flowlint.core import discover, load_files

        files = load_files(REPO, discover(REPO, ("flow_pipeline_tpu",)))
        n_roots = 0
        for sf in files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.FunctionDef) \
                        and rules_purity._decorated_jit(node):
                    n_roots += 1
                elif isinstance(node, ast.Call) \
                        and rules_purity._wrapper_kind(node):
                    n_roots += 1
        assert n_roots >= 10, n_roots
