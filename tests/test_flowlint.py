"""flowlint rule tests: each rule against known-good / known-bad fixture
snippets, plus the regression gate that the repo itself lints clean
(what `make lint` / CI enforce)."""

# flowlint: skip-file
# (the fixture strings below deliberately contain findings)

import os
import sys
import textwrap

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

from tools.flowlint.runner import run_lint  # noqa: E402


def _lint(tmp_path, source: str, name: str = "fix.py", rules=None):
    (tmp_path / name).write_text(textwrap.dedent(source))
    return run_lint(str(tmp_path), [name], rules)


def _rules(findings):
    return [f.rule for f in findings]


class TestJitPurity:
    def test_direct_impurity_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            import time, jax

            @jax.jit
            def step(x):
                print("tracing")
                return x + time.time()
        """)
        msgs = [f.message for f in out]
        assert any("print" in m for m in msgs)
        assert any("time.time" in m for m in msgs)

    def test_transitive_reachability(self, tmp_path):
        out = _lint(tmp_path, """
            import jax

            def helper(y):
                import random
                return random.random() + y

            @jax.jit
            def step(y):
                return helper(y)
        """)
        assert any("random.random" in f.message for f in out)

    def test_partial_decorator_and_shard_map_forms(self, tmp_path):
        out = _lint(tmp_path, """
            import jax
            from functools import partial
            from jax.experimental.shard_map import shard_map

            @partial(jax.jit, static_argnames=("k",))
            def step(x, *, k):
                open("/tmp/x")
                return x

            def per_chip(x):
                import time
                return x + time.time()

            fn = jax.jit(shard_map(per_chip, mesh=None, in_specs=None,
                                   out_specs=None))
        """)
        msgs = " ".join(f.message for f in out)
        assert "open" in msgs and "time.time" in msgs

    def test_metric_mutation_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            import jax
            from flow_pipeline_tpu.obs import REGISTRY

            m = REGISTRY.counter("c", "help")

            @jax.jit
            def step(x):
                m.inc()
                return x
        """)
        assert any(".inc" in f.message for f in out)

    def test_global_write_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            import jax
            _CACHE = None

            @jax.jit
            def step(x):
                global _CACHE
                _CACHE = x
                return x
        """)
        assert any("module-global write" in f.message for f in out)

    def test_pure_jit_and_host_side_effects_clean(self, tmp_path):
        out = _lint(tmp_path, """
            import jax
            import jax.numpy as jnp
            from flow_pipeline_tpu.obs import REGISTRY

            m = REGISTRY.counter("c", "help")

            @jax.jit
            def step(x):
                return jnp.sum(x) * 2

            def host_loop(x):
                m.inc()          # fine: NOT reachable from a jit body
                print("host")
                return step(x)
        """)
        assert _rules(out) == []


class TestUint64Discipline:
    def test_unmarked_module_not_checked(self, tmp_path):
        out = _lint(tmp_path, """
            import numpy as np
            def f(x):
                return x.astype(np.int64) + np.array([1])
        """)
        assert _rules(out) == []

    def test_marked_module_flags_casts_and_dtypeless(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np
            import jax.numpy as jnp

            def f(x):
                a = x.astype(np.int64)
                b = jnp.asarray(x).astype(jnp.int32)
                c = np.array([1, 2])
                d = np.zeros(4)
                e = np.int64(7) + x
                ok = np.asarray(x)            # dtype-preserving: allowed
                ok2 = np.zeros(4, np.uint64)  # explicit dtype: allowed
                return a, b, c, d, e, ok, ok2
        """)
        assert _rules(out) == ["uint64-discipline"] * 5

    def test_suppression_with_reason(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(x):
                # flowlint: disable=uint64-discipline -- indices < 2^31, not counters
                return x.astype(np.int32)
        """)
        assert _rules(out) == []

    def test_suppression_without_reason_is_a_finding(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(x):
                return x.astype(np.int32)  # flowlint: disable=uint64-discipline
        """)
        assert "suppression" in _rules(out)

    def test_trailing_suppression_does_not_mask_next_line(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: uint64-exact
            import numpy as np

            def f(x):
                a = x.astype(np.int32)  # flowlint: disable=uint64-discipline -- bounded
                b = x.astype(np.int64)
                return a, b
        """)
        assert _rules(out) == ["uint64-discipline"]  # only line b


class TestLockDiscipline:
    def test_guarded_write_enforced(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def good(self):
                    with self._lock:
                        self._n += 1

                def bad(self):
                    self._n += 1
        """)
        assert _rules(out) == ["lock-discipline"]
        assert "outside" in out[0].message

    def test_undeclared_attribute_flagged(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            class Box:
                def __init__(self):
                    self._m = 0

                def touch(self):
                    self._m = 5
        """)
        assert any("undeclared attribute" in f.message for f in out)

    def test_tuple_unpack_write_seen(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._cv = threading.Condition()
                    self._err = None  # guarded-by: _cv

                def take(self):
                    err, self._err = self._err, None
                    return err
        """)
        assert _rules(out) == ["lock-discipline"]

    def test_blocking_call_under_lock(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading, time

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def slow(self):
                    with self._lock:
                        self._n += 1
                        time.sleep(1)
        """)
        assert any("blocking" in f.message for f in out)

    def test_cv_wait_on_held_lock_allowed(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._cv = threading.Condition()
                    self._n = 0  # guarded-by: _cv

                def drain(self):
                    with self._cv:
                        self._cv.wait_for(lambda: self._n == 0, 5)
        """)
        assert _rules(out) == []

    def test_module_global_guard(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            _LOCK = threading.Lock()
            _POOL = None  # guarded-by: _LOCK

            def good():
                global _POOL
                with _LOCK:
                    if _POOL is None:
                        _POOL = object()
                return _POOL

            def bad():
                global _POOL
                _POOL = None
        """)
        assert _rules(out) == ["lock-discipline"]
        assert "_POOL" in out[0].message


class TestLockRuleExprScan:
    def test_no_duplicate_findings_in_nested_statements(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading, time

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: _lock

                def slow(self):
                    with self._lock:
                        if self._n > 0:
                            time.sleep(1)
        """)
        assert len([f for f in out if "blocking" in f.message]) == 1

    def test_nested_cv_wait_under_outer_lock_allowed(self, tmp_path):
        out = _lint(tmp_path, """
            # flowlint: lock-checked
            import threading

            class Box:
                def __init__(self):
                    # flowlint: unguarded -- the lock itself
                    self._lock = threading.Lock()
                    # flowlint: unguarded -- the lock itself
                    self._cv = threading.Condition()
                    self._n = 0  # guarded-by: _cv

                def drain(self):
                    with self._lock:
                        with self._cv:
                            self._cv.wait_for(lambda: self._n == 0, 5)
        """)
        assert _rules(out) == []


class TestSuppressionHygiene:
    def test_unknown_rule_in_disable_reported(self, tmp_path):
        out = _lint(tmp_path, """
            def f():
                # flowlint: disable=lock-dicipline -- typo'd rule name
                return 1
        """)
        assert any("unknown rule" in f.message for f in out)

    def test_unused_suppression_reported_on_full_run(self, tmp_path):
        out = _lint(tmp_path, """
            def f():
                # flowlint: disable=jit-purity -- nothing here triggers it
                return 1
        """)
        assert any("no longer matches" in f.message for f in out)

    def test_unused_not_reported_when_rules_narrowed(self, tmp_path):
        out = _lint(tmp_path, """
            def f():
                # flowlint: disable=jit-purity -- nothing here triggers it
                return 1
        """, rules=("uint64-discipline",))
        assert _rules(out) == []


class TestNativeLoaderOverride:
    def test_missing_override_raises_every_call(self, monkeypatch):
        import importlib

        import flow_pipeline_tpu.native as native

        monkeypatch.setenv("FLOWDECODE_LIB", "/nonexistent/libx.so")
        importlib.reload(native)
        import pytest as _pytest
        with _pytest.raises(RuntimeError, match="FLOWDECODE_LIB"):
            native.available()
        # the strict override must NOT latch: a caller that swallowed the
        # first error must not silently get the no-native fallback
        with _pytest.raises(RuntimeError, match="FLOWDECODE_LIB"):
            native.available()
        monkeypatch.delenv("FLOWDECODE_LIB")
        importlib.reload(native)  # restore normal loader state


class TestFlagRegistry:
    def _write_registry(self, tmp_path, names):
        util = tmp_path / "utils"
        util.mkdir()
        (util / "flags.py").write_text(
            "KNOWN_FLAGS = frozenset({" +
            ", ".join(repr(n) for n in names) + "})\n")
        return "utils/flags.py"

    def test_undeclared_token_and_declaration(self, tmp_path):
        reg = self._write_registry(tmp_path, ["kafka.topic"])
        (tmp_path / "README.md").write_text("uses -kafka.topic\n")
        (tmp_path / "app.py").write_text(textwrap.dedent("""
            def build(fs):
                fs.string("kafka.topic", "flows", "topic")
                fs.string("kafka.brokerz", "x", "typo'd declaration")
                argv = ["-kafka.topic", "t", "-no.such.flag=1"]
                return argv
        """))
        out = run_lint(str(tmp_path), [reg, "app.py"])
        msgs = " ".join(f.message for f in out)
        assert "kafka.brokerz" in msgs
        assert "-no.such.flag=1" in msgs
        assert "kafka.topic" not in " ".join(
            m for m in msgs.splitlines() if "not mentioned" in m)

    def test_undocumented_flag_flagged(self, tmp_path):
        reg = self._write_registry(tmp_path, ["secret.knob"])
        (tmp_path / "README.md").write_text("no flags here\n")
        out = run_lint(str(tmp_path), [reg])
        assert any("secret.knob" in f.message and "not mentioned" in f.message
                   for f in out)


class TestRepoRegression:
    def test_repo_lints_clean(self):
        findings = run_lint(REPO)
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_repo_has_jit_roots_covered(self):
        # the purity rule must actually be traversing this codebase: the
        # fused engine step and the hh update are jit roots, so a planted
        # impurity in models/ must be reachable (guards against the rule
        # silently finding zero roots after a refactor)
        import ast

        from tools.flowlint import rules_purity
        from tools.flowlint.core import discover, load_files

        files = load_files(REPO, discover(REPO, ("flow_pipeline_tpu",)))
        n_roots = 0
        for sf in files:
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.FunctionDef) \
                        and rules_purity._decorated_jit(node):
                    n_roots += 1
                elif isinstance(node, ast.Call) \
                        and rules_purity._wrapper_kind(node):
                    n_roots += 1
        assert n_roots >= 10, n_roots
