"""Dotted-flag parser + metrics/observability tests."""

import urllib.request

import pytest

from flow_pipeline_tpu.obs import MetricsRegistry, MetricsServer
from flow_pipeline_tpu.utils.flags import FlagSet


class TestFlags:
    def make(self):
        fs = FlagSet("test")
        fs.string("kafka.brokers", "127.0.0.1:9092", "brokers")
        fs.integer("flush.count", 100, "count")
        fs.number("flush.dur", 5.0, "dur")
        fs.boolean("proto.fixedlen", False, "fixedlen")
        fs.string("postgres.pass", "", "password", env="POSTGRES_PASSWORD")
        return fs

    def test_defaults(self):
        vals = self.make().parse([])
        assert vals["flush.count"] == 100
        assert vals["proto.fixedlen"] is False

    def test_space_and_equals_forms(self):
        vals = self.make().parse(
            ["-kafka.brokers", "k:9092", "-flush.count=7", "-proto.fixedlen"]
        )
        assert vals["kafka.brokers"] == "k:9092"
        assert vals["flush.count"] == 7
        assert vals["proto.fixedlen"] is True

    def test_bool_explicit_false(self):
        vals = self.make().parse(["-proto.fixedlen=false"])
        assert vals["proto.fixedlen"] is False

    def test_double_dash_accepted(self):
        vals = self.make().parse(["--flush.count", "3"])
        assert vals["flush.count"] == 3

    def test_unknown_flag_names_itself(self):
        with pytest.raises(ValueError, match="flag provided but not defined: -nope"):
            self.make().parse(["-nope", "1"])

    def test_missing_value(self):
        with pytest.raises(ValueError, match="needs a value"):
            self.make().parse(["-kafka.brokers"])

    def test_bad_int(self):
        with pytest.raises(ValueError, match="invalid value for -flush.count"):
            self.make().parse(["-flush.count", "abc"])

    def test_env_fallback(self, monkeypatch):
        monkeypatch.setenv("POSTGRES_PASSWORD", "sekret")
        vals = self.make().parse([])
        assert vals["postgres.pass"] == "sekret"
        # explicit flag beats env (reference precedence,
        # ref: inserter/inserter.go:220-224)
        vals = self.make().parse(["-postgres.pass", "flag"])
        assert vals["postgres.pass"] == "flag"

    def test_help_exits_zero(self, capsys):
        with pytest.raises(SystemExit) as e:
            self.make().parse(["-help"])
        assert e.value.code == 0
        assert "kafka.brokers" in capsys.readouterr().out


class TestMetrics:
    def test_counter_inc_and_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("requests_total", "reqs")
        c.inc()
        c.inc(2, path="/metrics")
        assert c.value() == 1
        assert c.value(path="/metrics") == 2
        text = reg.render()
        assert "# TYPE requests_total counter" in text
        assert 'requests_total{path="/metrics"} 2.0' in text

    def test_gauge_set(self):
        reg = MetricsRegistry()
        g = reg.gauge("lag", "lag")
        g.set(42)
        assert "lag 42" in reg.render()
        assert "# TYPE lag gauge" in reg.render()

    def test_gauge_help_mentioning_counter_unmangled(self):
        # regression: naive str.replace corrupted HELP text containing the
        # word "counter" instead of the TYPE line
        reg = MetricsRegistry()
        reg.gauge("queue_depth", "items behind the counter").set(1)
        text = reg.render()
        assert "# HELP queue_depth items behind the counter" in text
        assert "# TYPE queue_depth gauge" in text

    def test_summary_quantiles(self):
        reg = MetricsRegistry()
        s = reg.summary("latency_us", "lat")
        for v in range(100):
            s.observe(float(v))
        assert 45 <= s.quantile(0.5) <= 55
        text = reg.render()
        assert "latency_us_count 100" in text

    def test_summary_labels(self):
        """Labeled summaries: per-label-set windows/quantiles render as
        their own series (the per-router delay panels), while _sum/_count
        keep aggregating across labels (bench.py's stage budget reads
        them)."""
        reg = MetricsRegistry()
        s = reg.summary("delay_s", "d")
        for v in (1.0, 3.0):
            s.observe(v, router="a")
        s.observe(100.0, router="b")
        assert s.quantile(0.99, router="a") <= 3.0
        assert s.quantile(0.5, router="b") == 100.0
        assert s.quantile(0.5) == 0.0  # unlabeled series: no observations
        assert s._sum == 104.0 and s._count == 3
        text = reg.render()
        assert 'delay_s{quantile="0.5",router="a"}' in text
        assert 'delay_s_count{router="b"} 1' in text

    def test_summary_label_cardinality_capped(self):
        """Label values can come from spoofable exporter addresses; past
        the cap, unseen label sets fold into _other instead of pinning a
        fresh sample window each (collector OOM guard)."""
        reg = MetricsRegistry()
        s = reg.summary("d_us", "d", max_label_sets=4)
        for i in range(50):
            s.observe(1.0, router=f"10.0.0.{i}")
        assert len(s._obs) <= 5  # 4 real sets + the _other overflow
        assert s._counts[(("router", "_other"),)] == 46
        assert s._count == 50  # totals still see every observation
        assert 'router="_other"' in s.render()

    def test_same_name_same_metric(self):
        reg = MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_http_endpoint(self):
        reg = MetricsRegistry()
        reg.counter("flows_processed_total", "n").inc(7)
        server = MetricsServer(0, registry=reg).start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics"
            ).read().decode()
            assert "flows_processed_total 7.0" in body
            # unknown path -> 404
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://127.0.0.1:{server.port}/nope")
        finally:
            server.stop()


class TestTracing:
    def test_stage_timer_exports_summary_family(self):
        from flow_pipeline_tpu.obs import REGISTRY
        from flow_pipeline_tpu.obs.tracing import StageTimer

        t = StageTimer()
        with t.stage("decoding"):
            pass
        rendered = REGISTRY.render()
        assert "flow_summary_decoding_time_us" in rendered

    def test_worker_observes_stage_metrics(self):
        from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
        from flow_pipeline_tpu.gen import FlowGenerator, MockerProfile
        from flow_pipeline_tpu.models import WindowAggConfig, WindowAggregator
        from flow_pipeline_tpu.sink import MemorySink
        from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer

        bus = InProcessBus()
        bus.create_topic("flows", 1)
        g = FlowGenerator(MockerProfile(), seed=3, t0=1_699_999_800, rate=20.0)
        Producer(bus, fixedlen=True).send_many(g.batch(1000).to_messages())
        worker = StreamWorker(
            Consumer(bus, fixedlen=True),
            {"flows_5m": WindowAggregator(WindowAggConfig(batch_size=512))},
            [MemorySink()],
            WorkerConfig(poll_max=512),
        )
        worker.run(stop_when_idle=True)
        assert worker.stages._summaries["processing"]._count > 0
        assert worker.stages._summaries["flushing"]._count > 0

    def test_device_trace_writes_profile(self, tmp_path):
        import jax.numpy as jnp

        from flow_pipeline_tpu.obs.tracing import device_trace

        logdir = str(tmp_path / "trace")
        with device_trace(logdir):
            jnp.ones(8).sum().block_until_ready()
        import glob
        import os

        assert glob.glob(os.path.join(logdir, "**", "*.pb"),
                         recursive=True) or glob.glob(
            os.path.join(logdir, "**", "*.trace.json.gz"), recursive=True)
