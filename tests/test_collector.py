"""Collector tests: hand-crafted NetFlow v5/v9/IPFIX and sFlow v5 datagrams
through the decoders, the template cache lifecycle, the GoFlow-shaped metric
surface, and a live UDP end-to-end path."""

import socket
import struct
import time

import pytest

from flow_pipeline_tpu.collector import (
    CollectorConfig,
    CollectorServer,
    TemplateCache,
    decode_netflow,
    decode_sflow,
)
from flow_pipeline_tpu.schema.message import FlowType
from flow_pipeline_tpu.transport import InProcessBus, Producer

NOW = 1_700_000_000


def v5_datagram(n=2, sampling=100):
    header = struct.pack(">HHIIIIBBH", 5, n, 3_600_000, NOW, 0, 42, 0, 0,
                         sampling)
    recs = b""
    for i in range(n):
        recs += struct.pack(
            ">4s4s4sHHIIIIHHBBBBHHBBH",
            bytes([10, 0, 0, i + 1]), bytes([192, 168, 1, i + 1]),
            bytes(4), 1, 2,
            10 + i, 1000 + i,             # packets, octets
            3_590_000, 3_599_000,         # first/last sysuptime ms
            1234, 443, 0, 0x18, 6, 0,     # ports, pad, tcpflags, proto, tos
            65001, 65002, 24, 24, 0,
        )
    return header + recs


def v9_template_and_data():
    # template 256: IPV4_SRC(8,4), IPV4_DST(12,4), IN_BYTES(1,4),
    # IN_PKTS(2,4), PROTOCOL(4,1), L4_SRC(7,2), L4_DST(11,2), SRC_AS(16,2)
    fields = [(8, 4), (12, 4), (1, 4), (2, 4), (4, 1), (7, 2), (11, 2),
              (16, 2)]
    tmpl_body = struct.pack(">HH", 256, len(fields))
    for t, l in fields:
        tmpl_body += struct.pack(">HH", t, l)
    tmpl_set = struct.pack(">HH", 0, 4 + len(tmpl_body)) + tmpl_body
    rec = (bytes([10, 1, 1, 1]) + bytes([10, 2, 2, 2])
           + struct.pack(">II", 5000, 7) + bytes([17])
           + struct.pack(">HH", 53, 5353) + struct.pack(">H", 64512))
    data_set = struct.pack(">HH", 256, 4 + len(rec)) + rec
    body = tmpl_set + data_set
    header = struct.pack(">HHIIII", 9, 2, 1_000_000, NOW, 7, 1)
    return header + body


def ipfix_datagram():
    fields = [(8, 4), (12, 4), (1, 4), (2, 4), (4, 1), (150, 4), (151, 4)]
    tmpl_body = struct.pack(">HH", 300, len(fields))
    for t, l in fields:
        tmpl_body += struct.pack(">HH", t, l)
    tmpl_set = struct.pack(">HH", 2, 4 + len(tmpl_body)) + tmpl_body
    rec = (bytes([172, 16, 0, 9]) + bytes([172, 16, 0, 10])
           + struct.pack(">II", 900, 3) + bytes([6])
           + struct.pack(">II", NOW - 10, NOW - 1))
    data_set = struct.pack(">HH", 300, 4 + len(rec)) + rec
    total = 16 + len(tmpl_set) + len(data_set)
    header = struct.pack(">HHIII", 10, total, NOW, 99, 5)
    return header + tmpl_set + data_set


def eth_ipv4_tcp_packet():
    eth = bytes(6) + bytes(6) + struct.pack(">H", 0x0800)
    ip = bytes([0x45, 0x10]) + struct.pack(">H", 100) + bytes(4)
    ip += bytes([62, 6]) + bytes(2)  # ttl, proto tcp, checksum
    ip += bytes([10, 9, 8, 7]) + bytes([10, 6, 5, 4])
    tcp = struct.pack(">HH", 55555, 443) + bytes(9) + bytes([0x12]) + bytes(2)
    return eth + ip + tcp


def sflow_datagram(rate=512):
    pkt = eth_ipv4_tcp_packet()
    raw = struct.pack(">IIII", 1, 1500, 4, len(pkt)) + pkt
    rec = struct.pack(">II", 1, len(raw)) + raw
    sample_body = struct.pack(">IIIIIIII", 1, 1, rate, 1000, 0, 5, 6, 1) + rec
    sample = struct.pack(">II", 1, len(sample_body)) + sample_body
    header = struct.pack(">II", 5, 1) + bytes([192, 0, 2, 1])
    header += struct.pack(">IIII", 0, 77, 123456, 1)
    return header + sample


class TestNetFlowV5:
    def test_decode_fields(self):
        msgs = decode_netflow(v5_datagram(), TemplateCache())
        assert len(msgs) == 2
        m = msgs[0]
        assert m.type == FlowType.NETFLOW_V5
        assert m.src_addr == b"\x00" * 12 + bytes([10, 0, 0, 1])
        assert m.bytes == 1000 and m.packets == 10
        assert (m.proto, m.src_port, m.dst_port) == (6, 1234, 443)
        assert (m.src_as, m.dst_as) == (65001, 65002)
        assert m.sampling_rate == 100
        assert m.time_received == NOW
        # first/last anchored to export clock: 10s and 1s before export
        assert m.time_flow_start == NOW - 10
        assert m.time_flow_end == NOW - 1
        assert m.etype == 0x0800

    def test_truncated_raises(self):
        with pytest.raises(ValueError):
            decode_netflow(v5_datagram()[:-10], TemplateCache())


class TestNetFlowV9:
    def test_template_then_data(self):
        cache = TemplateCache()
        msgs = decode_netflow(v9_template_and_data(), cache, source="r1")
        assert len(cache) == 1
        assert len(msgs) == 1
        m = msgs[0]
        assert m.type == FlowType.NETFLOW_V9
        assert m.src_addr.endswith(bytes([10, 1, 1, 1]))
        assert m.bytes == 5000 and m.packets == 7
        assert m.proto == 17 and m.src_port == 53
        assert m.src_as == 64512

    def test_data_before_template_skipped(self):
        cache = TemplateCache()
        datagram = v9_template_and_data()
        # strip the template set (first 4+36=40 bytes after the 20B header)
        header, tmpl_and_data = datagram[:20], datagram[20:]
        tmpl_len = struct.unpack_from(">HH", tmpl_and_data, 0)[1]
        data_only = header[:2] + struct.pack(">H", 1) + header[4:]
        data_only += tmpl_and_data[tmpl_len:]
        msgs = decode_netflow(data_only, cache, source="r1")
        assert msgs == []
        assert cache.missing == 1
        # once the template arrives, the same data decodes
        assert len(decode_netflow(datagram, cache, source="r1")) == 1

    def test_templates_per_source(self):
        cache = TemplateCache()
        decode_netflow(v9_template_and_data(), cache, source="r1")
        # same template id from a different source is unknown
        datagram = v9_template_and_data()
        header, rest = datagram[:20], datagram[20:]
        tmpl_len = struct.unpack_from(">HH", rest, 0)[1]
        data_only = header + rest[tmpl_len:]
        assert decode_netflow(data_only, cache, source="r2") == []


def v9_options_sampling(rate=2048):
    """Options template (set 1) announcing SAMPLING_INTERVAL, then its
    option-data record, then a flow data record WITHOUT inline sampling."""
    # options template 512: scope (1 field, 4B) + option SAMPLING_INTERVAL(34, 4B)
    otmpl = struct.pack(">HHH", 512, 4, 4)  # tid, scope_len, opt_len
    otmpl += struct.pack(">HH", 1, 4)  # scope field: System(1), 4 bytes
    otmpl += struct.pack(">HH", 34, 4)  # SAMPLING_INTERVAL, 4 bytes
    oset = struct.pack(">HH", 1, 4 + len(otmpl)) + otmpl
    odata_rec = struct.pack(">I", 0) + struct.pack(">I", rate)
    odata = struct.pack(">HH", 512, 4 + len(odata_rec)) + odata_rec
    # regular template 300 without sampling field + one data record
    fields = [(8, 4), (12, 4), (1, 4), (2, 4)]
    tmpl = struct.pack(">HH", 300, len(fields))
    for t, l in fields:
        tmpl += struct.pack(">HH", t, l)
    tset = struct.pack(">HH", 0, 4 + len(tmpl)) + tmpl
    rec = bytes([10, 0, 0, 1]) + bytes([10, 0, 0, 2]) + struct.pack(">II", 500, 2)
    dset = struct.pack(">HH", 300, 4 + len(rec)) + rec
    body = oset + odata + tset + dset
    return struct.pack(">HHIIII", 9, 4, 0, NOW, 1, 9) + body


class TestOptionsSampling:
    def test_exporter_sampling_applied(self):
        cache = TemplateCache()
        msgs = decode_netflow(v9_options_sampling(rate=2048), cache, "r9")
        assert len(msgs) == 1
        assert msgs[0].sampling_rate == 2048
        assert cache.exporter_sampling("r9", 9) == 2048

    def test_sampling_persists_across_datagrams(self):
        cache = TemplateCache()
        decode_netflow(v9_options_sampling(rate=512), cache, "r9")
        # later datagram: only template + data (no options sets)
        datagram = v9_template_and_data()
        msgs = decode_netflow(datagram, cache, "r9")
        # source_id differs (1 vs 9) -> different exporter, rate NOT applied
        assert msgs[0].sampling_rate == 1
        # same exporter id as the options announcement -> applied
        header = struct.pack(">HHIIII", 9, 2, 1_000_000, NOW, 7, 9)
        msgs = decode_netflow(header + datagram[20:], cache, "r9")
        assert msgs[0].sampling_rate == 512

    def test_inline_sampling_of_one_not_overridden(self):
        # explicit inline SAMPLING_INTERVAL=1 (unsampled flows from an
        # otherwise-sampling exporter) must NOT inherit the exporter rate
        cache = TemplateCache()
        decode_netflow(v9_options_sampling(rate=4096), cache, "r9")
        fields = [(1, 4), (34, 4)]
        tmpl = struct.pack(">HH", 302, len(fields))
        for t, l in fields:
            tmpl += struct.pack(">HH", t, l)
        tset = struct.pack(">HH", 0, 4 + len(tmpl)) + tmpl
        rec = struct.pack(">II", 100, 1)  # inline sampling exactly 1
        dset = struct.pack(">HH", 302, 4 + len(rec)) + rec
        datagram = struct.pack(">HHIIII", 9, 2, 0, NOW, 2, 9) + tset + dset
        msgs = decode_netflow(datagram, cache, "r9")
        assert msgs[0].sampling_rate == 1

    def test_malformed_options_set_does_not_drop_flows(self):
        # options template whose byte lengths overrun its set must be
        # skipped; the datagram's flow records still decode
        cache = TemplateCache()
        bad_otmpl = struct.pack(">HHH", 513, 400, 400)  # lengths overrun
        oset = struct.pack(">HH", 1, 4 + len(bad_otmpl)) + bad_otmpl
        good = v9_template_and_data()
        datagram = good[:20] + oset + good[20:]
        msgs = decode_netflow(datagram, cache, "r1")
        assert len(msgs) == 1  # the flow survived the bad options set

    def test_corrupt_options_data_record_does_not_drop_flows(self):
        # an options-DATA record with a corrupt varlen prefix must be
        # swallowed like a malformed options template: the datagram's
        # flow records still decode
        cache = TemplateCache()
        # IPFIX options template 600: one varlen field
        otmpl = struct.pack(">HHH", 600, 1, 0) + struct.pack(">HH", 371, 0xFFFF)
        oset = struct.pack(">HH", 3, 4 + len(otmpl)) + otmpl
        # options data whose varlen prefix (200) exceeds the set's bytes
        odata = struct.pack(">HH", 600, 4 + 3) + bytes([200, 0, 0])
        # regular template + one flow record
        tmpl = struct.pack(">HH", 601, 1) + struct.pack(">HH", 1, 4)
        tset = struct.pack(">HH", 2, 4 + len(tmpl)) + tmpl
        dset = struct.pack(">HH", 601, 4 + 4) + struct.pack(">I", 4242)
        body = oset + odata + tset + dset
        header = struct.pack(">HHIII", 10, 16 + len(body), NOW, 1, 5)
        msgs = decode_netflow(header + body, cache)
        assert len(msgs) == 1 and msgs[0].bytes == 4242

    def test_v9_vendor_field_type_no_enterprise_skip(self):
        # v9 has no IPFIX enterprise encoding: type >= 0x8000 is 4 bytes of
        # spec like any other, not 8
        cache = TemplateCache()
        fields = [(0x8001, 4), (1, 4)]
        tmpl = struct.pack(">HH", 320, len(fields))
        for t, l in fields:
            tmpl += struct.pack(">HH", t, l)
        tset = struct.pack(">HH", 0, 4 + len(tmpl)) + tmpl
        rec = bytes(4) + struct.pack(">I", 777)  # vendor field, then bytes
        dset = struct.pack(">HH", 320, 4 + len(rec)) + rec
        datagram = struct.pack(">HHIIII", 9, 2, 0, NOW, 3, 1) + tset + dset
        msgs = decode_netflow(datagram, cache, "r1")
        assert len(msgs) == 1
        assert msgs[0].bytes == 777

    def test_inline_sampling_wins(self):
        cache = TemplateCache()
        decode_netflow(v9_options_sampling(rate=4096), cache, "r9")
        # template carrying inline SAMPLING_INTERVAL(34) beats exporter rate
        fields = [(1, 4), (34, 4)]
        tmpl = struct.pack(">HH", 301, len(fields))
        for t, l in fields:
            tmpl += struct.pack(">HH", t, l)
        tset = struct.pack(">HH", 0, 4 + len(tmpl)) + tmpl
        rec = struct.pack(">II", 100, 64)  # bytes, inline sampling 64
        dset = struct.pack(">HH", 301, 4 + len(rec)) + rec
        datagram = struct.pack(">HHIIII", 9, 2, 0, NOW, 2, 9) + tset + dset
        msgs = decode_netflow(datagram, cache, "r9")
        assert msgs[0].sampling_rate == 64


class TestIPFIX:
    def test_template_then_data(self):
        cache = TemplateCache()
        msgs = decode_netflow(ipfix_datagram(), cache)
        assert len(msgs) == 1
        m = msgs[0]
        assert m.type == FlowType.IPFIX
        assert m.bytes == 900 and m.packets == 3 and m.proto == 6
        assert m.time_flow_start == NOW - 10
        assert m.time_flow_end == NOW - 1


class TestIPFIXVarlen:
    """RFC 7011 §7 variable-length fields: records decode, varlen content
    (strings/opaque) is consumed and skipped, corrupt prefixes raise."""

    @staticmethod
    def varlen_datagram(payloads, long_form=False):
        # template 310: IN_BYTES(1,4), an unknown varlen field, IN_PKTS(2,4)
        fields = [(1, 4), (371, 0xFFFF), (2, 4)]
        tmpl_body = struct.pack(">HH", 310, len(fields))
        for t, l in fields:
            tmpl_body += struct.pack(">HH", t, l)
        tmpl_set = struct.pack(">HH", 2, 4 + len(tmpl_body)) + tmpl_body
        recs = b""
        for i, payload in enumerate(payloads):
            prefix = (bytes([255]) + struct.pack(">H", len(payload))
                      if long_form else bytes([len(payload)]))
            recs += struct.pack(">I", 100 + i) + prefix + payload
            recs += struct.pack(">I", 10 + i)
        data_set = struct.pack(">HH", 310, 4 + len(recs)) + recs
        total = 16 + len(tmpl_set) + len(data_set)
        header = struct.pack(">HHIII", 10, total, NOW, 1, 5)
        return header + tmpl_set + data_set

    def test_varlen_records_decode(self):
        cache = TemplateCache()
        msgs = decode_netflow(
            self.varlen_datagram([b"", b"interface-name", b"x" * 200]), cache
        )
        assert [(m.bytes, m.packets) for m in msgs] == [
            (100, 10), (101, 11), (102, 12)
        ]

    def test_varlen_long_form(self):
        cache = TemplateCache()
        msgs = decode_netflow(
            self.varlen_datagram([b"y" * 300, b"z"], long_form=True), cache
        )
        assert [(m.bytes, m.packets) for m in msgs] == [(100, 10), (101, 11)]

    def test_varlen_starved_fixed_tail_raises(self):
        # a varlen value that fits the set but leaves fewer bytes than the
        # remaining fixed fields must raise — slicing past the set end
        # would silently read the next set's bytes as field content
        fields = [(1, 4), (371, 0xFFFF), (2, 4)]
        tmpl_body = struct.pack(">HH", 311, len(fields))
        for t, l in fields:
            tmpl_body += struct.pack(">HH", t, l)
        tmpl_set = struct.pack(">HH", 2, 4 + len(tmpl_body)) + tmpl_body
        # record: IN_BYTES, varlen(payload 3), then only 2 bytes remain for
        # the 4-byte IN_PKTS
        rec = struct.pack(">I", 100) + bytes([3]) + b"abc" + b"\x00\x07"
        data_set = struct.pack(">HH", 311, 4 + len(rec)) + rec
        trailing = struct.pack(">HH", 312, 4)  # a following (empty) set
        total = 16 + len(tmpl_set) + len(data_set) + len(trailing)
        header = struct.pack(">HHIII", 10, total, NOW, 1, 5)
        with pytest.raises(ValueError):
            decode_netflow(header + tmpl_set + data_set + trailing,
                           TemplateCache())

    def test_varlen_content_overrun_raises(self):
        cache = TemplateCache()
        good = self.varlen_datagram([b"abcdef"])
        # inflate the 1-byte varlen prefix so the content overruns the set
        bad = bytearray(good)
        prefix_at = len(good) - (4 + 6 + 1)  # prefix, payload, trailing IN_PKTS
        assert bad[prefix_at] == 6
        bad[prefix_at] = 200
        with pytest.raises(ValueError):
            decode_netflow(bytes(bad), cache)


class TestSFlow:
    def test_flow_sample_with_raw_header(self):
        msgs = decode_sflow(sflow_datagram(), now=NOW)
        assert len(msgs) == 1
        m = msgs[0]
        assert m.type == FlowType.SFLOW_5
        assert m.sampling_rate == 512
        assert m.bytes == 1500 and m.packets == 1
        assert m.src_addr.endswith(bytes([10, 9, 8, 7]))
        assert m.dst_addr.endswith(bytes([10, 6, 5, 4]))
        assert (m.proto, m.src_port, m.dst_port) == (6, 55555, 443)
        assert m.tcp_flags == 0x12
        assert m.ip_ttl == 62
        assert m.etype == 0x0800
        assert m.sampler_address.endswith(bytes([192, 0, 2, 1]))
        assert (m.in_if, m.out_if) == (5, 6)

    def test_bad_version(self):
        bad = struct.pack(">II", 4, 1) + bytes(24)
        with pytest.raises(ValueError):
            decode_sflow(bad)

    def test_record_overrunning_sample_raises(self):
        # corrupt rlen pointing past the sample boundary must raise, not
        # silently mis-parse the next sample's bytes as record content
        good = sflow_datagram()
        bad = bytearray(good)
        # record header (rfmt, rlen) sits 8 bytes into the sample body,
        # which starts at 28 (header) + 8 (sample fmt+len) + 32 (body fixed)
        rlen_off = 28 + 8 + 32 + 4
        struct.pack_into(">I", bad, rlen_off, 0xFFFF)
        with pytest.raises(ValueError):
            decode_sflow(bytes(bad), now=NOW)

    def test_overstated_record_count_raises(self):
        good = sflow_datagram()
        bad = bytearray(good)
        n_rec_off = 28 + 8 + 28  # last word of the fixed sample body
        struct.pack_into(">I", bad, n_rec_off, 5)  # claims 5 records, has 1
        with pytest.raises(ValueError):
            decode_sflow(bytes(bad), now=NOW)


class TestCollectorServer:
    def make(self):
        from flow_pipeline_tpu.obs import MetricsRegistry

        bus = InProcessBus()
        bus.create_topic("flows", 1)
        producer = Producer(bus, fixedlen=True)
        server = CollectorServer(
            producer,
            CollectorConfig(netflow_addr=("127.0.0.1", 0),
                            sflow_addr=("127.0.0.1", 0)),
            registry=MetricsRegistry(),  # isolated from the global registry
        )
        return bus, producer, server

    def test_handlers_and_metrics(self):
        bus, producer, server = self.make()
        assert server.handle_netflow(v5_datagram()) == 2
        assert server.handle_sflow(sflow_datagram()) == 1
        assert server.handle_netflow(b"\x00\x63bogus") == 0  # version 99
        assert producer.produced == 3
        assert server.m_nf_records.value(router="") == 2
        assert server.m_sf_samples.value(type="FlowSample",
                                          agent="") == 1
        assert server.m_nf_errors.value(router="") == 1
        assert server.m_flow_bytes.value(type="NetFlow",
                                         remote_ip="") == 2001
        assert server.m_udp_pkts.value() == 3

    def test_per_exporter_labels(self):
        """router= (NetFlow) / agent= (sFlow) labels carry the exporter
        address, so the dashboards can break down by exporter like the
        reference perfs.json does (`by (router)` / `by (agent)`)."""
        bus, producer, server = self.make()
        server.handle_netflow(v9_template_and_data(), "10.0.0.1:2055")
        server.handle_sflow(sflow_datagram(), "10.0.0.2:6343")
        server.handle_netflow(b"\x00\x63bogus", "10.0.0.3:2055")
        assert server.m_nf_records.value(router="10.0.0.1") == 1
        assert server.m_nf_templates.value(router="10.0.0.1") == 1
        assert server.m_nf_errors.value(router="10.0.0.3") == 1
        assert server.m_sf_samples.value(type="FlowSample",
                                         agent="10.0.0.2") == 1
        # flow traffic carries the exporter as remote_ip (GoFlow parity)
        assert server.m_flow_bytes.value(type="NetFlow",
                                         remote_ip="10.0.0.1") > 0
        assert server.m_flow_bytes.value(type="sFlow",
                                         remote_ip="10.0.0.2") > 0

    def test_per_router_delay_and_decode_summaries(self):
        """The delay summary is labeled per exporter and the decode
        summary per protocol, so the dashboard's by-router delay
        quantile panels resolve against real series (test_deploy
        asserts the panel side of this contract)."""
        bus, producer, server = self.make()
        server.handle_netflow(v5_datagram(), "10.0.0.1:2055")
        server.handle_netflow(v5_datagram(), "10.0.0.9:2055")
        server.handle_sflow(sflow_datagram(), "10.0.0.2:6343")
        # per-router windows are independent; both observed something
        assert server.m_nf_delay.quantile(0.5, router="10.0.0.1") >= 0.0
        assert server.m_nf_delay._counts[(("router", "10.0.0.1"),)] == 2
        assert server.m_nf_delay._counts[(("router", "10.0.0.9"),)] == 2
        rendered = server.m_nf_delay.render()
        assert 'quantile="0.99",router="10.0.0.1"' in rendered
        assert 'flow_process_nf_delay_summary_seconds_count' \
            '{router="10.0.0.9"} 2' in rendered
        decode = server.m_decode_us.render()
        assert 'name="NetFlow"' in decode and 'name="sFlow"' in decode
        # totals still aggregate across label sets (stage-budget contract)
        assert server.m_decode_us._count == 3

    def test_struct_error_datagrams_survive(self):
        # crafted packets that trip fixed-layout unpacks (struct.error) must
        # be counted as errors, never propagate out of the handlers
        bus, producer, server = self.make()
        trunc_tmpl = (struct.pack(">HHIIII", 9, 1, 0, NOW, 0, 1)
                      + struct.pack(">HH", 0, 8) + struct.pack(">HH", 256, 10))
        assert server.handle_netflow(trunc_tmpl) == 0
        short_sflow = struct.pack(">II", 5, 2) + bytes(24)  # ipv6 agent cut
        assert server.handle_sflow(short_sflow) == 0
        lying_sample = (struct.pack(">II", 5, 1) + bytes([1, 2, 3, 4])
                        + struct.pack(">IIII", 0, 1, 1, 1)
                        + struct.pack(">II", 1, 400))  # sample len > datagram
        assert server.handle_sflow(lying_sample) == 0
        assert server.m_nf_errors.value(router="") == 1
        assert server.m_sf_errors.value(agent="") == 2  # sFlow errors separate metric
        assert producer.produced == 0

    def test_template_overrun_not_cached(self):
        # fcount larger than the flowset body must not swallow the next set
        cache = TemplateCache()
        bad_tmpl = struct.pack(">HH", 256, 6) + struct.pack(">HHHH", 8, 4, 12, 4)
        datagram = (struct.pack(">HHIIII", 9, 1, 0, NOW, 0, 1)
                    + struct.pack(">HH", 0, 4 + len(bad_tmpl)) + bad_tmpl)
        with pytest.raises(ValueError):
            decode_netflow(datagram, cache)
        assert len(cache) == 0

    def test_v5_receive_time_parameter_wins(self):
        msgs = decode_netflow(v5_datagram(), TemplateCache(), now=NOW + 500)
        assert msgs[0].time_received == NOW + 500
        # flow times still anchor to the exporter clock
        assert msgs[0].time_flow_start == NOW - 10

    def test_nf_delay_summary_observed(self):
        # "time between flow and processing": exporter header clock ->
        # wall clock, weighted per record (2 records in the v5 datagram)
        bus, producer, server = self.make()
        dgram = bytearray(v5_datagram())
        struct.pack_into(">I", dgram, 8, int(time.time()) - 3)  # unix_secs
        assert server.handle_netflow(bytes(dgram)) == 2
        assert server.m_nf_delay._count == 2
        # observations carry the router label (empty for an unknown
        # source), like every other per-exporter metric on this server
        p50 = server.m_nf_delay.quantile(0.5, router="")
        assert 2.0 <= p50 <= 5.0
        rendered = server.m_nf_delay.render()
        assert "flow_process_nf_delay_summary_seconds{quantile=" in rendered

    def test_handle_netflow_stamps_receive_time(self):
        # the server stamps wall-clock receive time (reference collector
        # behavior); a skewed exporter header clock (NOW, ~2023) must not
        # leak into time_received and shift window assignment
        from flow_pipeline_tpu.transport import Consumer

        bus, producer, server = self.make()
        before = int(time.time())
        assert server.handle_netflow(v5_datagram()) == 2
        batch = Consumer(bus, "flows", fixedlen=True).poll()
        received = batch.columns["time_received"]
        assert (received >= before).all()
        assert (received <= int(time.time()) + 1).all()

    def test_udp_end_to_end(self):
        bus, producer, server = self.make()
        server.start()
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.sendto(v5_datagram(), ("127.0.0.1", server.ports["netflow"]))
            s.sendto(sflow_datagram(), ("127.0.0.1", server.ports["sflow"]))
            deadline = time.time() + 5
            while producer.produced < 3 and time.time() < deadline:
                time.sleep(0.02)
        finally:
            server.stop()
        assert producer.produced == 3
        # the produced frames decode back to flows on the bus
        from flow_pipeline_tpu.transport import Consumer

        batch = Consumer(bus, fixedlen=True).poll()
        assert len(batch) == 3
