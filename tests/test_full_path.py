"""Full-stack slice: UDP datagrams -> collector -> bus -> worker (all
model families) -> SQLite, in one process. This is the reference's whole
compose demo (collect topology) as a test: L1 collection through L5
storage with exact totals checked at the end."""

import socket
import struct
import sys
import time

from flow_pipeline_tpu.collector import CollectorConfig, CollectorServer
from flow_pipeline_tpu.engine import StreamWorker, WorkerConfig
from flow_pipeline_tpu.models import (
    DDoSConfig,
    DDoSDetector,
    HeavyHitterConfig,
    WindowAggConfig,
    WindowAggregator,
)
from flow_pipeline_tpu.engine import WindowedHeavyHitter
from flow_pipeline_tpu.sink import SQLiteSink
from flow_pipeline_tpu.transport import Consumer, InProcessBus, Producer

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_collector import sflow_datagram, v5_datagram  # noqa: E402


def test_udp_to_sqlite_exact_totals():
    from flow_pipeline_tpu.obs import MetricsRegistry

    bus = InProcessBus()
    bus.create_topic("flows", 2)
    server = CollectorServer(
        Producer(bus, fixedlen=True),
        CollectorConfig(netflow_addr=("127.0.0.1", 0),
                        sflow_addr=("127.0.0.1", 0)),
        registry=MetricsRegistry(),  # isolated: exact-value asserts below
    ).start()
    sink = SQLiteSink(":memory:")
    worker = StreamWorker(
        Consumer(bus, fixedlen=True),
        {
            "flows_5m": WindowAggregator(WindowAggConfig(batch_size=512)),
            "top_talkers": WindowedHeavyHitter(
                HeavyHitterConfig(batch_size=512, width=1 << 12,
                                  capacity=64), k=10),
            "top_src_ports": WindowedHeavyHitter(
                HeavyHitterConfig(key_cols=("src_port",), batch_size=512,
                                  width=1 << 12, capacity=64), k=10),
            "ddos_alerts": DDoSDetector(DDoSConfig(batch_size=512)),
        },
        [sink],
        WorkerConfig(poll_max=512),
    )
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        n_datagrams = 50
        for i in range(n_datagrams):
            # vary the sequence number so datagrams are distinct
            d = bytearray(v5_datagram(n=2))  # 2 flows, 1000+1001 bytes
            struct.pack_into(">I", d, 16, i)
            s.sendto(bytes(d), ("127.0.0.1", server.ports["netflow"]))
            s.sendto(sflow_datagram(), ("127.0.0.1", server.ports["sflow"]))
        expected_flows = n_datagrams * 3  # 2 netflow + 1 sflow each round

        deadline = time.time() + 60
        while worker.flows_seen < expected_flows:
            assert time.time() < deadline, (
                f"only {worker.flows_seen}/{expected_flows} reached the worker"
            )
            if not worker.run_once():
                time.sleep(0.05)
        worker.finalize()
    finally:
        server.stop()

    # exact totals end to end: v5 rows carry 1000+1001 bytes per datagram,
    # the sFlow sample 1500
    total_bytes, total_count = sink.query(
        "SELECT SUM(bytes), SUM(count) FROM flows_5m"
    )[0]
    assert total_count == expected_flows
    assert total_bytes == n_datagrams * (1000 + 1001 + 1500)
    # the ranked tables flushed at finalize
    (n_talkers,) = sink.query("SELECT COUNT(*) FROM top_talkers")[0]
    assert n_talkers > 0
    rows = sink.query(
        "SELECT rank, src_port, bytes FROM top_src_ports ORDER BY rank LIMIT 1"
    )
    assert rows and rows[0][0] == 0 and rows[0][2] > 0
    # collector metric surface saw the datagrams
    assert server.m_udp_pkts.value() == n_datagrams * 2
    assert worker.consumer.lag() == 0  # offsets fully committed
